"""Error enclosures for approximately-served answers.

Every approximate answer the serving tier emits carries a bound the
caller can hold it to; the accuracy harness (scripts/sketch_harness.py)
asserts the exact-raw answer lies INSIDE the bound, so the math here
leans conservative:

- **Moment sketches**: Cantelli's one-sided inequality. For ANY
  distribution with mean m and variance s^2, the q-quantile satisfies

      m - s * sqrt((1-q)/q)  <=  Q(q)  <=  m + s * sqrt(q/(1-q)),

  clamped to the sketch's exact [min, max]. This holds for arbitrary
  data (no smoothness assumption — the maxent ESTIMATE may be sharp
  or sloppy, the bound stands regardless). When the log-domain
  moments are valid both domains' enclosures hold, so they intersect.

- **t-digests**: the neighbor-centroid enclosure from the accuracy
  analysis (arXiv:1902.04023-style): the q-quantile's rank falls in a
  known centroid; its value is enclosed by the NEIGHBOR centroid
  means (a centroid's members, under the digest's ordering invariant,
  do not stray past the adjacent means), widened by any caller rank
  slack (stale/dirty weight under degraded serving) and clamped to
  [min, max] when the caller knows them (moment records do).

- **HLL**: the classic 1.04/sqrt(m) standard error at 3 sigma.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.sketch.moment import MomentSketch, quantile_estimate


class QuantileBound:
    """(estimate, lo, hi) triple; ``hi - lo`` is the enclosure width
    and ``error`` the reported half-width around the estimate."""

    __slots__ = ("est", "lo", "hi")

    def __init__(self, est: float, lo: float, hi: float) -> None:
        self.est = float(est)
        self.lo = float(min(lo, est))
        self.hi = float(max(hi, est))

    @property
    def error(self) -> float:
        return max(self.hi - self.est, self.est - self.lo)

    def widen(self, other: "QuantileBound") -> None:
        self.lo = min(self.lo, other.lo)
        self.hi = max(self.hi, other.hi)


def _cantelli(count: float, mean: float, var: float, vmin: float,
              vmax: float, q: float, rank_slack: float = 0.0,
              ) -> tuple[float, float]:
    """Guaranteed [lo, hi] for the q-quantile from two moments.
    ``rank_slack`` widens the target rank both ways (degraded serving
    over stale windows: the quantile's true rank within the sketched
    data is only known to +-slack)."""
    s = float(np.sqrt(max(var, 0.0)))
    ql = min(max(q - rank_slack, 0.0), 1.0)
    qh = min(max(q + rank_slack, 0.0), 1.0)
    if qh >= 1.0:
        hi = vmax
    else:
        hi = mean + s * float(np.sqrt(qh / (1.0 - qh)))
    if ql <= 0.0:
        lo = vmin
    else:
        lo = mean - s * float(np.sqrt((1.0 - ql) / ql))
    return max(lo, vmin), min(hi, vmax)


def moment_quantile_bound(sk: MomentSketch, q: float,
                          rank_slack: float = 0.0) -> QuantileBound:
    """Estimate + guaranteed enclosure for one quantile of a moment
    sketch. Linear-domain Cantelli always applies; the log-domain one
    (exp of the enclosure of ln x's quantile — quantiles commute with
    monotone maps) intersects in when valid."""
    est = float(quantile_estimate(sk, np.array([q]))[0])
    if sk.count <= 0 or not np.isfinite(est):
        return QuantileBound(np.nan, np.nan, np.nan)
    lo, hi = _cantelli(sk.count, sk.mean, sk.var, sk.vmin, sk.vmax, q,
                       rank_slack)
    ls = sk.log_stats()
    if ls is not None and sk.vmin > 0:
        lm, lv = ls
        llo, lhi = _cantelli(sk.count, lm, lv, sk.log_min, sk.log_max,
                             q, rank_slack)
        # Both enclosures are sound -> the intersection is.
        lo = max(lo, float(np.exp(llo)))
        hi = min(hi, float(np.exp(lhi)))
    # Small-count discreteness guard: with n values the empirical
    # quantile sits ON a sample; the enclosure already contains every
    # sample in rank range, nothing further needed — but numerical
    # round-off in the power sums can nip the enclosure past a sample
    # at tiny n, so pad by one part in 1e9 of the span.
    pad = (sk.vmax - sk.vmin) * 1e-9
    return QuantileBound(min(max(est, lo), hi), lo - pad, hi + pad)


def tdigest_quantile_bound(means: np.ndarray, weights: np.ndarray,
                           q: float, vmin: float | None = None,
                           vmax: float | None = None,
                           rank_slack: float = 0.0,
                           cdf_uncertainty_w: float = 0.0,
                           ) -> QuantileBound:
    """Estimate + enclosure for one quantile of a POOLED digest —
    the concatenation of several window digests plus exact raw
    points (the planner's bucket/range merges).

    Two rank-uncertainty sources add up:

    - ``rank_slack``: the caller's own slack (stale-weight fraction
      under degraded serving).
    - ``cdf_uncertainty_w``: summed WEIGHT uncertainty of the merged
      CDF — each contributing digest's interpolated CDF is off by at
      most its heaviest centroid's weight (the within-centroid
      distribution is unknown around any probe point; exact raw
      points contribute zero). Concatenating digests sums these. The
      old neighbor-centroid-only argument is sound within ONE
      digest's ordering invariant but NOT across a concatenation —
      window A's centroid members may stray past window B's adjacent
      mean — so the enclosure takes the slacked ranks [q - u, q + u]
      (u = total uncertainty fraction) and widens to the OUTER
      neighbor means beyond them, clamped to the exact [vmin, vmax]
      from the moment records."""
    keep = weights > 0
    m = np.asarray(means, np.float64)[keep]
    w = np.asarray(weights, np.float64)[keep]
    if len(m) == 0:
        return QuantileBound(np.nan, np.nan, np.nan)
    order = np.argsort(m, kind="stable")
    m, w = m[order], w[order]
    total = float(w.sum())
    cum = np.cumsum(w)
    centers = (cum - w / 2) / max(total, 1e-30)
    q = min(max(q, 0.0), 1.0)
    est = float(np.interp(q, centers, m))
    slack = rank_slack + cdf_uncertainty_w / max(total, 1e-30)
    rlo = min(max(q - slack, 0.0), 1.0) * total
    rhi = min(max(q + slack, 0.0), 1.0) * total
    # Centroid index whose span [cum - w, cum] contains each rank
    # end, then one more neighbor outward (within-centroid spread).
    ilo = int(np.searchsorted(cum, rlo, side="left"))
    ihi = int(np.searchsorted(cum, rhi, side="left"))
    ilo = min(ilo, len(m) - 1)
    ihi = min(ihi, len(m) - 1)
    lo = m[ilo - 1] if ilo > 0 else (vmin if vmin is not None else m[0])
    hi = (m[ihi + 1] if ihi + 1 < len(m)
          else (vmax if vmax is not None else m[-1]))
    if vmin is not None:
        lo = max(float(lo), float(vmin))
        est = max(est, float(vmin))
    if vmax is not None:
        hi = min(float(hi), float(vmax))
        est = min(est, float(vmax))
    return QuantileBound(est, float(lo), float(hi))


def moment_bounds_batch(cols, q: float,
                        rank_slack: np.ndarray | float = 0.0,
                        ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized (estimate, lo, hi) for one quantile over a
    MomentColumns block — the per-(series, bucket) serving path.
    Estimates are Cornish-Fisher (log domain where valid); the
    enclosure is the same Cantelli math as moment_quantile_bound,
    elementwise: linear-domain always, intersected with the
    log-domain enclosure on rows whose log section is valid. A
    dashboard's hundreds of thousands of buckets cost a handful of
    numpy passes instead of a maxent solve each."""
    from opentsdb_tpu.sketch.moment import (central_moments,
                                            cf_quantile)
    n = np.maximum(cols.count, 1.0)
    slack = np.broadcast_to(np.asarray(rank_slack, np.float64),
                            cols.count.shape)
    ql = np.clip(q - slack, 0.0, 1.0)
    qh = np.clip(q + slack, 0.0, 1.0)
    m1, var, m3, m4 = central_moments(n, cols.moments)
    s = np.sqrt(var)
    with np.errstate(divide="ignore", invalid="ignore"):
        hi = np.where(qh >= 1.0, cols.vmax,
                      m1 + s * np.sqrt(qh / np.maximum(1 - qh,
                                                       1e-300)))
        lo = np.where(ql <= 0.0, cols.vmin,
                      m1 - s * np.sqrt((1 - ql) / np.maximum(ql,
                                                             1e-300)))
    lo = np.maximum(lo, cols.vmin)
    hi = np.minimum(hi, cols.vmax)
    log_rows = cols.log_ok & (cols.vmin > 0)
    est = cf_quantile(n, m1, var, m3, m4, cols.vmin, cols.vmax, q)
    if log_rows.any():
        lmin = np.where(log_rows, np.log(np.maximum(cols.vmin,
                                                    1e-300)), 0.0)
        lmax = np.where(log_rows, np.log(np.maximum(cols.vmax,
                                                    1e-300)), 1.0)
        lm1, lvar, lm3, lm4 = central_moments(n, cols.logs)
        ls = np.sqrt(lvar)
        with np.errstate(divide="ignore", invalid="ignore",
                         over="ignore"):
            lhi = np.where(qh >= 1.0, lmax,
                           lm1 + ls * np.sqrt(
                               qh / np.maximum(1 - qh, 1e-300)))
            llo = np.where(ql <= 0.0, lmin,
                           lm1 - ls * np.sqrt(
                               (1 - ql) / np.maximum(ql, 1e-300)))
            # Both enclosures are sound -> intersect on log rows.
            lo = np.where(log_rows,
                          np.maximum(lo, np.exp(np.maximum(llo,
                                                           -700))),
                          lo)
            hi = np.where(log_rows,
                          np.minimum(hi, np.exp(np.minimum(lhi,
                                                           700))),
                          hi)
            lest = np.exp(np.clip(
                cf_quantile(n, lm1, lvar, lm3, lm4, lmin, lmax, q),
                -700, 700))
        wide = log_rows & ((lmax - lmin) > 2.0)
        est = np.where(wide, np.clip(lest, cols.vmin, cols.vmax),
                       est)
    pad = (cols.vmax - cols.vmin) * 1e-9
    est = np.clip(est, lo, hi)
    return est, lo - pad, hi + pad


def tdigest_bounds_rows(means: np.ndarray, weights: np.ndarray,
                        q: float, vmin: np.ndarray, vmax: np.ndarray,
                        rank_slack: np.ndarray | float = 0.0,
                        cdf_uncertainty_w: np.ndarray | float = 0.0,
                        ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Row-wise tdigest_quantile_bound over padded [N, K] centroid
    arrays (pad slots carry weight 0), one numpy pass for every
    bucket of a series. Rows must already be value-sorted with pads
    at the END (digest cells store centroids sorted; the serving
    path pads on the right)."""
    w = np.asarray(weights, np.float64)
    m = np.asarray(means, np.float64)
    N, K = m.shape
    total = w.sum(axis=1)
    cum = np.cumsum(w, axis=1)
    centers = (cum - w / 2) / np.maximum(total, 1e-30)[:, None]
    # Pad slots: push centers past 1 so searches never land on them.
    centers = np.where(w > 0, centers, 2.0)
    live = (w > 0).sum(axis=1)
    q = min(max(q, 0.0), 1.0)
    # Row-wise interp at q: searchsorted per row on monotone centers.
    idx = np.clip(_row_searchsorted(centers, np.full(N, q)), 0,
                  np.maximum(live - 1, 0))
    idx0 = np.maximum(idx - 1, 0)
    c0 = np.take_along_axis(centers, idx0[:, None], 1)[:, 0]
    c1 = np.take_along_axis(centers, idx[:, None], 1)[:, 0]
    m0 = np.take_along_axis(m, idx0[:, None], 1)[:, 0]
    m1_ = np.take_along_axis(m, idx[:, None], 1)[:, 0]
    frac = np.where(c1 > c0, (q - c0) / np.maximum(c1 - c0, 1e-30),
                    0.0)
    est = m0 + np.clip(frac, 0.0, 1.0) * (m1_ - m0)
    first = m[:, 0]
    last = np.take_along_axis(m, np.maximum(live - 1, 0)[:, None],
                              1)[:, 0]
    est = np.where(q <= centers[:, 0], first, est)
    lastc = np.take_along_axis(centers,
                               np.maximum(live - 1, 0)[:, None],
                               1)[:, 0]
    est = np.where(q >= lastc, last, est)
    slack = (np.broadcast_to(np.asarray(rank_slack, np.float64),
                             total.shape)
             + np.asarray(cdf_uncertainty_w, np.float64)
             / np.maximum(total, 1e-30))
    rlo = np.clip(q - slack, 0.0, 1.0) * total
    rhi = np.clip(q + slack, 0.0, 1.0) * total
    cum_masked = np.where(w > 0, cum, np.inf)
    ilo = np.minimum(_row_searchsorted(cum_masked, rlo),
                     np.maximum(live - 1, 0))
    ihi = np.minimum(_row_searchsorted(cum_masked, rhi),
                     np.maximum(live - 1, 0))
    lo = np.where(ilo > 0,
                  np.take_along_axis(m, np.maximum(ilo - 1,
                                                   0)[:, None],
                                     1)[:, 0],
                  vmin)
    hi = np.where(ihi + 1 < live,
                  np.take_along_axis(m, np.minimum(ihi + 1,
                                                   K - 1)[:, None],
                                     1)[:, 0],
                  vmax)
    lo = np.maximum(lo, vmin)
    hi = np.minimum(hi, vmax)
    est = np.clip(est, vmin, vmax)
    est = np.clip(est, lo, hi)
    return est, lo, hi


def _row_searchsorted(a: np.ndarray, v: np.ndarray) -> np.ndarray:
    """searchsorted(a[i], v[i], side='left') per row, vectorized:
    count of entries strictly below v (rows are monotone)."""
    return (a < v[:, None]).sum(axis=1)


def hll_error(p: int, estimate: float, nsigma: float = 3.0) -> float:
    """Absolute +-bound on an HLL cardinality estimate with 2^p
    registers (relative standard error 1.04/sqrt(m), at nsigma)."""
    m = 1 << int(p)
    return float(estimate) * nsigma * 1.04 / float(np.sqrt(m))


def dirty_rank_slack(clean_weight: float, stale_weight: float,
                     assumed_growth: float = 1.0) -> float:
    """Rank slack for serving STALE windows under degraded
    (rollup-only) mode: a dirty window's record reflects its last
    fold; up to ``assumed_growth`` * its recorded weight may have
    arrived since (plus the recorded values themselves may have been
    superseded). Every unseen/changed point can shift the target rank
    by at most 1, so slack = changeable / total, capped at 0.5
    (beyond that the enclosure is the full [min, max] anyway)."""
    total = clean_weight + stale_weight
    if total <= 0:
        return 0.5
    changeable = stale_weight * (1.0 + assumed_growth)
    return float(min(changeable / (total + stale_weight * assumed_growth),
                     0.5))
