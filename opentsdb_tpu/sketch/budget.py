"""Storyboard-style byte-budget allocation for sketch columns.

The rollup tier used to apply ONE uniform rule — t-digest + HLL
columns at every resolution >= ``rollup_sketch_min_res`` — which
spends the same bytes per record whether a resolution serves one
dashboard a day or every percentile panel in the fleet. Storyboard
(arXiv:2002.03063) frames this properly: given a fixed summary-byte
budget and a query workload over precomputed windows, choose each
window class's summary kind/size to minimize expected error.

``allocate`` is that optimizer, reduced to the tier's shape: per
RESOLUTION (the tier's window classes), pick a rung on the upgrade
ladder none -> moment -> moment+digest(k ascending), by greedy
marginal utility (workload-weighted error reduction per byte) — the
classic knapsack heuristic, optimal here because rung error gains are
diminishing. Record-count estimates are quantized to powers of 4 so
day-to-day data drift doesn't flap the chosen layout (a layout change
rebuilds the tier — intended when the operator re-budgets, not every
morning).

Inputs come from two places: the TIER derives record estimates from
its raw store at open (deterministic given the same data order of
magnitude), and ``tsdb sketch-plan`` additionally folds in a measured
workload profile from the PR-6 slow-query/trace ring.
"""

from __future__ import annotations

from typing import Iterable, NamedTuple

from opentsdb_tpu.sketch.moment import MomentSketch

# Upgrade ladder: (digest_k, moment_k, rank-error proxy). Error
# proxies are the documented accuracy scales of each summary — the
# allocator only needs their ORDER and rough ratios: a k-centroid
# t-digest's mid-quantile rank error ~ 1/k (arXiv:1902.04023), a
# k=8 moment sketch's maxent estimate lands near percent-level on
# smooth data but its GUARANTEED Cantelli enclosure is wide, scored
# here between "nothing" and "small digest".
LADDER: tuple[tuple[int, int, float], ...] = (
    (0, 0, 0.50),        # no sketch columns: pNN at this res is raw
    (0, 8, 0.08),        # moment-only rung (tiny, exactly mergeable)
    (32, 8, 0.031),      # + small digest
    (64, 8, 0.016),      # + default digest
    (128, 8, 0.008),     # + wide digest
)


class ResAllocation(NamedTuple):
    res: int
    digest_k: int
    moment_k: int
    hll_p: int
    records: int          # estimated records at this resolution
    bytes_per_record: int
    total_bytes: int
    err_proxy: float


def record_bytes(digest_k: int, moment_k: int, hll_p: int) -> int:
    """Encoded sketch-cell bytes per record for a rung (summary.py
    sketch_encode: 4B header + 8B/centroid + HLL registers + moment
    section). HLL registers ride the DIGEST rungs only: a moment-only
    rung stays ~200 B (its whole point), and ranged /distinct serves
    presence-only there while distinct-VALUES estimates need a digest
    rung anyway."""
    if not digest_k and not moment_k:
        return 0
    n = 4 + 8 * digest_k + ((1 << hll_p) if (hll_p and digest_k)
                            else 0)
    if moment_k:
        n += 2 + MomentSketch.encoded_size(moment_k)
    return n


def quantize_records(n: int) -> int:
    """Round a record-count estimate up to a power of 4 (min 256):
    allocation inputs must be stable under ordinary data growth."""
    q = 256
    while q < n:
        q *= 4
    return q


def allocate(budget_bytes: int, records: dict[int, int],
             workload: dict[int, float] | None = None, *,
             hll_p: int = 8,
             ladder: Iterable[tuple[int, int, float]] = LADDER,
             ) -> dict[int, ResAllocation]:
    """Spend ``budget_bytes`` across resolutions.

    ``records``: estimated record count per resolution (quantized
    internally). ``workload``: relative query weight per resolution
    (defaults to uniform — every resolution equally likely to serve).
    Deterministic: ties break toward the finer resolution.
    """
    ladder = tuple(ladder)
    res_list = sorted(records)
    if not res_list:
        return {}
    recs = {r: quantize_records(int(records[r])) for r in res_list}
    if workload:
        wsum = sum(max(float(workload.get(r, 0.0)), 0.0)
                   for r in res_list) or 1.0
        weights = {r: max(float(workload.get(r, 0.0)), 0.0) / wsum
                   for r in res_list}
        # A resolution nobody queries still deserves epsilon weight:
        # workloads shift, and a zero weight would starve it forever.
        weights = {r: max(w, 0.01) for r, w in weights.items()}
    else:
        weights = {r: 1.0 / len(res_list) for r in res_list}

    level = {r: 0 for r in res_list}
    spent = 0

    def rung_cost(r: int, lvl: int) -> int:
        dk, mk, _ = ladder[lvl]
        return record_bytes(dk, mk, hll_p if (dk or mk) else 0) * recs[r]

    while True:
        best = None
        for r in res_list:
            lvl = level[r]
            if lvl + 1 >= len(ladder):
                continue
            delta = rung_cost(r, lvl + 1) - rung_cost(r, lvl)
            if spent + delta > budget_bytes:
                continue
            gain = weights[r] * (ladder[lvl][2] - ladder[lvl + 1][2])
            util = gain / max(delta, 1)
            if best is None or util > best[0] or (
                    util == best[0] and r < best[1]):
                best = (util, r, delta)
        if best is None:
            break
        _, r, delta = best
        level[r] += 1
        spent += delta

    out = {}
    for r in res_list:
        dk, mk, err = ladder[level[r]]
        hp = hll_p if dk else 0   # HLL rides the digest rungs only
        bpr = record_bytes(dk, mk, hp)
        out[r] = ResAllocation(r, dk, mk, hp, recs[r], bpr,
                               bpr * recs[r], err)
    return out


def workload_from_ring(records: list[dict],
                       resolutions: Iterable[int]) -> dict[int, float]:
    """Derive per-resolution query weights from trace-ring records
    (the PR-6 slow-query/ambient-sample ring at /api/traces): each
    record's downsample interval maps to the coarsest resolution that
    nests into it — the resolution a sketch-served percentile of that
    query would read."""
    res = sorted(int(r) for r in resolutions)
    weights = {r: 0.0 for r in res}
    for rec in records:
        iv = _interval_of(rec)
        if iv is None:
            continue
        best = None
        for r in res:
            if r <= iv and iv % r == 0:
                best = r
        if best is not None:
            weights[best] += 1.0
    return weights


def _interval_of(rec: dict) -> int | None:
    """Downsample interval of one trace-ring record (from the 'm'
    query expression it stores)."""
    m = rec.get("m") or rec.get("query")
    if not isinstance(m, str):
        return None
    try:
        from opentsdb_tpu.query.grammar import parse_m
        parsed = parse_m(m)
    except Exception:
        return None
    return parsed.downsample[0] if parsed.downsample else None


def render_plan(allocs: dict[int, ResAllocation],
                budget_bytes: int) -> str:
    """Human-readable allocation table (the ``tsdb sketch-plan``
    output)."""
    from opentsdb_tpu.rollup.tier import res_label
    lines = [f"sketch byte budget: {budget_bytes:,} B",
             f"{'res':>6} {'records~':>10} {'digest_k':>8} "
             f"{'moment_k':>8} {'hll_p':>5} {'B/rec':>6} "
             f"{'total':>12} {'err~':>6}"]
    total = 0
    for r in sorted(allocs):
        a = allocs[r]
        total += a.total_bytes
        lines.append(
            f"{res_label(r):>6} {a.records:>10,} {a.digest_k:>8} "
            f"{a.moment_k:>8} {a.hll_p:>5} {a.bytes_per_record:>6} "
            f"{a.total_bytes:>12,} {a.err_proxy:>6.3f}")
    lines.append(f"planned total: {total:,} B "
                 f"({'within' if total <= budget_bytes else 'OVER'} "
                 f"budget)")
    return "\n".join(lines)
