"""Accuracy-budgeted approximate serving (the error-contract tier).

The rollup tier has carried sketch columns since PR 2, but every
percentile-downsample query still paid a raw scan: there was no
*contract* under which an approximate answer could be served. This
package adds one:

- ``moment``  — moment-sketch columns (arXiv:1803.01969): tiny
  (~100-200 B) records of count/min/max/power-moments (+ log-moments),
  merged by pure addition, with a maximum-entropy quantile solver on
  the read side.
- ``bounds``  — guaranteed error enclosures: Cantelli/Chebyshev-style
  quantile bounds from moments, neighbor-centroid enclosures from
  t-digest weights (arXiv:1902.04023-style), HLL standard error.
- ``serving`` — the planner step that serves ``dsagg pNN`` queries
  from merged rollup sketch columns when the caller opts in
  (``approx=1`` / ``max_error=X``) or the admission ladder degrades,
  attaching a per-result reported bound and falling back to the exact
  raw path whenever the bound exceeds the caller's budget.
- ``budget``  — a Storyboard-style (arXiv:2002.03063) allocator that
  spends ``Config.sketch_byte_budget`` across resolutions (kind +
  size per resolution) instead of the uniform
  ``rollup_sketch_min_res`` cutoff.

Contract: an approximate answer always DECLARES itself —
``"approx": {"kind": ..., "error": ...}`` in ``/q`` JSON and an
``X-Tsd-Approx`` header — and the reported bound must contain the
exact-raw answer (scripts/sketch_harness.py asserts exactly that).
"""
