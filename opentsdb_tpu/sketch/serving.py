"""Error-contracted approximate serving of percentile downsamples.

The planner step behind ``dsagg pNN`` approximate answers: merge the
rollup tier's per-window sketch columns (t-digest or moment — the
per-resolution allocation decides which exist) into per-(series,
bucket) quantile estimates WITH guaranteed enclosures
(sketch/bounds.py), run a bounds-propagating group stage (monotone
aggregators only — applying a monotone aggregator to the lo/hi rails
yields a sound group enclosure), and report one error figure per
result. The caller opts in (``approx=1`` / ``max_error=X``) or the
admission ladder's rollup-only step implies it; when the reported
bound exceeds the caller's budget the query falls back to the exact
raw path (or, under rollup-only, sheds with 503 — there IS no raw
path at that ladder step).

Two serving modes mirror the rollup planner's:

- **opt-in** (normal load): edge windows and dirty windows are
  raw-stitched — their contributions are EXACT (zero-width bounds),
  so the only error source is sketch compression on clean windows and
  the reported enclosure is unconditional.
- **rollup-only** (ladder degradation): zero raw work. Dirty windows
  serve their STALE sketch records with the rank bound widened by the
  stale weight fraction (bounds.dirty_rank_slack) and the result
  declares ``stale_windows``/``omitted_edges`` — degraded answers
  are bounded relative to the folded data and say so, never silently
  partial.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import numpy as np

from opentsdb_tpu.core import codec
from opentsdb_tpu.obs import trace as _trace
from opentsdb_tpu.obs.registry import METRICS as _metrics
from opentsdb_tpu.query.aggregators import Aggregators
from opentsdb_tpu.rollup import summary as rsummary
from opentsdb_tpu.sketch import bounds as _bounds
from opentsdb_tpu.sketch.moment import MomentSketch

_M_HIT = _metrics.counter("sketch.serve.hit")
_M_FALLBACK = _metrics.counter("sketch.serve.fallback")
# Histogram of reported RELATIVE error bounds (percent units so the
# p50/p95/p99 expansion reads naturally in /stats).
_M_ERR = _metrics.timer("sketch.error.reported")


class ApproxSpec(NamedTuple):
    """What the caller asked for. ``max_error`` is a RELATIVE
    half-width budget (reported_error <= max_error * |estimate|);
    None = serve at any bound (but still report it)."""
    enabled: bool = False
    max_error: float | None = None


class ApproxInfo(NamedTuple):
    kind: str             # "tdigest" | "moment"
    error: float          # max absolute half-width across buckets
    rel_error: float      # max relative half-width
    res: int
    stale_windows: int = 0
    omitted_edges: int = 0
    # Dirty windows in range that NO fold has ever recorded (a fresh
    # hour under rollup-only): their buckets are absent from the
    # answer and the contract requires saying so, not just bounding
    # what IS returned.
    missing_windows: int = 0

    def as_json(self) -> dict:
        from opentsdb_tpu.rollup.tier import res_label
        out = {"kind": self.kind, "error": self.error,
               "rel_error": self.rel_error,
               "res": res_label(self.res)}
        if self.stale_windows:
            out["stale_windows"] = self.stale_windows
        if self.omitted_edges:
            out["omitted_edges"] = self.omitted_edges
        if self.missing_windows:
            out["missing_windows"] = self.missing_windows
        return out


# Group aggregators that are monotone in every argument — applying
# them to the lo/hi rails preserves enclosure soundness. ("dev" is
# not; it falls back to the exact path.)
_MONOTONE_MOMENTS = {"sum", "min", "max", "avg", "count",
                     "zimsum", "mimmin", "mimmax"}


class _Bucket:
    __slots__ = ("means", "weights", "vmin", "vmax", "clean_w",
                 "stale_w", "mblobs", "raw", "maxw")

    def __init__(self) -> None:
        self.means: list[np.ndarray] = []
        self.weights: list[np.ndarray] = []
        # Summed heaviest-centroid weight of every contributing
        # digest: the pooled CDF's rank uncertainty (bounds.py
        # cdf_uncertainty_w). Exact raw points contribute zero.
        self.maxw = 0.0
        self.vmin = np.inf
        self.vmax = -np.inf
        self.clean_w = 0.0
        self.stale_w = 0.0
        self.mblobs: list[bytes] = []
        self.raw: list[np.ndarray] = []


def plan_percentile(executor, spec, start: int, end: int, *,
                    rollup_only: bool = False):
    """Serve ``spec`` (percentile downsample aggregator) from sketch
    columns. Returns (results, res, ApproxInfo) or None (caller runs
    the exact path / sheds)."""
    tsdb = executor.tsdb
    tier = getattr(tsdb, "rollups", None)
    if tier is None or not tier.ready:
        _M_FALLBACK.inc()
        return None
    if spec.rate:
        _M_FALLBACK.inc()
        return None
    interval, dsagg = spec.downsample
    ds = Aggregators.get(dsagg)
    if ds.kind != "percentile":
        return None
    agg = Aggregators.get(spec.aggregator)
    if not (agg.kind == "percentile"
            or (agg.kind == "moment"
                and agg.name in _MONOTONE_MOMENTS)):
        _M_FALLBACK.inc()
        return None
    res = tier.sketch_res_for_interval(interval)
    if res is None:
        _M_FALLBACK.inc()
        return None
    digest_k, moment_k, _hp = tier.sketch_kinds(res)
    kind = "tdigest" if digest_k else "moment"

    q = float(ds.quantile)
    # Rail cache (the fragment-cache discipline for sketch serving):
    # a dashboard's repeat polls re-read the SAME clean window
    # records, and the record scan + cell decode + bound math is the
    # whole cost. A fully-window-covered range with no dirty windows
    # caches its per-series rails, keyed by the selector and
    # revalidated against the tier's fold/refresh stamps — any fold
    # (writer) or capture refresh (replica) invalidates. Dirty or
    # edge-stitched ranges bypass both ways (they ARE the live
    # tail).
    from opentsdb_tpu.query.executor import _filter_key
    from opentsdb_tpu.rollup.planner import window_split
    cache = getattr(executor, "_sketch_rail_cache", None)
    w_lo, w_hi, edges = window_split(start, end, res)
    hours = tier.dirty_hour_bases()
    range_clean = (w_hi >= w_lo and not edges and len(hours) == 0)
    if len(hours) and w_hi >= w_lo:
        dr = hours - hours % res
        range_clean = (not edges
                       and not ((dr >= w_lo) & (dr <= w_hi)).any())
    ckey = cval = None
    if cache is not None:
        try:
            exact, group_bys = executor._tag_filters(spec.tags)
        except Exception:
            exact = group_bys = None
        if exact is not None:
            ckey = (id(tier), spec.metric,
                    _filter_key(exact, group_bys), start, end, res,
                    interval, q, kind, rollup_only)
            cval = (tier.folds, getattr(tier, "refreshes", 0),
                    tier.records_written, tier.ready)
    spans = None
    stale_windows = 0
    missing_windows = 0
    if ckey is not None and range_clean:
        hit = cache.get(ckey)
        if hit is not None and hit[0] == cval:
            spans = hit[1]
    if spans is None:
        from opentsdb_tpu.rollup import planner as rplanner
        sel = rplanner._select_windows(executor, tier, spec.metric,
                                       spec.tags, start, end, res,
                                       want_sketches=True,
                                       rollup_only=rollup_only)
        if sel is None:
            _M_FALLBACK.inc()
            return None
        records, raw_parts, dirty_set = sel
        with _trace.span("sketch.assemble", res=res, kind=kind):
            series = _assemble(records, raw_parts, dirty_set,
                               interval, kind, moment_k, rollup_only)
        if series is None:
            _M_FALLBACK.inc()
            return None
        per_series, stale_windows, seen_dirty = series
        if rollup_only:
            missing_windows = len(dirty_set - seen_dirty)
        if not per_series:
            # Nothing in range: the exact path answers (it knows how
            # to produce the canonical empty result / raise).
            _M_FALLBACK.inc()
            return None
        # Per-(series, bucket) estimates + enclosures, one batched
        # numpy pass per series (a dashboard is hundreds of
        # thousands of buckets; per-bucket python bound math was the
        # wall).
        spans = {}
        try:
            for skey, buckets in per_series.items():
                rails = _series_rails(buckets, q, kind,
                                      moment_k or MomentSketch().k)
                if rails is None:
                    _M_FALLBACK.inc()
                    return None  # undecodable cell: exact path
                spans[skey] = rails
        except ValueError:
            _M_FALLBACK.inc()
            return None
        if (ckey is not None and range_clean and not raw_parts
                and stale_windows == 0):
            cost = sum(len(r[0]) for r in spans.values())
            cache.put(ckey, (cval, spans), cost=max(cost, 1))

    results, err_abs, err_rel = _group_stage(executor, spec, spans)
    info = ApproxInfo(kind, err_abs, err_rel, res,
                      stale_windows=stale_windows,
                      omitted_edges=len(edges) if rollup_only else 0,
                      missing_windows=missing_windows)
    if os.environ.get("TSDB_SKETCH_BUG") == "loose-bound":
        # Test-only sabotage (scripts/sketch_harness.py --bug): report
        # a bound 100x tighter than computed — the exact violation the
        # accuracy harness's gate must catch.
        info = info._replace(error=info.error / 100.0,
                             rel_error=info.rel_error / 100.0)
    _M_HIT.inc()
    _M_ERR.observe(err_rel * 100.0)
    tier.note_hit(res)
    return results, res, info


def _assemble(records, raw_parts, dirty_set, interval, kind,
              moment_k, rollup_only):
    """-> ({series_key: {bucket_ts: _Bucket}}, stale_windows) or None
    when a clean window lacks the sketch column this tier claims to
    store (foreign/mixed layout: the exact path is the safe answer)."""
    per_series: dict[bytes, dict[int, _Bucket]] = {}
    stale_windows = 0
    seen_dirty: set[int] = set()

    def bucket(skey, bt) -> _Bucket:
        row = per_series.get(skey)
        if row is None:
            row = per_series[skey] = {}
        b = row.get(bt)
        if b is None:
            b = row[bt] = _Bucket()
        return b

    for skey, (bases, recs, sketches) in records.items():
        # Window -> (count, min, max) from the moment records: the
        # exact extremes that clamp the sketch enclosures.
        stats = {int(b): (float(r["count"]), float(r["min"]),
                          float(r["max"]))
                 for b, r in zip(bases, recs)}
        sk_bases = set()
        for wb, blob in sketches:
            wb = int(wb)
            sk_bases.add(wb)
            dirty = wb in dirty_set
            if dirty and not rollup_only:
                continue  # raw stitch covers it exactly
            try:
                means, weights, _regs, mblob = \
                    rsummary.sketch_decode_full(blob)
            except Exception:
                return None
            cnt, vmin, vmax = stats.get(wb, (0.0, np.inf, -np.inf))
            w = float(np.sum(weights)) if len(weights) else cnt
            if w <= 0 and mblob is None:
                continue
            b = bucket(skey, wb - wb % interval)
            if dirty:
                stale_windows += 1
                seen_dirty.add(wb)
                b.stale_w += max(w, cnt)
            else:
                b.clean_w += max(w, cnt)
            b.vmin = min(b.vmin, vmin)
            b.vmax = max(b.vmax, vmax)
            if kind == "tdigest":
                if len(means) == 0 and w > 0:
                    return None  # digest column missing at this res
                b.means.append(np.asarray(means, np.float64))
                b.weights.append(np.asarray(weights, np.float64))
                if len(weights):
                    b.maxw += float(np.max(weights))
            else:
                if mblob is None:
                    return None  # moment column missing
                b.mblobs.append(mblob)
        # A clean window with a record but NO sketch cell cannot be
        # served approximately; its points would silently vanish.
        for wb in stats:
            if wb not in sk_bases and wb not in dirty_set \
                    and stats[wb][0] > 0:
                return None
    for skey, (ts, vals) in raw_parts.items():
        if not len(ts):
            continue
        bts = ts - ts % interval
        cuts = np.concatenate(
            ([0], np.flatnonzero(np.diff(bts)) + 1, [len(ts)]))
        for a, z in zip(cuts[:-1], cuts[1:]):
            seg = np.asarray(vals[a:z], np.float64)
            b = bucket(skey, int(bts[a]))
            b.raw.append(seg)
            b.clean_w += len(seg)
            b.vmin = min(b.vmin, float(seg.min()))
            b.vmax = max(b.vmax, float(seg.max()))
    return per_series, stale_windows, seen_dirty


def _series_rails(buckets: dict, q: float, kind: str,
                  moment_k: int):
    """(bucket_ts[N], est[N], lo[N], hi[N]) for one series — the
    batched replacement for per-bucket bound math. t-digest buckets
    pack their (already-sorted) centroid arrays + unit-weight raw
    points into padded [N, K] rows and run one vectorized enclosure
    pass; moment buckets merge into MomentColumns (row additions)
    and run the elementwise Cantelli + Cornish-Fisher pass. Returns
    None on an empty/undecodable cell."""
    from opentsdb_tpu.sketch.moment import MomentColumns
    bts = sorted(buckets)
    N = len(bts)
    slack = np.zeros(N)
    vmin = np.empty(N)
    vmax = np.empty(N)
    for i, bt in enumerate(bts):
        b = buckets[bt]
        if b.stale_w > 0:
            slack[i] = _bounds.dirty_rank_slack(b.clean_w, b.stale_w)
        vmin[i] = b.vmin
        vmax[i] = b.vmax
    if kind == "tdigest":
        rows = []
        needs_sort = False
        K = 0
        for bt in bts:
            b = buckets[bt]
            parts = len(b.means) + len(b.raw)
            if parts == 0:
                return None
            # Digest centroids come value-sorted; raw stitches come
            # TIME-sorted — any raw part (or a multi-digest merge)
            # forces the row re-sort.
            needs_sort = needs_sort or parts > 1 or bool(b.raw)
            K = max(K, sum(len(x) for x in b.means)
                    + sum(len(x) for x in b.raw))
            rows.append(b)
        if K == 0:
            return None
        means2d = np.full((N, K), np.inf)
        w2d = np.zeros((N, K))
        unc = np.empty(N)
        for i, b in enumerate(rows):
            off = 0
            for mm, ww in zip(b.means, b.weights):
                means2d[i, off:off + len(mm)] = mm
                w2d[i, off:off + len(mm)] = ww
                off += len(mm)
            for seg in b.raw:
                # Raw points fold in as unit-weight centroids: exact
                # contributions, no compression step.
                means2d[i, off:off + len(seg)] = seg
                w2d[i, off:off + len(seg)] = 1.0
                off += len(seg)
            unc[i] = b.maxw
        if needs_sort:
            order = np.argsort(means2d, axis=1, kind="stable")
            means2d = np.take_along_axis(means2d, order, 1)
            w2d = np.take_along_axis(w2d, order, 1)
        est, lo, hi = _bounds.tdigest_bounds_rows(
            np.where(np.isfinite(means2d), means2d, 0.0), w2d, q,
            vmin, vmax, rank_slack=slack, cdf_uncertainty_w=unc)
        return np.asarray(bts, np.int64), est, lo, hi
    cols = MomentColumns(N, moment_k)
    for i, bt in enumerate(bts):
        b = buckets[bt]
        for blob in b.mblobs:
            cols.add_blob(i, blob)   # raises ValueError on foreign
        for seg in b.raw:
            cols.add_values(i, seg)
    if (cols.count <= 0).any():
        return None
    est, lo, hi = _bounds.moment_bounds_batch(cols, q, slack)
    return np.asarray(bts, np.int64), est, lo, hi


def _group_stage(executor, spec, spans):
    """Bounds-propagating group aggregation on the shared bucket grid.

    Mirrors the exact path's semantics — union grid of member bucket
    timestamps, linear interpolation inside each series' [first,
    last] for interpolating aggregators, none for the zimsum family —
    applied to the est/lo/hi rails separately. Monotone aggregators
    only (callers gate), so the rails stay a sound enclosure.
    Returns ([QueryResult], max_abs_err, max_rel_err)."""
    from opentsdb_tpu.query.executor import QueryResult, _Span

    tsdb = executor.tsdb
    group_by_keys = sorted(
        k for k, _ in executor._tag_filters(spec.tags)[1])
    groups: dict[tuple, list] = {}
    named_spans: dict[bytes, dict] = {}
    for skey in sorted(spans):
        tag_uids = codec.series_tag_uids(skey)
        named = {tsdb.tagk.get_name(k): tsdb.tagv.get_name(v)
                 for k, v in tag_uids.items()}
        named_spans[skey] = named
        gkey = tuple(tag_uids.get(k, b"") for k in group_by_keys)
        groups.setdefault(gkey, []).append(skey)

    agg = Aggregators.get(spec.aggregator)
    interp = executor._interp(spec)
    results = []
    max_abs = 0.0
    max_rel = 0.0
    for gkey in sorted(groups):
        skeys = groups[gkey]
        grid = np.unique(np.concatenate(
            [spans[s][0] for s in skeys]))
        rails = []  # per series (est, lo, hi) on grid, nan outside
        for s in skeys:
            bts, est, lo, hi = spans[s]
            rails.append(tuple(
                _on_grid(grid, bts, v, interp) for v in (est, lo, hi)))
        E = np.stack([r[0] for r in rails])    # [S, G]
        Lo = np.stack([r[1] for r in rails])
        Hi = np.stack([r[2] for r in rails])
        mask = (~np.isnan(E)).any(axis=0)
        with np.errstate(all="ignore"):
            est_g = _agg_reduce_cols(E, agg)
            lo_g = _agg_reduce_cols(Lo, agg)
            hi_g = _agg_reduce_cols(Hi, agg)
        sps = [_Span(s, named_spans[s], None, None) for s in skeys]
        tags, aggregated = executor._group_tags(sps)
        ts_out = grid[mask]
        est_out = est_g[mask]
        err = np.maximum(hi_g[mask] - est_out, est_out - lo_g[mask])
        if len(err):
            max_abs = max(max_abs, float(err.max()))
            denom = np.maximum(np.abs(est_out), 1e-12)
            max_rel = max(max_rel, float((err / denom).max()))
        results.append(QueryResult(spec.metric, tags, aggregated,
                                   ts_out, est_out.astype(np.float64)))
    return results, max_abs, max_rel


def _on_grid(grid, bts, vals, interp):
    """One series' rail evaluated on the union grid: exact at its own
    buckets, interpolated inside [first, last] per the group gap
    policy, nan outside (no contribution) — the exact group stage's
    participation rules."""
    out = np.full(len(grid), np.nan)
    idx = np.searchsorted(bts, grid)
    exact = (idx < len(bts)) & (bts[np.minimum(idx, len(bts) - 1)]
                                == grid)
    out[exact] = vals[np.searchsorted(bts, grid[exact])]
    if interp == "none" or len(bts) < 2:
        return out
    inside = (grid > bts[0]) & (grid < bts[-1]) & ~exact
    if not inside.any():
        return out
    if interp == "lerp":
        out[inside] = np.interp(grid[inside], bts, vals)
    else:  # step-hold
        j = np.searchsorted(bts, grid[inside], side="right") - 1
        out[inside] = vals[np.clip(j, 0, len(bts) - 1)]
    return out


def _agg_reduce_cols(M: np.ndarray, agg) -> np.ndarray:
    """Column-wise group reduction over a [S, G] rail matrix (nan =
    series not contributing at that bucket), one numpy pass for the
    whole grid."""
    if agg.kind == "percentile":
        return np.nanquantile(M, agg.quantile, axis=0)
    name = agg.name
    if name in ("sum", "zimsum"):
        return np.nansum(M, axis=0)
    if name in ("min", "mimmin"):
        return np.nanmin(M, axis=0)
    if name in ("max", "mimmax"):
        return np.nanmax(M, axis=0)
    if name == "avg":
        return np.nanmean(M, axis=0)
    if name == "count":
        return (~np.isnan(M)).sum(axis=0).astype(np.float64)
    raise ValueError(f"non-monotone group aggregator: {name}")
