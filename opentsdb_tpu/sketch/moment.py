"""Moment sketches: tiny, exactly-mergeable quantile summaries.

One sketch summarizes a value multiset with its count, min, max, the
first ``k`` power sums sum(x^i), and — when every value is positive —
the first ``k`` log-power sums sum(ln(x)^i) (arXiv:1803.01969). Two
sketches merge by elementwise ADDITION of the sums (min/max fold), so
cross-window and cross-shard fan-in is associative and exact — unlike
a t-digest, whose merge recompresses lossily. The log-domain BOUNDS
need no extra bytes: when every value is positive, ln(min)/ln(max)
ARE the log-domain extremes. At the default k=5 a record is 104
bytes — under a quarter of the default 64-centroid t-digest column.

Read side, two estimators:

- ``quantile_estimate`` (one sketch, sharp): maximum-entropy density
  matching the Chebyshev-rebased moments, solved by damped Newton on
  a fixed grid. Used where one solve amortizes over a whole request
  (the ranged /sketch endpoint).
- ``cf_quantile`` (vectorized, ~1 us/bucket): the Cornish-Fisher
  expansion through skewness/kurtosis, computed in the log domain for
  wide-range positive data — the per-(series, bucket) serving path,
  where a dashboard asks for hundreds of thousands of buckets.

Estimates are soft; the GUARANTEED enclosure reported to callers
comes from ``sketch/bounds.py`` (Cantelli-style, needs only count/
mean/variance/min/max — so it holds for ANY underlying data, not
just data the estimators model well).
"""

from __future__ import annotations

import struct

import numpy as np

DEFAULT_K = 5

# Encoded layout (little-endian), version 2:
#   u8 version (=2)
#   u8 k        (power moments)
#   u8 logk     (log moments; 0 = no log section)
#   u8 pad
#   u4 count
#   f8 min, f8 max
#   f8 moments[k]                      (sum x^1 .. sum x^k)
#   [ f8 logs[logk] ]                  (iff logk > 0)
_HDR = struct.Struct("<BBBxIdd")
_VERSION = 2
# Version 1 (PR-13 pre-release) carried f8 count + explicit log
# min/max; nothing persisted it outside tests, so no legacy decode.


class MomentSketch:
    """Mutable host-side moment state (the numpy twin of the jitted
    fold in ops/sketches.moment_add)."""

    __slots__ = ("k", "count", "vmin", "vmax", "moments",
                 "log_ok", "logs")

    def __init__(self, k: int = DEFAULT_K) -> None:
        self.k = int(k)
        self.count = 0.0
        self.vmin = np.inf
        self.vmax = -np.inf
        self.moments = np.zeros(self.k, np.float64)
        # log_ok: every value folded so far was > 0 (the log section
        # is only meaningful — and only kept through merges — then).
        self.log_ok = True
        self.logs = np.zeros(self.k, np.float64)

    # -- folding -----------------------------------------------------------

    def add(self, values: np.ndarray) -> "MomentSketch":
        v = np.asarray(values, np.float64)
        if len(v) == 0:
            return self
        self.count += len(v)
        self.vmin = min(self.vmin, float(v.min()))
        self.vmax = max(self.vmax, float(v.max()))
        p = v.copy()
        for i in range(self.k):
            self.moments[i] += p.sum()
            if i + 1 < self.k:
                p *= v
        if self.log_ok and float(v.min()) > 0.0:
            lv = np.log(v)
            p = lv.copy()
            for i in range(self.k):
                self.logs[i] += p.sum()
                if i + 1 < self.k:
                    p *= lv
        else:
            self.log_ok = False
        return self

    def merge(self, other: "MomentSketch") -> "MomentSketch":
        if other.count == 0:
            return self
        k = min(self.k, other.k)
        if k < self.k:
            self.k = k
            self.moments = self.moments[:k]
            self.logs = self.logs[:k]
        self.count += other.count
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        self.moments += other.moments[:k]
        if self.log_ok and other.log_ok:
            self.logs += other.logs[:k]
        else:
            self.log_ok = False
        return self

    # -- derived -----------------------------------------------------------

    @property
    def mean(self) -> float:
        return self.moments[0] / self.count if self.count else 0.0

    @property
    def var(self) -> float:
        if self.count < 1 or self.k < 2:
            return 0.0
        m = self.mean
        return max(self.moments[1] / self.count - m * m, 0.0)

    @property
    def log_min(self) -> float:
        """ln of the smallest value (log_ok implies vmin > 0)."""
        return float(np.log(self.vmin)) if self.vmin > 0 else -np.inf

    @property
    def log_max(self) -> float:
        return float(np.log(self.vmax)) if self.vmax > 0 else -np.inf

    def log_stats(self) -> tuple[float, float] | None:
        """(mean, var) of ln(x), or None when the log section is
        invalid (any non-positive value folded in)."""
        if not self.log_ok or self.count < 1 or self.k < 2:
            return None
        m = self.logs[0] / self.count
        return m, max(self.logs[1] / self.count - m * m, 0.0)

    # -- wire format -------------------------------------------------------

    def encode(self) -> bytes:
        logk = self.k if (self.log_ok and self.count > 0) else 0
        out = [_HDR.pack(_VERSION, self.k, logk,
                         min(int(self.count), 0xFFFFFFFF),
                         self.vmin if self.count else 0.0,
                         self.vmax if self.count else 0.0),
               self.moments.astype("<f8").tobytes()]
        if logk:
            out.append(self.logs.astype("<f8").tobytes())
        return b"".join(out)

    @classmethod
    def decode(cls, blob: bytes) -> "MomentSketch":
        ver, k, logk, count, vmin, vmax = _HDR.unpack_from(blob, 0)
        if ver != _VERSION:
            raise ValueError(f"unknown moment-sketch version {ver}")
        sk = cls(k)
        sk.count = float(count)
        sk.vmin = vmin if count else np.inf
        sk.vmax = vmax if count else -np.inf
        off = _HDR.size
        sk.moments = np.frombuffer(blob, "<f8", k, off).copy()
        off += 8 * k
        if logk:
            sk.logs = np.frombuffer(blob, "<f8", logk, off).copy()
            sk.log_ok = True
        else:
            sk.log_ok = False
        return sk

    @staticmethod
    def encoded_size(k: int, with_log: bool = True) -> int:
        return _HDR.size + 8 * k + (8 * k if with_log else 0)


def from_arrays(count, vmin, vmax, moments,
                logs=None) -> MomentSketch:
    """Assemble a sketch from already-summed arrays (the batched fold
    path: summary.window_sketches computes exactly these columns)."""
    sk = MomentSketch(len(moments))
    sk.count = float(count)
    sk.vmin, sk.vmax = float(vmin), float(vmax)
    sk.moments = np.asarray(moments, np.float64).copy()
    if logs is not None:
        sk.logs = np.asarray(logs, np.float64).copy()
        sk.log_ok = True
    else:
        sk.log_ok = False
    return sk


class MomentColumns:
    """Struct-of-arrays over many decoded sketches (one per window/
    bucket): the vectorized serving path's working form. Merging a
    window into a bucket is row addition; estimates and bounds are
    elementwise numpy over all rows at once."""

    __slots__ = ("k", "count", "vmin", "vmax", "moments", "log_ok",
                 "logs")

    def __init__(self, n: int, k: int = DEFAULT_K) -> None:
        self.k = k
        self.count = np.zeros(n)
        self.vmin = np.full(n, np.inf)
        self.vmax = np.full(n, -np.inf)
        self.moments = np.zeros((n, k))
        self.log_ok = np.ones(n, bool)
        self.logs = np.zeros((n, k))

    def add_blob(self, i: int, blob: bytes) -> None:
        """Merge one encoded sketch into row ``i``."""
        ver, k, logk, count, vmin, vmax = _HDR.unpack_from(blob, 0)
        if ver != _VERSION:
            raise ValueError(f"unknown moment-sketch version {ver}")
        use = min(k, self.k)
        self.count[i] += count
        self.vmin[i] = min(self.vmin[i], vmin)
        self.vmax[i] = max(self.vmax[i], vmax)
        self.moments[i, :use] += np.frombuffer(blob, "<f8", use,
                                               _HDR.size)
        if logk:
            self.logs[i, :use] += np.frombuffer(
                blob, "<f8", use, _HDR.size + 8 * k)
        else:
            self.log_ok[i] = False

    def add_values(self, i: int, values: np.ndarray) -> None:
        """Merge exact raw values into row ``i`` (the stitched edge/
        dirty contributions)."""
        v = np.asarray(values, np.float64)
        if not len(v):
            return
        self.count[i] += len(v)
        self.vmin[i] = min(self.vmin[i], float(v.min()))
        self.vmax[i] = max(self.vmax[i], float(v.max()))
        p = v.copy()
        for j in range(self.k):
            self.moments[i, j] += p.sum()
            if j + 1 < self.k:
                p *= v
        if self.log_ok[i] and float(v.min()) > 0:
            lv = np.log(v)
            p = lv.copy()
            for j in range(self.k):
                self.logs[i, j] += p.sum()
                if j + 1 < self.k:
                    p *= lv
        else:
            self.log_ok[i] = False

    def row(self, i: int) -> MomentSketch:
        return from_arrays(self.count[i], self.vmin[i], self.vmax[i],
                           self.moments[i],
                           self.logs[i] if self.log_ok[i] else None)


# ---------------------------------------------------------------------------
# Normal quantile (Acklam's rational approximation; no scipy)
# ---------------------------------------------------------------------------

_A = (-3.969683028665376e+01, 2.209460984245205e+02,
      -2.759285104469687e+02, 1.383577518672690e+02,
      -3.066479806614716e+01, 2.506628277459239e+00)
_B = (-5.447609879822406e+01, 1.615858368580409e+02,
      -1.556989798598866e+02, 6.680131188771972e+01,
      -1.328068155288572e+01)
_C = (-7.784894002430293e-03, -3.223964580411365e-01,
      -2.400758277161838e+00, -2.549732539343734e+00,
      4.374664141464968e+00, 2.938163982698783e+00)
_D = (7.784695709041462e-03, 3.224671290700398e-01,
      2.445134137142996e+00, 3.754408661907416e+00)


def norm_ppf(q: np.ndarray) -> np.ndarray:
    """Vectorized standard-normal quantile, |err| < 1.15e-9."""
    q = np.clip(np.asarray(q, np.float64), 1e-12, 1 - 1e-12)
    out = np.empty_like(q)
    lo = q < 0.02425
    hi = q > 1 - 0.02425
    mid = ~(lo | hi)
    if mid.any():
        r = q[mid] - 0.5
        s = r * r
        num = ((((_A[0] * s + _A[1]) * s + _A[2]) * s + _A[3]) * s
               + _A[4]) * s + _A[5]
        den = ((((_B[0] * s + _B[1]) * s + _B[2]) * s + _B[3]) * s
               + _B[4]) * s + 1.0
        out[mid] = r * num / den
    for sel, sign, qq in ((lo, -1.0, q), (hi, 1.0, 1.0 - q)):
        if sel.any():
            r = np.sqrt(-2.0 * np.log(qq[sel]))
            num = ((((_C[0] * r + _C[1]) * r + _C[2]) * r + _C[3]) * r
                   + _C[4]) * r + _C[5]
            den = (((_D[0] * r + _D[1]) * r + _D[2]) * r
                   + _D[3]) * r + 1.0
            out[sel] = sign * -(num / den)
    return out


def cf_quantile(count, mean, var, m3, m4, vmin, vmax,
                q: float) -> np.ndarray:
    """Vectorized Cornish-Fisher quantile estimate from the first
    four CENTRAL-moment inputs (elementwise over buckets): z adjusted
    by skewness and excess kurtosis, clamped to [min, max]. All
    inputs are same-shape arrays."""
    s = np.sqrt(np.maximum(var, 0.0))
    z = float(norm_ppf(np.array([q]))[0])
    with np.errstate(divide="ignore", invalid="ignore"):
        g1 = np.where(s > 0, m3 / np.maximum(s ** 3, 1e-300), 0.0)
        g2 = np.where(s > 0,
                      m4 / np.maximum(var ** 2, 1e-300) - 3.0, 0.0)
    # Clamp the shape terms: CF diverges for extreme skew/kurtosis,
    # and the estimate only needs to be NEAR — the bound is separate.
    g1 = np.clip(g1, -3.0, 3.0)
    g2 = np.clip(g2, -6.0, 6.0)
    w = (z + g1 * (z * z - 1.0) / 6.0
         + g2 * (z ** 3 - 3.0 * z) / 24.0
         - g1 * g1 * (2.0 * z ** 3 - 5.0 * z) / 36.0)
    est = mean + s * w
    return np.clip(est, vmin, vmax)


def central_moments(count, raw: np.ndarray):
    """(mean, var, m3, m4) columns from raw power-sum columns
    [N, k>=4] (elementwise; the k<4 tail pads with zeros — CF then
    degrades to the normal/2-moment estimate)."""
    n = np.maximum(count, 1.0)
    k = raw.shape[1]
    m1 = raw[:, 0] / n
    m2 = (raw[:, 1] / n if k > 1 else m1 * m1)
    var = np.maximum(m2 - m1 * m1, 0.0)
    if k > 2:
        e3 = raw[:, 2] / n
        m3 = e3 - 3 * m1 * m2 + 2 * m1 ** 3
    else:
        m3 = np.zeros_like(m1)
    if k > 3:
        e4 = raw[:, 3] / n
        e3 = raw[:, 2] / n
        m4 = (e4 - 4 * m1 * e3 + 6 * m1 * m1 * m2
              - 3 * m1 ** 4)
        m4 = np.maximum(m4, 0.0)
    else:
        m4 = 3.0 * var * var  # normal kurtosis: g2 = 0
    return m1, var, m3, m4


# ---------------------------------------------------------------------------
# Maximum-entropy quantile solver (the sharp single-sketch path)
# ---------------------------------------------------------------------------

_GRID = 257          # density grid points on [-1, 1]
_NEWTON_STEPS = 30
_TOL = 1e-9


def _cheb_vander(x: np.ndarray, k: int) -> np.ndarray:
    """[len(x), k+1] matrix of T_0..T_k evaluated at x (recurrence)."""
    out = np.empty((len(x), k + 1))
    out[:, 0] = 1.0
    if k >= 1:
        out[:, 1] = x
    for i in range(2, k + 1):
        out[:, i] = 2 * x * out[:, i - 1] - out[:, i - 2]
    return out


def _cheb_moments(power_sums: np.ndarray, count: float, lo: float,
                  hi: float) -> np.ndarray | None:
    """Chebyshev moments E[T_i(y)], y = (2x - (lo+hi)) / (hi-lo), from
    raw power sums — the binomial rebase. Returns None when the rebase
    is numerically untrustworthy (catastrophic cancellation leaves
    |E[T_i]| > 1, which no distribution on [-1, 1] can produce)."""
    k = len(power_sums)
    if hi <= lo:
        return None
    # Raw moments of x (E[x^i], i=0..k).
    mu = np.empty(k + 1)
    mu[0] = 1.0
    mu[1:] = power_sums / count
    # Moments of y via (a + b*x)^i expansion: a = -(lo+hi)/(hi-lo),
    # b = 2/(hi-lo).
    a = -(lo + hi) / (hi - lo)
    b = 2.0 / (hi - lo)
    ymom = np.empty(k + 1)
    for i in range(k + 1):
        acc = 0.0
        for j in range(i + 1):
            acc += (_BINOM(i, j) * (a ** (i - j)) * (b ** j) * mu[j])
        ymom[i] = acc
    # Chebyshev T_i as polynomials in y (coefficient recurrence).
    coef = [np.array([1.0]), np.array([0.0, 1.0])]
    for i in range(2, k + 1):
        c = np.zeros(i + 1)
        c[1:] += 2 * coef[-1]
        c[:len(coef[-2])] -= coef[-2]
        coef.append(c)
    cm = np.array([float(np.dot(c, ymom[:len(c)])) for c in coef])
    if not np.all(np.isfinite(cm)) or np.any(np.abs(cm[1:]) > 1.0 + 1e-6):
        return None
    return np.clip(cm, -1.0, 1.0)


def _BINOM(n: int, r: int) -> float:
    from math import comb
    return float(comb(n, r))


def _maxent_cdf(cheb_mom: np.ndarray) -> tuple[np.ndarray, np.ndarray] | None:
    """Solve the maxent density on [-1, 1] matching ``cheb_mom``
    (index 0 == 1). Returns (grid y, CDF at y) or None on failure."""
    k = len(cheb_mom) - 1
    y = np.linspace(-1.0, 1.0, _GRID)
    T = _cheb_vander(y, k)                     # [G, k+1]
    lam = np.zeros(k + 1)
    w = np.full(_GRID, 2.0 / (_GRID - 1))      # trapezoid weights
    w[0] *= 0.5
    w[-1] *= 0.5
    target = cheb_mom
    for _ in range(_NEWTON_STEPS):
        e = T @ lam
        e -= e.max()                           # overflow guard
        dens = np.exp(e) * w
        z = dens.sum()
        if not np.isfinite(z) or z <= 0:
            return None
        p = dens / z
        cur = T.T @ p                          # E[T_i]
        grad = cur - target
        if np.abs(grad).max() < _TOL:
            break
        # Hessian: Cov(T_i, T_j) under p.
        H = (T.T * p) @ T - np.outer(cur, cur)
        H[np.diag_indices_from(H)] += 1e-10
        try:
            step = np.linalg.solve(H, grad)
        except np.linalg.LinAlgError:
            return None
        # Damping: bound the update so a stiff Hessian can't explode.
        n = np.abs(step).max()
        if n > 5.0:
            step *= 5.0 / n
        lam = lam - step
    e = T @ lam
    e -= e.max()
    dens = np.exp(e) * w
    z = dens.sum()
    if not np.isfinite(z) or z <= 0:
        return None
    cdf = np.cumsum(dens / z)
    cdf[-1] = 1.0
    return y, cdf


def quantile_estimate(sk: MomentSketch, qs: np.ndarray,
                      fast: bool = False) -> np.ndarray:
    """Quantile estimates (one per q in [0, 1]): the maxent solve, or
    — ``fast`` / solver-declined — the vectorizable Cornish-Fisher
    form. Callers always get values inside [min, max]; the GUARANTEED
    enclosure is computed separately (sketch/bounds.py), so a cheap
    estimate is merely less sharp, never unsound."""
    qs = np.clip(np.asarray(qs, np.float64), 0.0, 1.0)
    if sk.count <= 0:
        return np.full(len(qs), np.nan)
    if sk.vmax <= sk.vmin:
        return np.full(len(qs), sk.vmin)
    use_log = (sk.log_ok and sk.vmin > 0
               and (sk.log_max - sk.log_min) > 2.0)
    if not fast and sk.k >= 3:
        for domain in (("log", "lin") if use_log else ("lin", "log")):
            if domain == "log":
                if not sk.log_ok or sk.log_max <= sk.log_min:
                    continue
                cm = _cheb_moments(sk.logs, sk.count, sk.log_min,
                                   sk.log_max)
                lo, hi = sk.log_min, sk.log_max
            else:
                cm = _cheb_moments(sk.moments, sk.count, sk.vmin,
                                   sk.vmax)
                lo, hi = sk.vmin, sk.vmax
            if cm is None:
                continue
            solved = _maxent_cdf(cm)
            if solved is None:
                continue
            y, cdf = solved
            est_y = np.interp(qs, cdf, y)
            est = lo + (est_y + 1.0) * 0.5 * (hi - lo)
            if domain == "log":
                est = np.exp(est)
            return np.clip(est, sk.vmin, sk.vmax)
    # Cornish-Fisher (log-domain preferred for wide positive data).
    one = np.ones(1)
    out = np.empty(len(qs))
    if use_log:
        raw = sk.logs.reshape(1, -1)
        m1, var, m3, m4 = central_moments(one * sk.count, raw)
        for i, q in enumerate(qs):
            out[i] = float(np.exp(np.clip(
                cf_quantile(one * sk.count, m1, var, m3, m4,
                            one * sk.log_min, one * sk.log_max,
                            float(q))[0],
                sk.log_min, sk.log_max)))
    else:
        raw = sk.moments.reshape(1, -1)
        m1, var, m3, m4 = central_moments(one * sk.count, raw)
        for i, q in enumerate(qs):
            out[i] = float(cf_quantile(
                one * sk.count, m1, var, m3, m4, one * sk.vmin,
                one * sk.vmax, float(q))[0])
    return np.clip(out, sk.vmin, sk.vmax)
