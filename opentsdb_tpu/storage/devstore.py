"""Device-resident columnar hot window — queries without host→device upload.

Measured motivation (scripts/tpu_probe.py on the real v5e): the fused query
kernels run at HBM speed (~1 ms for 10M points) but moving those points to
the device costs seconds — host→device bandwidth is the entire query cost.
The reference never faces this because its compute sits where its data is
(Java heap over HBase scans); a TPU-native design has to put the data where
the compute is instead. This module keeps the recent ingest window's flat
columns (rel-timestamp, value, series-id) resident in device HBM, appended
as data arrives, so the steady-state dashboard query touches the host only
for the series directory and the tiny [S]-sized group maps.

Design:

- **Per-metric windows.** Each metric holds a host-side series directory
  (series_key -> dense sid, the group-by/tag-filter substrate) and a list
  of immutable device chunks; a query concatenates the chunks ON DEVICE
  (HBM-to-HBM, no transfer) and caches the result until the next flush.
- **Host staging.** ``append`` is O(1) host work (numpy refs into a list);
  chunks upload in ``staging_points``-sized batches, padded to powers of
  two so jit shapes repeat. One upload per ~million points amortizes the
  slow host link at ingest time, once, instead of per query.
- **Exactness, not cache-maybe.** The window only serves a query when its
  answer is guaranteed byte-identical to the storage scan path:
  - per-series timestamps must be strictly monotone across appends (the
    overwhelmingly common collector pattern); an out-of-order or rewritten
    timestamp marks the metric dirty and queries fall back to the scan
    path (``dirty_fallbacks`` counts them);
  - evicting old chunks advances ``complete_from``; queries reaching
    before it fall back.
  - deletes/fsck rewrites call ``invalidate``.
- **Sizing.** ~12 B/point device-side: the 1B-point north-star workload is
  ~12 GB — within one v5e chip's 16 GB HBM, which is exactly the design
  point (BASELINE.json: 1B points, single chip serving).

No reference analog: HBase scans are the reference's only read path
(src/core/TsdbQuery.java:240-285); this is the TPU-era replacement for
"the data lives next to the compute".
"""

from __future__ import annotations

import contextlib
import threading
import time as _time
from typing import NamedTuple

import numpy as np


def _pad_pow2(n: int, lo: int = 1024) -> int:
    size = lo
    while size < n:
        size *= 2
    return size


class DevColumns(NamedTuple):
    """One metric's resident window, ready for the fused kernels."""
    rel_ts: object          # [N] int32 device, seconds since ``epoch``
    values: object          # [N] float32 device
    sid: object             # [N] int32 device
    valid: object           # [N] bool device (padding mask)
    epoch: int              # int64 base the rel timestamps offset from
    series_keys: list       # sid -> series_key bytes
    generation: int         # bumps when the directory grows
    version: int            # bumps on ANY data change (new/evicted
    #                         chunks) — derived-result cache key


class DevChunks(NamedTuple):
    """One metric's resident window as its RAW device chunk list — no
    concatenation. The chunked query stage (ops/kernels
    window_series_stage_chunks) folds these into [S, B] grids with
    per-chunk transients, so a window can approach the chip's whole
    HBM: the concat view costs a second full copy of the columns plus
    N-sized kernel transients, which caps it near half the HBM."""
    chunks: list            # [(rel_ts, values, sid, valid) device arrays]
    epoch: int
    series_keys: list
    generation: int
    version: int


class _MetricWindow:
    __slots__ = ("sids", "keys", "last_ts", "epoch", "chunks",
                 "staged_ts", "staged_vals", "staged_sid", "staged_n",
                 "dirty", "complete_from", "concat", "generation",
                 "version", "device_points", "inflight",
                 "inflight_since")

    def __init__(self) -> None:
        self.sids: dict[bytes, int] = {}
        self.keys: list[bytes] = []
        self.last_ts: list[int] = []
        self.epoch: int | None = None
        self.chunks: list[dict] = []      # ts/vals/sid device + n/max_ts
        self.staged_ts: list[np.ndarray] = []
        self.staged_vals: list[np.ndarray] = []
        self.staged_sid: list[np.ndarray] = []
        self.staged_n = 0
        self.dirty = False
        self.complete_from: int | None = None  # None = since forever
        self.concat: DevColumns | None = None
        self.generation = 0
        self.version = 0          # bumps on ANY data change (chunk
        #                           appended/evicted, invalidate) —
        #                           derived-result cache key
        self.device_points = 0
        self.inflight = 0               # taken-but-not-uploaded batches
        # Monotonic time of THIS metric's last upload progress while it
        # has in-flight batches (None = quiescent): the per-metric
        # wedge detector, immune to other metrics' completions keeping
        # the global liveness signal fresh.
        self.inflight_since: float | None = None


class DeviceWindow:
    """Thread-safe store of per-metric device-resident columns."""

    _instances = 0

    def __init__(self, staging_points: int = 1 << 20,
                 max_points: int = 1 << 26,
                 background: bool = True,
                 stall_timeout: float = 60.0,
                 device=None) -> None:
        # Process-unique instance token: DevColumns.version counters
        # restart at 0 in a replacement window, so derived-result caches
        # key on (instance_id, version) to survive window swaps.
        DeviceWindow._instances += 1
        self.instance_id = DeviceWindow._instances
        self.staging_points = staging_points
        self.max_points = max_points
        self.background = background
        # Optional device pin: a mesh shard's window commits its chunks
        # to one specific device, so the stage kernels that consume the
        # committed inputs execute there — the per-shard placement the
        # sharded hot set (storage/devshard.py) is built on. None keeps
        # the historical behavior (jax's default device).
        self.device = device
        # Degraded-mode guard: a wedged accelerator (hung transport)
        # freezes the uploader mid-device-call FOREVER. Ingest and
        # queries must not hang with it — after stall_timeout they
        # dirty-mark the affected metric and proceed (queries fall back
        # to the storage scan path; the mark is sticky like every other
        # fallback). The reference's analog is the HBase-down drain
        # posture: degrade, never block the write path indefinitely.
        self.stall_timeout = stall_timeout
        self._lock = threading.RLock()
        self._metrics: dict[bytes, _MetricWindow] = {}
        # Background uploader: host->device copies of staged chunks run
        # off the ingest thread (the tunnel/PCIe copy otherwise blocks
        # ingest for its full duration). Bounded queue = backpressure;
        # single worker = chunk order (and so per-series time order in
        # the concatenated window) is preserved.
        import queue as _queue

        self._pending: _queue.Queue = _queue.Queue(maxsize=2)
        self._uploader: threading.Thread | None = None
        # Per-metric upload completion: queries wait only for THEIR
        # metric's in-flight batches, not the whole queue (joining the
        # global queue couples query latency to unrelated ingest bursts).
        self._cond = threading.Condition(self._lock)
        # Global residency accounting: max_points caps the SUM across
        # metrics (the HBM budget is per chip, not per metric); chunks
        # carry an upload sequence number so eviction picks the oldest
        # chunk fleet-wide.
        self._total_points = 0
        self._seq = 0
        # Liveness signal: bumps on EVERY upload completion (success or
        # failure). Stall handling keys off this, not off elapsed time
        # alone — a backlogged-but-progressing uploader (big chunks,
        # slow transport) must produce backpressure or a cache miss,
        # never the sticky dirty mark reserved for a wedged device
        # (ADVICE r03: a transient slowdown was a permanent cache loss).
        self._uploads_completed = 0
        # stats
        self.appended_points = 0
        self.evicted_points = 0
        self.dirty_fallbacks = 0
        self.upload_stalls = 0
        self.window_hits = 0
        self.window_misses = 0

    # -- ingest side ---------------------------------------------------

    def append(self, metric_uid: bytes, series_key: bytes,
               timestamps: np.ndarray, values: np.ndarray) -> None:
        """Record one series batch (timestamps int64 sorted ascending,
        values float64/float32). O(1) host work plus a device upload
        every ``staging_points`` points."""
        n = len(timestamps)
        if n == 0:
            return
        with self._lock:
            mw = self._metrics.get(metric_uid)
            if mw is None:
                mw = self._metrics[metric_uid] = _MetricWindow()
            if mw.dirty:
                return
            sid = mw.sids.get(series_key)
            if sid is None:
                sid = len(mw.keys)
                mw.sids[series_key] = sid
                mw.keys.append(series_key)
                mw.last_ts.append(-1)
                mw.generation += 1
            if int(timestamps[0]) <= mw.last_ts[sid]:
                # Out-of-order or rewritten timestamp: correctness now
                # needs storage's dedup/overwrite semantics. Mark the
                # metric dirty and free its device state — every query
                # falls back to the scan path from here on.
                self._mark_dirty(mw)
                return
            mw.last_ts[sid] = int(timestamps[-1])
            if mw.epoch is None:
                mw.epoch = int(timestamps[0])
            # Stage COPIES: the window owns its buffers. asarray would
            # alias a caller's array of the right dtype, and since
            # sort_dedup's sorted fast path started returning the
            # ingest input by reference, a collector reusing its batch
            # buffer would silently rewrite staged timestamps under
            # the window. The memcpy is ~12 B/point, noise next to the
            # upload it feeds.
            mw.staged_ts.append(np.array(timestamps, np.int64))
            mw.staged_vals.append(np.array(values, np.float32))
            mw.staged_sid.append(np.full(n, sid, np.int32))
            mw.staged_n += n
            self.appended_points += n
            work = (self._take_staged(mw)
                    if mw.staged_n >= self.staging_points else None)
        # The bounded put happens OUTSIDE _lock: the uploader takes the
        # lock to append finished chunks, so blocking on a full queue
        # while holding it would deadlock.
        if work is not None:
            self._submit(work)

    def _take_staged(self, mw: _MetricWindow):
        """Swap the staged batch out (caller holds _lock); the returned
        work item is submitted outside the lock. The upload sequence
        number is assigned HERE, under the lock, so racing producers
        can't enqueue a metric's batches out of time order (_upload
        inserts by seq; eviction relies on chunks[0] being oldest)."""
        if mw.staged_n == 0:
            return None
        batch = (mw.staged_ts, mw.staged_vals, mw.staged_sid,
                 mw.staged_n)
        mw.staged_ts, mw.staged_vals, mw.staged_sid = [], [], []
        mw.staged_n = 0
        if mw.inflight == 0:
            mw.inflight_since = _time.monotonic()
        mw.inflight += 1
        seq = self._seq
        self._seq += 1
        return (mw, batch, seq)

    def _run_upload(self, work) -> None:
        """Execute one upload on the calling thread with full failure
        handling (dirty-mark under the lock) and completion signalling.
        Must be called without _lock."""
        try:
            self._upload(*work)
        except Exception:  # pragma: no cover - device failure
            with self._lock:
                self._mark_dirty(work[0])
        finally:
            self._upload_done(work[0])

    def _submit(self, work) -> None:
        """Queue one (mw, batch, seq) for the uploader thread, or upload
        inline when background=False. Must be called without _lock."""
        if not self.background:
            self._run_upload(work)
            return
        if self._uploader is None:
            with self._lock:
                if self._uploader is None:
                    self._uploader = threading.Thread(
                        target=self._upload_loop, daemon=True,
                        name="devwindow-uploader")
                    self._uploader.start()
        import queue as _queue
        while True:
            with self._cond:
                base = self._uploads_completed
            try:
                self._pending.put(work, timeout=self.stall_timeout)
                return
            except _queue.Full:
                with self._cond:
                    if (self._uploads_completed != base
                            and not self._metric_stuck(
                                work[0], _time.monotonic())):
                        # An upload finished during the wait: the
                        # uploader is alive, just backlogged. Keep
                        # blocking — a bounded queue IS the backpressure
                        # mechanism — rather than dirty-marking a
                        # healthy metric's whole window. (Unless THIS
                        # metric's own oldest batch is ancient — then
                        # it is stuck regardless of global liveness.)
                        continue
                    # No upload completed for a full stall window on a
                    # full queue: the device (or its transport) is
                    # wedged. Drop THIS metric to degraded mode instead
                    # of blocking the ingest thread behind a dead
                    # accelerator. The dropped work item's in-flight
                    # count (taken in _take_staged) must be released
                    # here — it will never reach _run_upload — or
                    # queries would wait on it forever.
                    mw = work[0]
                    self.upload_stalls += 1
                    self._mark_dirty(mw)
                    mw.inflight -= 1
                    self._cond.notify_all()
                    return

    def _upload_loop(self) -> None:
        while True:
            work = self._pending.get()
            try:
                # _run_upload dirty-marks under the lock on failure: a
                # bare flag write would leave resident chunks counting
                # toward _total_points forever (a dead window holding
                # HBM and forcing eviction of healthy metrics).
                self._run_upload(work)
            finally:
                self._pending.task_done()

    def _upload_done(self, mw: _MetricWindow) -> None:
        with self._cond:
            mw.inflight -= 1
            if mw.inflight == 0:
                mw.inflight_since = None
            else:
                # This metric itself made progress: restart its
                # per-metric wedge clock.
                mw.inflight_since = _time.monotonic()
            self._uploads_completed += 1
            self._cond.notify_all()

    def _upload(self, mw: _MetricWindow, batch, seq: int) -> None:
        """Upload one staged batch as a padded immutable chunk."""
        import jax

        staged_ts, staged_vals, staged_sid, _ = batch
        ts = np.concatenate(staged_ts)
        rel64 = ts - mw.epoch
        if (rel64 > 2**31 - 1).any() or (rel64 < -(2**31)).any():
            # >68 years from the metric's epoch: the int32 rel column
            # would wrap silently. Fall back rather than mis-bucket.
            with self._lock:
                self._mark_dirty(mw)
            return
        rel = rel64.astype(np.int32)
        vals = np.concatenate(staged_vals)
        sid = np.concatenate(staged_sid)
        n = len(rel)
        pad = _pad_pow2(n)
        if pad != n:
            rel = np.pad(rel, (0, pad - n))
            vals = np.pad(vals, (0, pad - n))
            sid = np.pad(sid, (0, pad - n))
        valid = np.arange(pad) < n
        dev = self.device
        chunk = {
            "ts": jax.device_put(rel, dev),
            "vals": jax.device_put(vals, dev),
            "sid": jax.device_put(sid, dev),
            "valid": jax.device_put(valid, dev),
            "n": n, "pad": pad, "seq": seq,
            "min_ts": int(ts.min()), "max_ts": int(ts.max()),
        }
        with self._lock:
            if mw.dirty:  # marked dirty while we were copying
                return
            # Insert in seq order (assigned at _take_staged time). Two
            # things can land out of order here: racing producers whose
            # _pending.put() (outside the lock) inverts their take
            # order, and a query-side inline upload (columns()) racing
            # the background worker. Eviction relies on chunks[0] being
            # the metric's oldest.
            pos = len(mw.chunks)
            while pos > 0 and mw.chunks[pos - 1]["seq"] > seq:
                pos -= 1
            mw.chunks.insert(pos, chunk)
            mw.device_points += n
            self._total_points += n
            mw.concat = None
            mw.version += 1
            # Evict the globally-oldest chunks past the (per-chip, NOT
            # per-metric) budget. complete_from of the owning metric
            # advances past everything the evicted chunk could cover.
            while self._total_points > self.max_points:
                victim = min(
                    (m for m in self._metrics.values() if m.chunks),
                    key=lambda m: m.chunks[0]["seq"], default=None)
                if victim is None or (victim is mw
                                      and len(mw.chunks) == 1):
                    break  # never evict the chunk just added
                old = victim.chunks.pop(0)
                victim.device_points -= old["n"]
                self._total_points -= old["n"]
                self.evicted_points += old["n"]
                victim.concat = None
                victim.version += 1
                nxt = old["max_ts"] + 1
                if (victim.complete_from is None
                        or nxt > victim.complete_from):
                    victim.complete_from = nxt

    def flush(self) -> None:
        """Upload every metric's staged points and wait for the
        uploader to drain (query-side barrier)."""
        with self._lock:
            work = [w for w in map(self._take_staged,
                                   self._metrics.values()) if w]
        for w in work:
            self._submit(w)
        # Bounded barrier: join() would block forever if the uploader
        # is wedged inside a device call (task_done only fires after
        # the hung upload returns). Best-effort within stall_timeout.
        deadline = _time.monotonic() + self.stall_timeout
        while (self._pending.unfinished_tasks
               and _time.monotonic() < deadline):
            _time.sleep(0.01)

    def quiesce(self) -> None:
        """Materialize EVERYTHING into device chunks: upload all staged
        batches and wait for every metric's in-flight uploads. The
        reshard gate's drain step (devshard.py) — after it returns, a
        refs-only chunk snapshot is the complete window. A metric whose
        uploads stall past the wedge deadline degrades to dirty (the
        standard sticky fallback) rather than blocking forever."""
        self.flush()
        deadline = _time.monotonic() + 2 * self.stall_timeout
        with self._cond:
            while any(mw.inflight > 0 and not mw.dirty
                      for mw in self._metrics.values()):
                now = _time.monotonic()
                if now >= deadline:
                    for mw in self._metrics.values():
                        if mw.inflight > 0 and not mw.dirty:
                            self.upload_stalls += 1
                            self._mark_dirty(mw)
                    self._cond.notify_all()
                    break
                self._cond.wait(timeout=min(deadline - now, 0.05))

    def _snapshot_metrics(self) -> dict:
        """Refs-only snapshot for the reshard rebuild (devshard.py):
        per metric, the directory, chunk list, and coverage state at
        this instant. Chunks are immutable once inserted, so holding
        refs is safe; the caller must treat every field as read-only.
        Call after ``quiesce`` — staged/in-flight batches are not
        represented."""
        with self._lock:
            return {uid: {"keys": list(mw.keys),
                          "epoch": mw.epoch,
                          "chunks": list(mw.chunks),
                          "dirty": mw.dirty,
                          "complete_from": mw.complete_from}
                    for uid, mw in self._metrics.items()}

    def set_complete_from(self, metric_uid: bytes, floor: int) -> None:
        """Raise (never lower) a metric's coverage floor — the reshard
        rebuild carries the source shards' eviction horizon into the
        redistributed window so it never claims coverage the old set
        had already evicted."""
        with self._lock:
            mw = self._metrics.get(metric_uid)
            if mw is None:
                return
            if mw.complete_from is None or floor > mw.complete_from:
                mw.complete_from = floor

    def invalidate(self, metric_uid: bytes | None = None) -> None:
        """Mark window state unusable after storage mutations the append
        stream didn't see (deletes, fsck --fix rewrites, mid-batch
        throttles). The mark is sticky — popping the window instead
        would let the next append recreate one that claims coverage
        since forever while storage holds data it never saw."""
        with self._lock:
            targets = (list(self._metrics.values()) if metric_uid is None
                       else filter(None, [self._metrics.get(metric_uid)]))
            for mw in targets:
                self._mark_dirty(mw)

    def _mark_dirty(self, mw: _MetricWindow) -> None:
        """Sticky fallback mark + free the metric's device/staging state.
        Caller holds _lock."""
        mw.dirty = True
        mw.chunks.clear()
        mw.concat = None
        mw.version += 1
        mw.staged_ts.clear()
        mw.staged_vals.clear()
        mw.staged_sid.clear()
        mw.staged_n = 0
        self._total_points -= mw.device_points
        mw.device_points = 0

    # -- query side ----------------------------------------------------

    def _wait_quiet(self, mw: _MetricWindow) -> str:
        """Wait for this metric's in-flight uploads with the
        wedged-vs-slow distinction (ADVICE r03): the sticky dirty mark
        is reserved for a device that has completed NOTHING for a full
        stall window; a backlogged-but-progressing uploader yields a
        bounded plain miss instead (scan fallback now, window intact
        for the next query). Returns ``"ready"`` (quiescent — caller
        still re-checks dirty under the lock), or ``"slow"``.

        Progress = ``_uploads_completed`` advancing, ANY metric: device
        calls mostly serialize, so a completion is evidence the
        transport is alive. But it is not proof THIS metric's upload
        moves (a query-drain helper can be stuck in its own device call
        while the uploader thread completes others), so a per-metric
        hard deadline — ``inflight_since`` older than 4x stall_timeout
        — converts a persistently-stuck metric to sticky dirty no
        matter how fresh the global signal is; without it, every query
        of that metric would pay the 2x cap forever. ``dirty``
        short-circuits — an already-degraded metric answers
        immediately, not after a stall_timeout per query."""

        with self._cond:
            last = self._uploads_completed
            now = _time.monotonic()
            deadline = now + self.stall_timeout       # wedge detector
            cap = now + 2 * self.stall_timeout        # latency bound
            while mw.inflight > 0 and not mw.dirty:
                now = _time.monotonic()
                if self._uploads_completed != last:
                    last = self._uploads_completed
                    deadline = now + self.stall_timeout
                if now >= deadline or self._metric_stuck(mw, now):
                    # Nothing completed for a full stall window while
                    # we held in-flight work: wedged. Degrade this
                    # metric so the query (and every later one) takes
                    # the scan path instead of hanging on a dead
                    # device. Wake the other waiters — their loop
                    # re-checks dirty.
                    self.upload_stalls += 1
                    self._mark_dirty(mw)
                    self._cond.notify_all()
                    break
                if now >= cap:
                    return "slow"
                self._cond.wait(timeout=min(deadline, cap) - now)
        return "ready"

    def _metric_stuck(self, mw: _MetricWindow, now: float) -> bool:
        """True when THIS metric's oldest in-flight batch has made no
        progress for 4x stall_timeout — the per-metric wedge verdict
        that global upload completions cannot mask. Caller holds
        _cond/_lock."""
        return (mw.inflight_since is not None
                and now - mw.inflight_since >= 4 * self.stall_timeout)

    @contextlib.contextmanager
    def _ready_window(self, metric_uid: bytes, start: int):
        """The shared availability preamble of columns()/chunk_columns()
        as a context manager: drain this metric's staged batch, wait for
        ITS in-flight uploads, validate the exact-coverage contract.
        Yields the window WITH THE LOCK HELD (released on exit, every
        path — the old hand-off-a-held-lock contract deadlocked if any
        future early return forgot the release), or None for scan-path
        fallback."""
        with self._lock:
            mw = self._metrics.get(metric_uid)
            if mw is None:
                self.window_misses += 1
                yield None
                return
            work = self._take_staged(mw)
        # Upload + drain OUTSIDE the lock (the uploader takes the
        # lock to append chunks); then re-check under the lock —
        # the drain can mark dirty (upload failure) or advance
        # complete_from. The query's staged batch uploads INLINE
        # (not via the queue: queueing would couple this query's
        # latency to other metrics' stuck uploads — ADVICE r02) but
        # on a daemon helper thread: a device call wedged inside
        # the transport cannot be interrupted, so the query thread
        # must never make it directly. The helper's batch counts in
        # mw.inflight (released in _run_upload's finally), so the
        # unified _wait_quiet below applies the same wedged-vs-slow
        # policy to it; a parked helper is a bounded daemon-thread
        # leak, and if the device later revives and the upload
        # lands, _upload's dirty check discards it.
        if work is not None:
            threading.Thread(target=self._run_upload, args=(work,),
                             daemon=True,
                             name="devwindow-query-drain").start()
        if self._wait_quiet(mw) == "slow":
            with self._lock:       # counters mutate under the lock only
                self.window_misses += 1
            yield None
            return
        with self._lock:
            if mw.dirty:
                self.dirty_fallbacks += 1
                yield None
            elif (mw.complete_from is not None
                    and start < mw.complete_from) or not mw.chunks:
                self.window_misses += 1
                yield None
            else:
                yield mw

    def columns(self, metric_uid: bytes, start: int,
                end: int) -> DevColumns | None:
        """The metric's resident columns when they exactly cover
        [start, end]; None means the caller must use the scan path."""
        with self._ready_window(metric_uid, start) as mw:
            if mw is None:
                return None
            if mw.concat is None or mw.concat.generation != mw.generation:
                import jax.numpy as jnp

                mw.concat = DevColumns(
                    rel_ts=jnp.concatenate(
                        [c["ts"] for c in mw.chunks]),
                    values=jnp.concatenate(
                        [c["vals"] for c in mw.chunks]),
                    sid=jnp.concatenate([c["sid"] for c in mw.chunks]),
                    valid=jnp.concatenate(
                        [c["valid"] for c in mw.chunks]),
                    epoch=mw.epoch, series_keys=list(mw.keys),
                    generation=mw.generation,
                    version=mw.version)
            self.window_hits += 1
            return mw.concat

    def chunk_columns(self, metric_uid: bytes, start: int,
                      end: int) -> DevChunks | None:
        """Like columns(), but returns the raw chunk list without
        building (or caching) the concatenated view — the chunked query
        stage folds it without a second full copy of the columns. Same
        availability contract: None means scan-path fallback."""
        with self._ready_window(metric_uid, start) as mw:
            if mw is None:
                return None
            self.window_hits += 1
            return DevChunks(
                chunks=[(c["ts"], c["vals"], c["sid"], c["valid"])
                        for c in mw.chunks],
                epoch=mw.epoch, series_keys=list(mw.keys),
                generation=mw.generation, version=mw.version)

    # -- observability -------------------------------------------------

    def collect_stats(self, collector) -> None:
        collector.record("devwindow.points.appended", self.appended_points)
        collector.record("devwindow.points.evicted", self.evicted_points)
        collector.record("devwindow.hits", self.window_hits)
        collector.record("devwindow.misses", self.window_misses)
        collector.record("devwindow.dirty_fallbacks", self.dirty_fallbacks)
        collector.record("devwindow.upload_stalls", self.upload_stalls)
        with self._lock:
            collector.record("devwindow.metrics", len(self._metrics))
            collector.record(
                "devwindow.points.resident",
                sum(mw.device_points for mw in self._metrics.values()))
