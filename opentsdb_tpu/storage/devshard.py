"""Mesh-sharded device-resident hot set — the serving fleet's window.

``DeviceWindow`` (devstore.py) keeps one process's recent ingest
resident on ONE device; its capacity is that chip's HBM and every
query's stage kernels run there. This module shards the same hot set
across the mesh on the series axis: K logical shards, each a
``DeviceWindow`` pinned to one mesh device (``device=`` in devstore),
series routed by the fleet-wide identity hash
(``storage.sstable.series_hash`` — the same hash the storage sharder,
the TSST3 blooms, and the serve router use). Capacity and dashboard
throughput then scale with mesh width instead of per-process host RAM:
each shard's stage kernel folds only its own series' chunks ON ITS OWN
DEVICE (committed inputs pin the jit execution), and only the tiny
[S_shard, B] grids travel to device 0 for the group combine.

Logical vs physical: ``n_shards`` may exceed the device count (shards
round-robin over the devices), so the tier-1 suite exercises the whole
sharded path — routing, per-shard eviction independence, reshard,
crash recovery — on a single CPU device.

Exactness: unchanged from devstore. A series lives in EXACTLY one
shard, each shard's window keeps the per-series exact-coverage
contract (monotone appends, complete_from, sticky dirty marks), so the
union serves a query iff every shard that owns any of the metric's
series can serve it; otherwise the whole window declines to the scan
path. Per-shard eviction is independent by construction — a shard
evicting its oldest chunk never touches a neighbor device's columns.

RESHARD (mesh grows/shrinks, ownership handoff) is live and follows
the coherent-swap discipline of ``ReadOnlyRollupTier.refresh``: build
the NEW shard set complete off to the side, swap whole under the lock.

1. gate: journaling on, every old shard quiesced (staged batches
   uploaded, in-flight uploads drained) — appends block only for this
   drain; from here ingest dual-writes (old set keeps serving exact
   answers, the journal feeds the new set);
2. rebuild: device columns fetched back per shard, split per series,
   redistributed by ``series_hash % n_new`` into freshly pinned
   windows (coverage floors carried: a series' new ``complete_from``
   is the max over its metric's old shards);
3. drain: journal replayed in passes until nearly empty, then a final
   gated pass, the ``mesh.reshard.commit`` faultpoint, and the
   atomic swap (generation bump invalidates every derived cache).

A query that snapshotted the old shard list mid-reshard finishes on
the old set — pre-swap answers are complete, never a mix of old and
new columns. A crash at the commit point loses only device state
(the hot set is a cache); reopen + warm rebuilds a coherent set from
storage, which the crash-matrix ``meshreshard`` scenario proves.
"""

from __future__ import annotations

import threading
import time as _time
from typing import NamedTuple

import numpy as np

from ..fault import faultpoints
from .devstore import DeviceWindow
from .sstable import series_hash


class ShardedDevChunks(NamedTuple):
    """One metric's resident window across every shard, ready for the
    per-shard stage kernels. Row order of the combined result is shard
    order: combined sid = shard_starts[i] + local sid."""
    shards: list            # per-shard DevChunks | None (no series routed)
    shard_devices: list     # per-shard device (or None = default)
    shard_starts: list      # combined-sid offset of each shard's rows
    series_keys: list       # combined directory (concat in shard order)
    generation: tuple       # (reshard_gen, per-shard generations)
    version: tuple          # (reshard_gen, per-shard (instance, version))


class ShardedDeviceWindow:
    """Series-hash-sharded fleet of device-pinned ``DeviceWindow``s."""

    _instances = 0

    def __init__(self, devices=None, n_shards: int | None = None,
                 staging_points: int = 1 << 20,
                 max_points: int = 1 << 26,
                 background: bool = True,
                 stall_timeout: float = 60.0) -> None:
        if devices is None:
            devices = [None]
        devices = list(devices)
        if n_shards is None:
            n_shards = max(len(devices), 1)
        ShardedDeviceWindow._instances += 1
        self.instance_id = ("sharded", ShardedDeviceWindow._instances)
        self.staging_points = staging_points
        self.max_points = max_points
        self.background = background
        self.stall_timeout = stall_timeout
        self._lock = threading.RLock()
        self._devices = devices
        self._shards = self._build_shards(n_shards, devices)
        # Which shards have seen each metric: lets chunk_columns skip
        # shards with nothing routed to them (a DeviceWindow miss there
        # would otherwise veto the whole window).
        self._metric_shards: dict[bytes, set[int]] = {}
        # Sticky fleet-level dirty marks: survive reshard (a reshard
        # must never resurrect a window storage has diverged from).
        self._dirty_metrics: set[bytes] = set()
        # Dual-write journal, non-None only while a reshard is running.
        self._journal: list | None = None
        self.generation = 0          # bumps on every committed reshard
        # stats
        self.reshard_count = 0
        self.reshard_ms = 0.0        # last committed reshard, wall ms
        self.dirty_fallbacks = 0
        self.window_hits = 0
        self.window_misses = 0

    def _build_shards(self, n_shards: int, devices) -> list[DeviceWindow]:
        per = max(self.max_points // max(n_shards, 1), 1)
        return [DeviceWindow(staging_points=self.staging_points,
                             max_points=per,
                             background=self.background,
                             stall_timeout=self.stall_timeout,
                             device=devices[i % len(devices)]
                             if devices else None)
                for i in range(n_shards)]

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    def shard_of(self, series_key: bytes) -> int:
        return series_hash(series_key) % len(self._shards)

    # -- ingest side ---------------------------------------------------

    def append(self, metric_uid: bytes, series_key: bytes,
               timestamps: np.ndarray, values: np.ndarray) -> None:
        if len(timestamps) == 0:
            return
        with self._lock:
            if metric_uid in self._dirty_metrics:
                return
            idx = series_hash(series_key) % len(self._shards)
            shard = self._shards[idx]
            self._metric_shards.setdefault(metric_uid, set()).add(idx)
            if self._journal is not None:
                # Journal COPIES under the gate lock: the record must be
                # immutable (replay happens later) and ordered with the
                # reshard's snapshot boundary.
                self._journal.append(
                    (metric_uid, series_key,
                     np.array(timestamps, np.int64),
                     np.array(values, np.float32)))
            # Delegate under the fleet lock: the reshard gate's
            # quiesce+snapshot must never interleave with a half-landed
            # append (staged in neither the snapshot nor the journal).
            shard.append(metric_uid, series_key, timestamps, values)

    def flush(self) -> None:
        with self._lock:
            shards = list(self._shards)
        for s in shards:
            s.flush()

    def invalidate(self, metric_uid: bytes | None = None) -> None:
        with self._lock:
            if metric_uid is None:
                self._dirty_metrics.update(self._metric_shards)
            else:
                self._dirty_metrics.add(metric_uid)
            shards = list(self._shards)
        for s in shards:
            s.invalidate(metric_uid)

    # -- query side ----------------------------------------------------

    def chunk_columns(self, metric_uid: bytes, start: int,
                      end: int) -> ShardedDevChunks | None:
        """The metric's resident columns across every owning shard when
        ALL of them exactly cover [start, end]; None = scan fallback.
        Snapshot-consistent under reshard: the shard list is captured
        once, so a concurrent swap leaves this query on the complete
        pre-swap set, never a mix."""
        with self._lock:
            if metric_uid in self._dirty_metrics:
                self.dirty_fallbacks += 1
                return None
            shards = list(self._shards)
            gen = self.generation
            owners = sorted(self._metric_shards.get(metric_uid, ()))
        if not owners:
            self.window_misses += 1
            return None
        per = [None] * len(shards)
        for i in owners:
            if i >= len(shards):     # mapping raced a shrink; decline
                self.window_misses += 1
                return None
            cols = shards[i].chunk_columns(metric_uid, start, end)
            if cols is None:
                # An owning shard declined (dirty / evicted coverage /
                # slow upload): a partial union would be WRONG, so the
                # whole window falls back to the scan path.
                self.window_misses += 1
                return None
            per[i] = cols
        starts, keys = [], []
        for cols in per:
            starts.append(len(keys))
            if cols is not None:
                keys.extend(cols.series_keys)
        self.window_hits += 1
        return ShardedDevChunks(
            shards=per,
            shard_devices=[s.device for s in shards],
            shard_starts=starts,
            series_keys=keys,
            generation=(gen, tuple(
                c.generation if c is not None else -1 for c in per)),
            version=(gen, tuple(
                (shards[i].instance_id, per[i].version)
                if per[i] is not None else (0, -1)
                for i in range(len(shards)))))

    # -- reshard -------------------------------------------------------

    def reshard(self, n_shards: int | None = None,
                devices=None) -> dict:
        """Live redistribution of the hot set over a new shard count /
        device list. Returns a stats dict. Serialized: concurrent calls
        run back to back."""
        t0 = _time.monotonic()
        if devices is None:
            devices = self._devices
        devices = list(devices) if devices else [None]
        if n_shards is None:
            n_shards = max(len(devices), 1)
        # Phase 1 — gate: journaling on + old set fully materialized
        # into device chunks (appends block only for this drain).
        with self._lock:
            if self._journal is not None:
                raise RuntimeError("reshard already in progress")
            self._journal = []
            old = list(self._shards)
            for s in old:
                s.quiesce()
            snaps = [s._snapshot_metrics() for s in old]
            dirty = set(self._dirty_metrics)
        # Phase 2 — rebuild off-gate (old set serves, journal fills).
        new = self._build_shards(n_shards, devices)
        new_owner: dict[bytes, set[int]] = {}
        try:
            for uid in sorted({u for sn in snaps for u in sn}):
                if uid in dirty or any(
                        sn.get(uid, {}).get("dirty") for sn in snaps):
                    dirty.add(uid)
                    continue
                floor = None
                for sn in snaps:
                    cf = sn.get(uid, {}).get("complete_from")
                    if cf is not None:
                        floor = cf if floor is None else max(floor, cf)
                per_series = self._split_series(
                    [sn[uid] for sn in snaps if uid in sn])
                for key, (ts, vals) in per_series.items():
                    j = series_hash(key) % n_shards
                    new[j].append(uid, key, ts, vals)
                    new_owner.setdefault(uid, set()).add(j)
                if floor is not None:
                    for j in new_owner.get(uid, ()):
                        new[j].set_complete_from(uid, floor)
            # Phase 3 — drain the journal in passes, then the gated
            # commit. Each pass replays what accumulated while the
            # previous one ran; the final (small) remainder replays
            # under the lock so the swap sees a complete new set.
            while True:
                with self._lock:
                    batch, self._journal = self._journal, []
                if not batch:
                    break
                self._replay(batch, new, n_shards, new_owner, dirty)
                if len(batch) < 64:
                    break
            with self._lock:
                self._replay(self._journal, new, n_shards, new_owner,
                             dirty)
                self._journal = None
                # Crash here = SIGKILL at the commit: the swap never
                # happens, the old set keeps serving (stale-but-
                # complete), and a restart rebuilds from storage.
                faultpoints.fire("mesh.reshard.commit")
                self._shards = new
                self._devices = devices
                self._metric_shards = new_owner
                self._dirty_metrics = dirty
                self.generation += 1
                self.reshard_count += 1
                self.reshard_ms = (_time.monotonic() - t0) * 1e3
                return {"n_shards": n_shards,
                        "generation": self.generation,
                        "metrics": len(new_owner),
                        "dirty_metrics": len(dirty),
                        "reshard_ms": round(self.reshard_ms, 2)}
        except BaseException:
            with self._lock:
                self._journal = None     # abort: old set stays live
            raise

    @staticmethod
    def _split_series(metric_snaps: list[dict]) -> dict:
        """Per-series (abs_ts, vals) in append order from the refs-only
        snapshots of one metric across its old shards. A series lives
        in exactly one shard, and within a shard its points are in time
        order across seq-ordered chunks, so per-key concatenation
        preserves the strict-monotone append contract."""
        out: dict[bytes, list] = {}
        for sn in metric_snaps:
            keys = sn["keys"]
            epoch = sn["epoch"]
            segs: dict[int, list] = {}
            for ch in sn["chunks"]:
                v = np.asarray(ch["valid"])
                sid = np.asarray(ch["sid"])[v]
                ts = np.asarray(ch["ts"])[v].astype(np.int64) + epoch
                vals = np.asarray(ch["vals"])[v]
                order = np.argsort(sid, kind="stable")
                sid_o, ts_o, vals_o = sid[order], ts[order], vals[order]
                bounds = np.searchsorted(
                    sid_o, np.arange(len(keys) + 1))
                for s in range(len(keys)):
                    lo, hi = bounds[s], bounds[s + 1]
                    if hi > lo:
                        segs.setdefault(s, []).append(
                            (ts_o[lo:hi], vals_o[lo:hi]))
            for s, parts in segs.items():
                ts_cat = np.concatenate([p[0] for p in parts])
                vl_cat = np.concatenate([p[1] for p in parts])
                out[keys[s]] = (ts_cat, vl_cat)
        return out

    @staticmethod
    def _replay(batch, new, n_shards, new_owner, dirty) -> None:
        for uid, key, ts, vals in batch:
            if uid in dirty:
                continue
            j = series_hash(key) % n_shards
            new[j].append(uid, key, ts, vals)
            new_owner.setdefault(uid, set()).add(j)

    # -- observability -------------------------------------------------

    def resident_points(self) -> int:
        with self._lock:
            shards = list(self._shards)
        total = 0
        for s in shards:
            with s._lock:
                total += sum(mw.device_points
                             for mw in s._metrics.values())
        return total

    def collect_stats(self, collector) -> None:
        with self._lock:
            shards = list(self._shards)
        # Point/eviction/stall counters sum across shards; hit/miss/
        # dirty counters are FLEET-level (one query = one verdict, not
        # one per owning shard).
        agg = {"devwindow.points.appended": 0,
               "devwindow.points.evicted": 0,
               "devwindow.upload_stalls": 0,
               "devwindow.metrics": 0,
               "devwindow.points.resident": 0}

        class _Sink:
            def record(self, name, value):
                if name in agg:
                    agg[name] += value
        sink = _Sink()
        for s in shards:
            s.collect_stats(sink)
        for name, value in agg.items():
            collector.record(name, value)
        collector.record("devwindow.hits", self.window_hits)
        collector.record("devwindow.misses", self.window_misses)
        collector.record("devwindow.dirty_fallbacks",
                         self.dirty_fallbacks)
        collector.record("mesh.resident.points",
                         agg["devwindow.points.resident"])
        collector.record("mesh.resident.shards", len(shards))
        collector.record("mesh.resident.reshard.count",
                         self.reshard_count)
        collector.record("mesh.resident.reshard_ms",
                         round(self.reshard_ms, 2))
