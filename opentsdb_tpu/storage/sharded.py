"""Series-sharded multi-writer storage: N independent KVStore shards.

The reference gets horizontal write scaling for free from HBase region
partitioning on the metric-first row key (reference
src/core/IncomingDataPoints.java); this engine funneled every write
through one ``MemKVStore`` — one memtable lock, one WAL, one sstable
generation tier — so at the 1B+ scale the checkpoint spill/merge of the
WHOLE history became the single largest ingest stall
(``BENCH_SCALE_2000M.json``: 807 s of a 1207 s wall in
checkpoint.spill + checkpoint.wait + kv.put_batch, with single 177 s
pauses when a tiered collapse landed).

``ShardedKVStore`` partitions rows by a stable hash of the row key's
SERIES identity (metric UID + tag UID pairs — the base-time bytes are
excluded, so every row-hour of one series lands in the same shard, the
moral analog of the reference's salt+metric region prefix) into N
independent ``MemKVStore`` shards, each with its own memtable, WAL, and
sstable generation tier under ``<dir>/shard-<i>/``:

- **Ingest** routes columnar batches to shards WITHOUT re-encoding:
  ``add_batch`` sends one series per ``put_many_columnar`` call, so the
  whole key blob flows to a single shard (and into its columnar WAL
  record) untouched; mixed batches split into per-shard sub-blobs by
  numpy row indexing, still columnar.
- **Checkpoint** runs every shard's 3-phase spill in a bounded worker
  pool: each freeze is its own brief per-shard lock, the phase-2
  sstable writes overlap, and — because each shard holds ~1/N of the
  history and the generation caps are STAGGERED across shards (shard i
  caps at base+i, so size-tiered collapses fire on different
  checkpoints) — the worst-case mid-ingest pause becomes the largest
  single *shard's* merge instead of the whole history's.
- **Reads** fan a scan out across shards and merge the ordered
  per-shard iterators (keys are disjoint across shards by routing
  determinism, so the merge is a pure interleave); gets/atomics route
  point-wise.

Durability/consistency model: each shard is exactly a ``MemKVStore``
(crash-replay per shard WAL, per-shard manifest, per-shard flock); the
shard count and routing parameters are pinned by an atomically-written
``SHARDS.json`` at the store root, and reopening with a different
count is a hard error (rows would silently route to the wrong shard).
There is no cross-shard atomic cut: a checkpoint freezes shards a few
microseconds apart and a crash recovers each shard to its own last
durable record — the same weak cross-row guarantees one HBase region
server gives relative to another.
"""

from __future__ import annotations

import heapq
import json
import os
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import numpy as np

from opentsdb_tpu.core.const import TIMESTAMP_BYTES, UID_WIDTH
from opentsdb_tpu.core.errors import PleaseThrottleError
from opentsdb_tpu.fault import faultpoints as _fp
from opentsdb_tpu.obs import trace as _trace
from opentsdb_tpu.obs.registry import METRICS as _metrics
from opentsdb_tpu.storage.kv import Cell, KVStore, MemKVStore

MANIFEST_NAME = "SHARDS.json"

# Byte range of the row key holding the base time (excluded from the
# routing hash so a series never straddles shards).
_TS_LO = UID_WIDTH
_TS_HI = UID_WIDTH + TIMESTAMP_BYTES


def manifest_path(dir_path: str) -> str:
    return os.path.join(dir_path, MANIFEST_NAME)


class ShardedKVStore(KVStore):
    """N series-hash-partitioned MemKVStore shards behind one KVStore.

    ``dir_path=None`` builds an in-memory (non-persistent) sharded
    store — no WALs, no manifest — for benchmarks and tests.

    ``partial_existed`` semantics differ from MemKVStore on a mid-batch
    ``PleaseThrottleError``: cells route to shards out of input order,
    so the attached list is FULL-LENGTH (one flag per input cell) with
    ``False`` for cells that did not apply, rather than an
    applied-prefix. Callers that use the flags to queue compactions
    (the only current consumer) stay exact: every ``True`` cell
    applied onto an existing row.
    """

    def __init__(self, dir_path: str | None, shards: int | None = None,
                 data_table: str = "tsdb",
                 throttle_rows: int | None = None, fsync: bool = False,
                 read_only: bool = False,
                 spill_workers: int | None = None,
                 writer_epoch: int | None = None,
                 epoch_guard=None) -> None:
        self._dir = dir_path
        self.read_only = read_only
        self.data_table = data_table
        # Cluster write tier: ONE epoch (EPOCH.json at the store root,
        # next to SHARDS.json) covers all shards — they live and die
        # with the writer process as a unit — and one guard is shared
        # across every shard's mutation path.
        self.writer_epoch = writer_epoch
        self.epoch_guard = epoch_guard
        # Whole shards dropped from a fan-out by the series-hint
        # routing prefilter (scan_raw).
        self.bloom_shards_skipped = 0
        created_manifest = False
        if dir_path is not None:
            man = manifest_path(dir_path)
            if os.path.exists(man):
                with open(man) as f:
                    rec = json.load(f)
                n_disk = int(rec["shards"])
                if shards is not None and shards != n_disk:
                    raise ValueError(
                        f"shard-count mismatch: store at {dir_path!r} "
                        f"was created with {n_disk} shards, reopen "
                        f"requested {shards} (rows would route to the "
                        f"wrong shard; re-shard via export/import)")
                if rec.get("data_table", data_table) != data_table:
                    raise ValueError(
                        f"data-table mismatch: store at {dir_path!r} "
                        f"routes table {rec['data_table']!r} by series, "
                        f"reopen requested {data_table!r}")
                # Routing parameters are load-bearing exactly like the
                # count: a build whose key layout hashes different
                # byte ranges would silently route point ops to the
                # wrong shard (reads come back empty, writes diverge).
                if rec.get("version", 1) != 1 or list(
                        rec.get("series_bytes_excluded",
                                [_TS_LO, _TS_HI])) != [_TS_LO, _TS_HI]:
                    raise ValueError(
                        f"routing mismatch: store at {dir_path!r} was "
                        f"created with manifest version "
                        f"{rec.get('version')} / series bytes "
                        f"{rec.get('series_bytes_excluded')}, this "
                        f"build routes with v1 / {[_TS_LO, _TS_HI]}")
                n = n_disk
            else:
                if read_only:
                    raise FileNotFoundError(
                        f"no {MANIFEST_NAME} at {dir_path!r}: a replica "
                        f"cannot create a sharded store")
                if shards is None:
                    raise ValueError(
                        f"no {MANIFEST_NAME} at {dir_path!r} and no "
                        f"shard count given")
                n = shards
                self._write_manifest(dir_path, n, data_table)
                created_manifest = True
        else:
            if shards is None:
                raise ValueError("in-memory sharded store needs an "
                                 "explicit shard count")
            n = shards
        if n < 1:
            raise ValueError(f"shard count must be >= 1, got {n}")
        self.shard_count = n
        self._spill_workers = (spill_workers if spill_workers
                               else min(n, max(os.cpu_count() or 2, 2)))
        # Sketch-snapshot naming root (TSDB._sketch_path): the snapshot
        # is store-global (folded above the shard layer), so it lives
        # beside the manifest, not inside any shard.
        self._wal_path = (os.path.join(dir_path, "store")
                         if dir_path else None)
        per_throttle = (None if throttle_rows is None
                        else max((throttle_rows + n - 1) // n, 1))
        self.shards: list[MemKVStore] = []
        try:
            for i in range(n):
                wal = (os.path.join(dir_path, f"shard-{i}", "wal")
                       if dir_path else None)
                # Staggered generation caps (base + i, bounded): every
                # shard receives ~1/N of each spill, so with EQUAL caps
                # all shards would hit the size-tiered collapse on the
                # SAME checkpoint and the pauses would re-align into
                # one full-history-sized stall. Distinct caps offset
                # each shard's collapse schedule by whole checkpoints.
                self.shards.append(MemKVStore(
                    wal_path=wal, throttle_rows=per_throttle,
                    fsync=fsync, read_only=read_only,
                    max_generations=(MemKVStore._MAX_GENERATIONS
                                     + i % min(n, 8)),
                    writer_epoch=writer_epoch,
                    epoch_guard=epoch_guard))
        except BaseException:
            for s in self.shards:
                try:
                    s.close()
                except Exception:
                    pass
            if created_manifest:
                # First-time creation failed (stale shard lock, ENOSPC
                # mid-open): remove the manifest we just wrote, or it
                # would permanently pin a shard count for a store that
                # holds no data and hard-error every retry with a
                # different N.
                try:
                    os.unlink(manifest_path(dir_path))
                except OSError:
                    pass
            raise

    @staticmethod
    def _write_manifest(dir_path: str, n: int, data_table: str) -> None:
        """Atomically pin the shard layout (tmp + rename + dir fsync,
        the same durability contract as the per-shard manifests)."""
        os.makedirs(dir_path, exist_ok=True)
        man = manifest_path(dir_path)
        tmp = man + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "shards": n,
                       "data_table": data_table,
                       "series_bytes_excluded": [_TS_LO, _TS_HI]}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, man)
        dfd = os.open(dir_path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    # -- routing ----------------------------------------------------------

    def _route(self, table: str, key: bytes) -> int:
        """Stable shard index for a key. Data-table keys hash their
        series bytes (metric UID + tag pairs, base time excluded) so
        all hours of a series co-locate; everything else (UID table,
        short keys) hashes the whole key. crc32, not hash(): routing
        must be identical across processes and restarts."""
        if self.shard_count == 1:
            return 0
        if table == self.data_table and len(key) >= _TS_HI:
            h = zlib.crc32(key[_TS_HI:], zlib.crc32(key[:_TS_LO]))
        else:
            h = zlib.crc32(key)
        return h % self.shard_count

    # -- point ops (route + delegate) -------------------------------------

    def get(self, table: str, key: bytes,
            family: bytes | None = None) -> list[Cell]:
        return self.shards[self._route(table, key)].get(table, key, family)

    def has_row(self, table: str, key: bytes) -> bool:
        return self.shards[self._route(table, key)].has_row(table, key)

    def cell_count(self, table: str, key: bytes) -> int:
        return self.shards[self._route(table, key)].cell_count(table, key)

    def row_count(self, table: str) -> int:
        return sum(s.row_count(table) for s in self.shards)

    def put(self, table: str, key: bytes, family: bytes, qualifier: bytes,
            value: bytes, durable: bool = True) -> None:
        self.shards[self._route(table, key)].put(
            table, key, family, qualifier, value, durable=durable)

    def delete(self, table: str, key: bytes, family: bytes,
               qualifiers: list[bytes]) -> None:
        self.shards[self._route(table, key)].delete(
            table, key, family, qualifiers)

    def delete_row(self, table: str, key: bytes) -> None:
        self.shards[self._route(table, key)].delete_row(table, key)

    def atomic_increment(self, table: str, key: bytes, family: bytes,
                         qualifier: bytes, amount: int = 1) -> int:
        return self.shards[self._route(table, key)].atomic_increment(
            table, key, family, qualifier, amount)

    def compare_and_set(self, table: str, key: bytes, family: bytes,
                        qualifier: bytes, expected: bytes | None,
                        value: bytes) -> bool:
        return self.shards[self._route(table, key)].compare_and_set(
            table, key, family, qualifier, expected, value)

    # -- batched writes ----------------------------------------------------

    def put_many(self, table: str, family: bytes,
                 cells: list[tuple[bytes, bytes, bytes]],
                 durable: bool = True, sync: bool = True) -> list[bool]:
        if self.shard_count == 1:
            return self.shards[0].put_many(table, family, cells,
                                           durable=durable, sync=sync)
        by_shard: dict[int, list[int]] = {}
        for i, (key, _, _) in enumerate(cells):
            by_shard.setdefault(self._route(table, key), []).append(i)
        existed = [False] * len(cells)
        for si in sorted(by_shard):
            idx = by_shard[si]
            sub = [cells[i] for i in idx]
            try:
                flags = self.shards[si].put_many(table, family, sub,
                                                 durable=durable,
                                                 sync=sync)
            except PleaseThrottleError as e:
                part = getattr(e, "partial_existed", [])
                for i, f in zip(idx, part):
                    existed[i] = f
                e.partial_existed = existed  # full-length (see class doc)
                raise
            for i, f in zip(idx, flags):
                existed[i] = f
        return existed

    def put_many_columnar(self, table: str, family: bytes,
                          key_blob: bytes, key_len: int,
                          quals: list[bytes], vals: list[bytes],
                          durable: bool = True,
                          sync: bool = True) -> list[bool]:
        n = len(quals)
        if len(vals) != n or len(key_blob) != n * key_len:
            raise ValueError(
                f"columnar batch mismatch: {len(key_blob)} key bytes, "
                f"key_len {key_len}, {n} quals, {len(vals)} vals")
        if n == 0:
            return []
        if self.shard_count == 1:
            return self.shards[0].put_many_columnar(
                table, family, key_blob, key_len, quals, vals,
                durable=durable, sync=sync)
        L = key_len
        # Same-series fast path — the add_batch hot shape: one series
        # per batch, keys differing only in their base-time bytes. One
        # vectorized equality check, one route, and the key blob flows
        # through to the shard's columnar WAL record UNCHANGED.
        if table == self.data_table and L >= _TS_HI:
            mat = np.frombuffer(key_blob, np.uint8).reshape(n, L)
            same = bool(
                (mat[:, :_TS_LO] == mat[0, :_TS_LO]).all()
                and (mat[:, _TS_HI:] == mat[0, _TS_HI:]).all())
        else:
            mat = np.frombuffer(key_blob, np.uint8).reshape(n, L)
            first = key_blob[:L]
            same = n == 1 or key_blob == first * n
        if same:
            return self.shards[self._route(table, key_blob[:L])] \
                .put_many_columnar(table, family, key_blob, L, quals,
                                   vals, durable=durable, sync=sync)
        # Mixed batch: route per key, regroup into per-shard sub-blobs
        # (numpy row gather keeps them columnar — no per-cell tuples).
        routes = np.fromiter(
            (self._route(table, key_blob[i * L:(i + 1) * L])
             for i in range(n)), np.int64, n)
        existed = [False] * n
        for si in np.unique(routes):
            idx = np.flatnonzero(routes == si)
            sub_blob = mat[idx].tobytes()
            sub_q = [quals[i] for i in idx]
            sub_v = [vals[i] for i in idx]
            try:
                flags = self.shards[int(si)].put_many_columnar(
                    table, family, sub_blob, L, sub_q, sub_v,
                    durable=durable, sync=sync)
            except PleaseThrottleError as e:
                part = getattr(e, "partial_existed", [])
                for i, f in zip(idx.tolist(), part):
                    existed[i] = f
                e.partial_existed = existed
                raise
            for i, f in zip(idx.tolist(), flags):
                existed[i] = f
        return existed

    # -- scans (cross-shard fan-in) ----------------------------------------

    def scan(self, table: str, start: bytes, stop: bytes,
             family: bytes | None = None,
             key_regexp: bytes | None = None) -> Iterator[list[Cell]]:
        """Ordered fan-in: merge every shard's already-sorted scan.
        Routing determinism makes shard key sets disjoint, so the merge
        is a pure interleave (no cross-shard row merging). Snapshot
        semantics are per shard — exactly the weak cross-region
        guarantees an HBase multi-region scan gives."""
        its = [s.scan(table, start, stop, family=family,
                      key_regexp=key_regexp) for s in self.shards]
        return heapq.merge(*its, key=lambda cells: cells[0].key)

    def scan_raw(self, table: str, start: bytes, stop: bytes,
                 family: bytes | None = None,
                 key_regexp: bytes | None = None,
                 series_hint=None,
                 ) -> Iterator[tuple[bytes, list[tuple[bytes, bytes]]]]:
        """Fan-in scan; with a ``series_hint`` (uint64 series-identity
        hashes, a superset of the series the caller keeps) the fan-out
        first drops shards no candidate routes to — the routing hash
        IS the identity hash (sstable.series_hash), so ``h % N`` is
        exact, not probabilistic — then each shard's own series blooms
        prune generations."""
        shards = self.shards
        if (series_hint is not None and len(series_hint)
                and table == self.data_table and self.shard_count > 1):
            live = np.unique(series_hint
                             % np.uint64(self.shard_count)).tolist()
            if len(live) < self.shard_count:
                self.bloom_shards_skipped += \
                    self.shard_count - len(live)
                shards = [self.shards[int(i)] for i in live]
        its = [s.scan_raw(table, start, stop, family=family,
                          key_regexp=key_regexp,
                          series_hint=series_hint) for s in shards]
        parent = _trace.current_span()
        if parent is not None:
            # Per-shard fan-out spans: each shard's span accumulates
            # only the time spent pulling from THAT shard's iterator
            # (the heap merge interleaves them), attached to the span
            # current at fan-out time when its iterator is exhausted.
            idx_of = {id(s): i for i, s in enumerate(self.shards)}
            its = [_trace.timed_iter(it, parent, "shard.scan",
                                     {"shard": idx_of[id(s)]})
                   for it, s in zip(its, shards)]
            if len(shards) < self.shard_count:
                parent.tags["shards_skipped"] = (
                    self.shard_count - len(shards))
        return heapq.merge(*its, key=lambda row: row[0])

    # -- memtable introspection (sketch recovery re-fold) ------------------

    def memtable_keys(self, table: str) -> list[bytes]:
        out: list[bytes] = []
        for s in self.shards:
            out.extend(s.memtable_keys(table))
        return out

    def memtable_row_counts(self, table: str) -> list[int]:
        """Live-memtable row count per shard (the /stats gauge)."""
        return [s.memtable_row_counts(table)[0] for s in self.shards]

    @property
    def sstable_codec(self) -> str:
        return self.shards[0].sstable_codec if self.shards else "none"

    @sstable_codec.setter
    def sstable_codec(self, codec: str) -> None:
        for s in self.shards:
            s.sstable_codec = codec

    @property
    def wal_group_ms(self) -> float:
        return self.shards[0].wal_group_ms if self.shards else 0.0

    @wal_group_ms.setter
    def wal_group_ms(self, ms: float) -> None:
        for s in self.shards:
            s.wal_group_ms = ms

    def wal_barrier(self, ticket: int | None = None) -> None:
        """Group-commit barrier across every shard (per-shard tickets
        are not comparable store-wide, so the fan-out always waits for
        each shard's own current watermark)."""
        for s in self.shards:
            s.wal_barrier()

    def sstable_format_bytes(self) -> dict[int, int]:
        out: dict[int, int] = {}
        for s in self.shards:
            for fmt, n in s.sstable_format_bytes().items():
                out[fmt] = out.get(fmt, 0) + n
        return out

    def compress_stats(self) -> tuple[int, int]:
        raw = enc = 0
        for s in self.shards:
            r, e = s.compress_stats()
            raw += r
            enc += e
        return raw, enc

    def encoded_range(self, table: str, start: bytes,
                      stop: bytes | None):
        """Per-shard encoded_range fan-in (see MemKVStore): shards are
        key-disjoint by the series routing, so the union of per-shard
        disjoint spans is disjoint. None if any shard declines."""
        out = []
        for s in self.shards:
            got = s.encoded_range(table, start, stop)
            if got is None:
                return None
            out.extend(got)
        return out

    def pending_keys(self, table: str) -> list[bytes]:
        out: list[bytes] = []
        for s in self.shards:
            out.extend(s.pending_keys(table))
        return out

    def peek_spill_keys(self) -> dict[str, list[bytes]]:
        out: dict[str, list[bytes]] = {}
        for s in self.shards:
            for name, ks in s.peek_spill_keys().items():
                out.setdefault(name, []).extend(ks)
        return out

    def take_spill_keys(self) -> dict[str, list[bytes]]:
        out: dict[str, list[bytes]] = {}
        for s in self.shards:
            for name, ks in s.take_spill_keys().items():
                out.setdefault(name, []).extend(ks)
        return out

    @property
    def mutation_seq(self) -> int:
        return sum(s.mutation_seq for s in self.shards)

    @property
    def mutation_seqs(self) -> tuple[int, ...]:
        """Per-shard mutation sequence vector: lets consumers
        revalidate per shard instead of treating one put anywhere as
        invalidating everything (the summed ``mutation_seq`` above)."""
        return tuple(s.mutation_seq for s in self.shards)

    def dirty_bases(self, table: str) -> np.ndarray:
        """Union of every shard's incrementally-maintained dirty-base
        set (see MemKVStore.dirty_bases), sorted unique."""
        arrs = [a for a in (s.dirty_bases(table) for s in self.shards)
                if len(a)]
        if not arrs:
            return np.empty(0, np.int64)
        if len(arrs) == 1:
            return arrs[0]
        return np.unique(np.concatenate(arrs))

    def chunk_state(self, table: str, lo: int, hi: int):
        """Per-shard fragment-cache validation vectors (see
        MemKVStore.chunk_state); ``dirty`` is the OR across shards —
        a fan-in fragment merges every shard's rows, so one dirty
        shard taints the chunk."""
        epochs: list[int] = []
        floors: list[int] = []
        marks: list[int] = []
        dirty = False
        for s in self.shards:
            e, f, m, d = s.chunk_state(table, lo, hi)
            epochs.extend(e)
            floors.extend(f)
            marks.extend(m)
            dirty = dirty or d
        return tuple(epochs), tuple(floors), tuple(marks), dirty

    @property
    def record_spill_keys(self) -> bool:
        return all(s.record_spill_keys for s in self.shards)

    @record_spill_keys.setter
    def record_spill_keys(self, value: bool) -> None:
        for s in self.shards:
            s.record_spill_keys = value

    @property
    def delete_hook(self):
        return self.shards[0].delete_hook if self.shards else None

    @delete_hook.setter
    def delete_hook(self, fn) -> None:
        for s in self.shards:
            s.delete_hook = fn

    @property
    def spilled(self) -> bool:
        return any(s.spilled for s in self.shards)

    def memtable_cells(self, table: str, key: bytes,
                       family: bytes | None = None) -> list[Cell]:
        return self.shards[self._route(table, key)].memtable_cells(
            table, key, family)

    # -- lifecycle ---------------------------------------------------------

    def ensure_table(self, table: str) -> None:
        for s in self.shards:
            s.ensure_table(table)

    def flush(self) -> None:
        for s in self.shards:
            s.flush()

    def checkpoint(self) -> int:
        """Checkpoint every shard, phase-2 spills overlapped in a
        bounded worker pool. Each shard's freeze/swap is its own brief
        lock (ingest to OTHER shards never waits even for that), and
        the expensive merges run concurrently — the worst-case pause a
        writer can observe is one shard's largest merge, ~1/N of the
        single-store history collapse. Returns total rows spilled."""
        if self.read_only:
            return 0
        if _fp.active():
            # Fault injection armed: spill serially so the failpoint
            # hit schedule (and therefore the crash state) is
            # deterministic — which shard a count=k crash lands after
            # must not depend on pool scheduling. The per-shard join
            # site fires AFTER each shard's spill completes, so a
            # count=k crash leaves exactly k shards spilled and N-k
            # still WAL-only (the no-cross-shard-atomic-cut contract
            # the crash matrix verifies).
            total = 0
            for i, s in enumerate(self.shards):
                total += self._timed_spill(i, s)
                _fp.fire("sharded.spill.shard", self._dir)
            return total
        if self.shard_count == 1 or self._spill_workers <= 1:
            return sum(self._timed_spill(i, s)
                       for i, s in enumerate(self.shards))
        with ThreadPoolExecutor(
                max_workers=self._spill_workers,
                thread_name_prefix="shard-spill") as pool:
            return sum(pool.map(self._timed_spill,
                                range(self.shard_count), self.shards))

    @staticmethod
    def _timed_spill(i: int, shard: MemKVStore) -> int:
        """One shard's checkpoint, timed per shard (the join a writer
        can block on is one shard's largest merge — the per-shard
        timer is what makes staggered-compaction skew visible)."""
        with _metrics.timer("checkpoint.shard_spill",
                            {"shard": str(i)}).time():
            return shard.checkpoint()

    def refresh(self) -> bool:
        """Replica catch-up across every shard (each shard's refresh is
        the plain MemKVStore suffix-replay-or-rebuild)."""
        changed = False
        for s in self.shards:
            changed |= s.refresh()
        return changed

    @property
    def rebuilds(self) -> int:
        return sum(s.rebuilds for s in self.shards)

    @property
    def bloom_files_skipped(self) -> int:
        return sum(s.bloom_files_skipped for s in self.shards)

    @property
    def bloom_point_skips(self) -> int:
        return sum(s.bloom_point_skips for s in self.shards)

    @property
    def wal_swallowed_flush_errors(self) -> int:
        return sum(s.wal_swallowed_flush_errors for s in self.shards)

    def close(self) -> None:
        first: BaseException | None = None
        for s in self.shards:
            try:
                s.close()
            except BaseException as e:
                # Close EVERY shard even when one fails (a shard left
                # open wedges later reopens on its flock); surface the
                # first failure after the sweep.
                if first is None:
                    first = e
        if first is not None:
            raise first

    def _simulate_crash(self) -> None:
        """TEST HOOK: process-death simulation across all shards (see
        MemKVStore._simulate_crash)."""
        for s in self.shards:
            s._simulate_crash()

    # -- cluster promotion / demotion (cluster/) --------------------------

    def promote_writable(self, writer_epoch: int,
                         epoch_guard=None) -> None:
        """Replica promotion across every shard (each shard runs the
        MemKVStore fresh-inode takeover). A shard that fails to
        promote demotes the already-promoted prefix back — the store
        comes out all-writer or all-replica, never mixed."""
        done: list[MemKVStore] = []
        try:
            for s in self.shards:
                s.promote_writable(writer_epoch,
                                   epoch_guard=epoch_guard)
                done.append(s)
        except BaseException:
            for s in done:
                try:
                    s.demote_readonly()
                except Exception:
                    pass
            raise
        self.read_only = False
        self.writer_epoch = int(writer_epoch)
        self.epoch_guard = epoch_guard

    def demote_readonly(self) -> None:
        for s in self.shards:
            s.demote_readonly()
        self.read_only = True
        self.writer_epoch = None
        self.epoch_guard = None

    @property
    def fenced_bytes_refused(self) -> int:
        return sum(s.fenced_bytes_refused for s in self.shards)
