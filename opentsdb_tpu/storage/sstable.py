"""Immutable sorted-table file: the spill tier under the memtable.

The reference delegates at-rest storage to HBase HFiles; here a
checkpoint spills the memtable into immutable generation files, after
which the WAL is truncated — bounding both recovery time and memtable
RAM for long-running daemons (SURVEY §5.4, §7.2: "enough LSM to sustain
ingest while scans run, without rebuilding HBase").

File layout v3 (all integers big-endian):
    magic  b"TSST3"
    record*  :=  [u16 table_len][table][u16 key_len][key][u32 ncells]
                 ([u16 fam_len][fam][u16 q_len][q][u32 v_len][v])*
    records sorted by (table, key); one record per row.
    footer   :=  per table:
                   [u16 table_len][table][u32 nkeys]
                   [key_lens: nkeys x u32][offsets: nkeys x u64]
                   [keys blob]
    bloom    :=  per table (same order as footer):
                   [u16 table_len][table][u8 k][u64 nbits][bits]
                   (k == 0, nbits == 0 => table has no bloom)
    trailer  :=  [u32 ntables][u64 footer_start][u64 bloom_start]

Format v4 (magic TSST4, Config.sstable_codec="tsst4") compresses the
record section as columnar BLOCKS (opentsdb_tpu/compress/codecs.py:
delta-of-delta timestamps + XOR floats / zigzag int deltas, zlib and
verbatim fallbacks — each block self-describing):
    magic  b"TSST4"
    block*   :=  [u8 codec_tag][u32 raw_len][u32 enc_len][enc bytes]
                 where the raw bytes are a run of same-table v3-framed
                 records
    footer   :=  [u32 raw_len][u32 enc_len][zlib of the v3 footer]
    blocks   :=  [u32 nblocks][raw_starts: u64 x n][file_starts: u64 x n]
    bloom    :=  identical to v3
    trailer  :=  [u32 ntables][u64 footer_start][u64 bloom_start]
                 [u64 blocks_start][u64 raw_end]
Footer offsets are RAW-space offsets — the offset each record would
have in the equivalent v3 file — so the index, ``record_extents`` and
the copy-merge all keep working in one coordinate system; the blocks
index maps raw offsets to file offsets, and readers decode whole
blocks lazily behind a small per-file cache. Mixed-format stores are
first-class: compaction re-encodes into whatever codec the writer is
configured for, and v1-v3 generations keep opening, serving and
merging forever.

The footer exists because opening a file by scanning every row record
cost ~3 us/row in Python — 10+ s per 4.4M-row generation, paid on every
checkpoint swap-in AND at every daemon start. It opens with two numpy
frombuffer calls and one C pass over the key blob. v2 files (magic
TSST2, no bloom section, 12-byte trailer) and v1 files (magic TSST1,
no footer, full-scan index) are still read; they simply never prune.

The bloom section holds one FIXED-SIZE (BLOOM_BITS) bloom filter per
table over the SERIES IDENTITIES of its row keys — metric UID + tag
UID pairs with the base-time bytes excluded, hashed with the same
crc32 chain the series sharder routes by — so shard fan-out readers
can skip whole generations that cannot contain any requested series
(query/executor._series_hint). Fixed-size on purpose: compaction
merges blooms by OR-ing the source generations' bit arrays instead of
re-hashing millions of relocated keys (only the frozen memtable's keys
— bounded per checkpoint — are ever hashed at write time). A table
whose source blooms are missing (v1/v2 input) or whose keys are too
short to carry a series identity gets k == 0: readers treat that as
"may contain anything".

The reader mmaps the file and keeps only (key -> offset) indexes in
RAM; cell payloads are decoded lazily per row, so a spilled store
serves gets and scans without rehydrating the dataset.
"""

from __future__ import annotations

import io
import mmap
import os
import struct
import threading
import zlib
from bisect import bisect_left, bisect_right
from typing import Iterable, Iterator

import numpy as np

from opentsdb_tpu.compress import codecs as _codecs
from opentsdb_tpu.core.const import TIMESTAMP_BYTES, UID_WIDTH
from opentsdb_tpu.fault import faultpoints as _fp
from opentsdb_tpu.fault.faultpoints import fire as _fault
from opentsdb_tpu.obs.registry import METRICS as _metrics
from opentsdb_tpu.utils.nativeext import ext as _EXT

_MAGIC_V1 = b"TSST1"
_MAGIC_V2 = b"TSST2"
_MAGIC = b"TSST3"
_MAGIC_V4 = b"TSST4"
_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_TRAILER = struct.Struct(">IQ")     # v2: ntables, footer_start
_TRAILER_V3 = struct.Struct(">IQQ")  # ntables, footer_start, bloom_start
# v4: ntables, footer_start, bloom_start, blocks_start, raw_end
_TRAILER_V4 = struct.Struct(">IQQQQ")
_BLOOM_HDR = struct.Struct(">BQ")   # k, nbits
_BLOCK_HDR = struct.Struct(">BII")  # codec tag, raw_len, enc_len

# Target UNCOMPRESSED bytes per v4 block: big enough that the columnar
# codecs amortize their per-block headers and numpy passes, small
# enough that a point-get decodes a bounded unit. Runs longer than
# this split at record boundaries.
BLOCK_RAW_TARGET = 1 << 18

# Pipelined spill encode (Config.spill_encode_workers): per-block
# TSST4 encoding — including the codec's self-check round-trip — runs
# on a small shared thread pool while the spill keeps framing the next
# run, so compression stops serializing behind the memtable freeze.
# Completed blocks drain strictly in submission order, so the file
# bytes (and the sst.write.block fault/flush cadence) are identical to
# the serial encode; the pool is simply bypassed while faultpoints are
# armed so crash schedules stay deterministic. 0 workers = serial.
_ENC_LOCK = threading.Lock()
_ENC_WORKERS = 0
_ENC_POOL = None
# Encoded-but-unwritten blocks allowed in flight per writer before the
# producer blocks on the oldest (bounds memory at a few raw blocks).
_ENC_MAX_PENDING = 4


def set_encode_workers(n: int) -> None:
    """Configure the shared encode pool (make_tsdb plumbs
    Config.spill_encode_workers here). Shrinking/zeroing takes effect
    for FUTURE _BodyWriters; an existing pool is retired lazily."""
    global _ENC_WORKERS, _ENC_POOL
    n = max(int(n), 0)
    with _ENC_LOCK:
        if n == _ENC_WORKERS:
            return
        old = _ENC_POOL
        _ENC_WORKERS = n
        _ENC_POOL = None
    if old is not None:
        old.shutdown(wait=False)


def _encode_pool():
    """The lazily created shared pool, or None when disabled."""
    global _ENC_POOL
    with _ENC_LOCK:
        if _ENC_WORKERS <= 0:
            return None
        if _ENC_POOL is None:
            from concurrent.futures import ThreadPoolExecutor
            _ENC_POOL = ThreadPoolExecutor(
                max_workers=min(_ENC_WORKERS, 4),
                thread_name_prefix="sst-encode")
        return _ENC_POOL

# Whole-block decode on the read path (scan, point get, copy-merge,
# fsck round-trip audits) — p50/p95/p99 + count via /stats + /metrics.
_M_DECODE = _metrics.timer("compress.decode")
# Blocks decoded by the STREAMING range sweep (iter_rows_range):
# decoded once into a local buffer and dropped as the sweep advances,
# never inserted into the per-file point-get cache.
_M_STREAM = _metrics.counter("compress.stream_blocks")

# Series-identity byte ranges of a data row key (the base-time bytes
# between them are excluded — the sharder's routing identity,
# storage/sharded.py _route). Keys shorter than _IDENT_HI carry no
# identity and make their table bloomless.
_IDENT_LO = UID_WIDTH
_IDENT_HI = UID_WIDTH + TIMESTAMP_BYTES

# Fixed per-table bloom geometry (see module docstring: fixed so
# compaction can OR source blooms). 2^20 bits = 128 KiB per table per
# generation; at 2k series and k=3 the false-positive rate is ~2e-7,
# and a false positive only costs one needless generation scan.
# K doubles as the bloom FORMAT discriminator: the reader ignores a
# stored bloom whose (k, nbits) mismatch the current geometry, so
# files written before the k=2->3 probe fix degrade to bloomless
# (never a false negative) and age out through compaction.
BLOOM_BITS = 1 << 20
BLOOM_K = 3

# Tests set this to 2 to produce bloomless legacy-format files; the
# reader handles both forever (mixed-format stores are first-class:
# old generations age out through compaction).
WRITE_FORMAT = 3

# row := (table, key, [(family, qualifier, value), ...])
Row = tuple[str, bytes, list[tuple[bytes, bytes, bytes]]]


def series_hash(series_key: bytes) -> int:
    """The 32-bit series-identity hash shared by the shard router, the
    sstable blooms, and the executor's candidate-series hint: crc32 of
    (metric UID + tag UID pairs). For a full ROW key, hash
    key[:_IDENT_LO] and key[_IDENT_HI:] chained — crc32 chaining equals
    crc32 of the concatenation, so both spellings agree."""
    return zlib.crc32(series_key)


def _bloom_positions(h1: "np.ndarray") -> "np.ndarray":
    """[n, BLOOM_K] bit positions from 32-bit identity hashes
    (Kirsch-Mitzenmacher). h2 MUST mix h1's HIGH bits: positions are
    taken mod the power-of-two BLOOM_BITS, so an h2 derived from h1
    by multiply-add alone is a pure function of h1 mod BLOOM_BITS and
    the extra probes add no independence (the original k=2 derivation
    behaved as k=1 — ~10x the theoretical false-positive rate under
    the hostile-cardinality regime). Deriving from h1 >> 16 (odd-
    forced so the k*h2 strides cycle the whole table) restores the
    (1 - e^{-kn/m})^k envelope; 32-bit identity collisions still
    collapse pairs — a handful of false positives at million-series
    scale, never a false negative."""
    h1 = h1.astype(np.uint64)
    h2 = ((h1 >> np.uint64(16)) * np.uint64(0x9E3779B1)
          + np.uint64(0x7FEB352D)) & np.uint64(0xFFFFFFFF)
    h2 = h2 | np.uint64(1)
    ks = np.arange(BLOOM_K, dtype=np.uint64)
    return (h1[:, None] + ks * h2[:, None]) % np.uint64(BLOOM_BITS)


def _bloom_bits_from_hashes(h1s: "list[int] | np.ndarray",
                            ) -> "np.ndarray":
    """BLOOM_BITS-bit array (packed uint8, little bit order) with the
    hashes' positions set."""
    bits = np.zeros(BLOOM_BITS, bool)
    if len(h1s):
        pos = _bloom_positions(np.asarray(h1s, np.uint64))
        bits[pos.ravel().astype(np.int64)] = True
    return np.packbits(bits, bitorder="little")


def _bloom_hashes_for_keys(keys: "Iterable[bytes]") -> "list[int] | None":
    """Identity hashes for a table's row keys; None when any key is too
    short to carry a series identity (that table gets no bloom — a
    filter that cannot cover every key would hide rows)."""
    crc = zlib.crc32
    out: set[int] = set()
    for k in keys:
        if len(k) < _IDENT_HI:
            return None
        out.add(crc(k[_IDENT_HI:], crc(k[:_IDENT_LO])))
    return list(out)


def _slice_varlen(blob: bytes, lens_be: bytes) -> list[bytes]:
    if _EXT is not None:
        return _EXT.slice_varlen(blob, lens_be)
    lens = np.frombuffer(lens_be, ">u4")
    ends = np.cumsum(lens)
    starts = ends - lens
    return [blob[a:b] for a, b in zip(starts.tolist(), ends.tolist())]


class _BodyWriter:
    """The record section of a new sstable, in either format: v2/v3
    writes records straight through (byte-identical to the historical
    layout), v4 ("tsst4" codec) accumulates same-table record runs and
    flushes them as self-describing compressed blocks.

    ``write_record``/``write_run`` return the RAW-space offset of the
    written bytes — the file offset in v2/v3, the virtual uncompressed
    offset in v4 — which is what the footer indexes and
    ``record_extents`` reports, so every consumer stays in one
    coordinate system regardless of format."""

    def __init__(self, f, codec: str | None) -> None:
        self.f = f
        self.v4 = codec == "tsst4"
        magic = _MAGIC_V4 if self.v4 \
            else (_MAGIC if WRITE_FORMAT >= 3 else _MAGIC_V2)
        f.write(magic)
        self.raw_off = len(magic)
        self._chunks: list[bytes] = []
        self._offs: list[int] = []
        self._pend = 0
        self._table: str | None = None
        self.blocks: list[tuple[int, int]] = []  # (raw_start, file_start)
        # Pipelined encode (set_encode_workers): in-flight
        # (raw_start, future) pairs, drained FIFO so file bytes match
        # the serial encode exactly. None = serial (v2/v3 format, pool
        # disabled, or faultpoints armed — the crash schedules count
        # fault firings, which must happen on the spilling thread in
        # deterministic order).
        self._futs = None
        if self.v4 and not _fp.active():
            pool = _encode_pool()
            if pool is not None:
                self._pool = pool
                from collections import deque
                self._futs = deque()

    def _append(self, table: str, buf: bytes, starts) -> int:
        """Queue record bytes for the current block; returns the raw
        offset of ``buf``'s first byte. A table switch flushes BEFORE
        queueing (one table per block) and raw_off only advances here,
        so a flush's raw_start accounting is exact either way."""
        if self._table is not None and self._table != table:
            self._flush_block()
        self._table = table
        base = self._pend
        self._offs.extend(int(s) + base for s in starts)
        self._chunks.append(buf)
        self._pend += len(buf)
        off = self.raw_off
        self.raw_off += len(buf)
        if self._pend >= BLOCK_RAW_TARGET:
            self._flush_block()
        return off

    def write_record(self, table: str, rec: bytes) -> int:
        if not self.v4:
            off = self.raw_off
            self.raw_off += len(rec)
            self.f.write(rec)
            return off
        return self._append(table, rec, (0,))

    def write_run(self, table: str, buf: bytes, starts) -> int:
        """A run of verbatim record bytes with known record ``starts``
        (relative to ``buf``, first at 0) — the copy-merge's unit. v4
        splits long runs at record boundaries near BLOCK_RAW_TARGET."""
        if not self.v4:
            off = self.raw_off
            self.raw_off += len(buf)
            self.f.write(buf)
            return off
        s = np.asarray(starts, np.int64)
        off0 = None
        i = 0
        while i < len(s):
            j = int(np.searchsorted(s, s[i] + BLOCK_RAW_TARGET, "left"))
            j = max(j, i + 1)
            end = int(s[j]) if j < len(s) else len(buf)
            lo = int(s[i])
            o = self._append(table, bytes(buf[lo:end]),
                             (s[i:j] - lo).tolist())
            if off0 is None:
                off0 = o - lo
            i = j
        return off0 if off0 is not None else self.raw_off

    def _flush_block(self) -> None:
        if not self._pend:
            return
        raw = self._chunks[0] if len(self._chunks) == 1 \
            else b"".join(self._chunks)
        raw_start = self.raw_off - self._pend
        self._chunks.clear()
        self._offs, offs = [], self._offs
        self._pend = 0
        self._table = None
        if self._futs is not None:
            self._futs.append((raw_start, self._pool.submit(
                _codecs.encode_block_split, raw, offs)))
            while len(self._futs) > _ENC_MAX_PENDING:
                self._write_parts(*self._futs.popleft(), blocking=True)
            return
        self._write_parts(raw_start,
                          _codecs.encode_block_split(raw, offs))

    def _write_parts(self, raw_start: int, parts,
                     blocking: bool = False) -> None:
        """Write one flushed run's encoded blocks (``parts`` is the
        encode_block_split result, or its future when pipelined)."""
        if blocking:
            parts = parts.result()
        # One flush may emit several physical blocks: a run mixing
        # value kinds at a metric boundary splits so each side keeps a
        # structured (fused-servable) codec instead of whole-run zlib.
        for rel, sub, tag, enc in parts:
            self.blocks.append((raw_start + rel, self.f.tell()))
            self.f.write(_BLOCK_HDR.pack(tag, len(sub), len(enc)))
            self.f.write(enc)
        # Compressed block body written, not yet durable: torn mode
        # cuts INSIDE this block specifically (header + payload), the
        # state a mid-spill power cut leaves — recovery must treat the
        # whole .tmp as a stray, never parse a half block. Flushed
        # first so the cut has on-disk bytes to land in (a block spans
        # many buffered-writer pages anyway).
        self.f.flush()
        _fault("sst.write.block", getattr(self.f, "name", None),
               _BLOCK_HDR.size + len(enc))

    def finish(self) -> int:
        """Flush pending blocks; returns the footer's file offset."""
        if self.v4:
            self._flush_block()
            while self._futs:
                self._write_parts(*self._futs.popleft(), blocking=True)
        return self.f.tell()


def _write_bloom_and_trailer(
        f, ntables: int, footer_start: int,
        blooms: "dict[str, np.ndarray | None]",
        bw: "_BodyWriter | None" = None) -> None:
    """Write the bloom section (format 3+) and the trailer, then make
    the file durable. ``blooms`` maps table -> packed bit array or
    None (no bloom); at WRITE_FORMAT 2 the section and the extended
    trailer fields are omitted entirely (legacy layout). ``bw`` (a v4
    body writer) adds the blocks index + the extended v4 trailer."""
    if bw is not None and bw.v4:
        blocks_start = f.tell()
        f.write(_U32.pack(len(bw.blocks)))
        f.write(np.asarray([b[0] for b in bw.blocks], ">u8").tobytes())
        f.write(np.asarray([b[1] for b in bw.blocks], ">u8").tobytes())
        bloom_start = f.tell()
        for table in sorted(blooms):
            tb = table.encode()
            bits = blooms[table]
            f.write(_U16.pack(len(tb)) + tb)
            if bits is None:
                f.write(_BLOOM_HDR.pack(0, 0))
            else:
                f.write(_BLOOM_HDR.pack(BLOOM_K, BLOOM_BITS))
                f.write(bits.tobytes())
        f.write(_TRAILER_V4.pack(ntables, footer_start, bloom_start,
                                 blocks_start, bw.raw_off))
    elif WRITE_FORMAT < 3:
        f.write(_TRAILER.pack(ntables, footer_start))
    else:
        bloom_start = f.tell()
        for table in sorted(blooms):
            tb = table.encode()
            bits = blooms[table]
            f.write(_U16.pack(len(tb)) + tb)
            if bits is None:
                f.write(_BLOOM_HDR.pack(0, 0))
            else:
                f.write(_BLOOM_HDR.pack(BLOOM_K, BLOOM_BITS))
                f.write(bits.tobytes())
        f.write(_TRAILER_V3.pack(ntables, footer_start, bloom_start))
    f.flush()
    # Footer + bloom + trailer written, not yet durable: torn mode
    # cuts INSIDE this section specifically (rec_bytes spans exactly
    # the bytes since footer_start), leaving a body-complete file
    # whose index is garbage — the reader/recovery must treat it as a
    # stray .tmp, never parse a half footer.
    _fault("sst.write.footer", getattr(f, "name", None),
           max(f.tell() - footer_start, 1))
    os.fsync(f.fileno())


def _footer_bytes(index: dict[str, tuple[list[bytes], list[int]]],
                  ) -> bytes:
    out = io.BytesIO()
    for table in sorted(index):
        keys, offs = index[table]
        tb = table.encode()
        out.write(_U16.pack(len(tb)) + tb + _U32.pack(len(keys)))
        out.write(np.fromiter(map(len, keys), ">u4",
                              len(keys)).tobytes())
        out.write(np.asarray(offs, ">u8").tobytes())
        out.write(b"".join(keys))
    return out.getvalue()


def _finish_file(f, index: dict[str, tuple[list[bytes], list[int]]],
                 footer_start: int,
                 blooms: "dict[str, np.ndarray | None] | None" = None,
                 bw: "_BodyWriter | None" = None,
                 ) -> None:
    """Write the footer (+ blocks index + bloom section + trailer) and
    make the file durable. ``blooms`` overrides the per-table bloom
    bits (the copy-merge passes OR-ed source blooms); by default each
    table's bloom is built from its index keys. A v4 ``bw`` stores the
    footer zlib-compressed (the per-key index is ~25 B/row of highly
    redundant keys/offsets — left raw it would cap the whole file's
    compression ratio)."""
    if bw is not None and bw.v4:
        fb = _footer_bytes(index)
        z = zlib.compress(fb, 1)
        f.write(_U32.pack(len(fb)) + _U32.pack(len(z)) + z)
    else:
        # Streamed (not buffered): a 4M-row generation's footer is
        # ~100 MB and the v3 path must not grow a peak-RSS bump.
        for table in sorted(index):
            keys, offs = index[table]
            tb = table.encode()
            f.write(_U16.pack(len(tb)) + tb + _U32.pack(len(keys)))
            f.write(np.fromiter(map(len, keys), ">u4",
                                len(keys)).tobytes())
            f.write(np.asarray(offs, ">u8").tobytes())
            f.write(b"".join(keys))
    if blooms is None:
        blooms = {}
        for table, (keys, _) in index.items():
            hs = _bloom_hashes_for_keys(keys)
            blooms[table] = (None if hs is None
                             else _bloom_bits_from_hashes(hs))
    else:
        # One bloom entry per indexed table, always (the reader parses
        # the section by the trailer's table count).
        blooms = {t: blooms.get(t) for t in index}
    _write_bloom_and_trailer(f, len(index), footer_start, blooms, bw)


def _durable_rename(tmp: str, path: str) -> None:
    # Body complete in the page cache, not yet renamed: crash leaves a
    # .tmp recovery ignores; torn cuts into the record/footer section
    # (same outcome — the cut file never gets renamed).
    _fault("sst.write.body", tmp, 1 << 12)
    os.replace(tmp, path)
    # Rename visible, directory entry not yet fsynced: on process
    # death (os._exit) the rename IS visible — the interesting state
    # for crash recovery, which must treat the new file as a stray
    # until a manifest names it.
    _fault("sst.rename", path)
    # Make the rename itself durable before the caller truncates its
    # WAL: without the directory fsync a power loss could surface the
    # OLD generation alongside an already-truncated WAL.
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def write_sstable_bulk(path: str,
                       tables: dict[str, tuple[list[bytes], object]],
                       codec: str | None = None) -> int:
    """write_sstable for pre-materialized data: per table, a SORTED key
    list and either a parallel list of cell lists OR the memtable row
    dict itself (key -> {(fam, qual): value}, no tombstones). With the
    native extension the whole record section frames in one C pass per
    table (the per-row Python framing was ~5 us/row — the dominant cost
    of checkpoint spills at scale); without it, falls back to the
    streaming writer. A compressed ``codec`` always streams: blocks
    need per-record boundaries the C framer doesn't report."""
    if _EXT is None or codec == "tsst4":
        def rows():
            for table in sorted(tables):
                keys, data = tables[table]
                if isinstance(data, dict):
                    for k in keys:
                        yield table, k, sorted(
                            (f, q, v)
                            for (f, q), v in data[k].items())
                else:
                    for k, c in zip(keys, data):
                        yield table, k, c
        return write_sstable(path, rows(), codec=codec)
    tmp = path + ".tmp"
    n = 0
    with open(tmp, "wb") as f:
        f.write(_MAGIC if WRITE_FORMAT >= 3 else _MAGIC_V2)
        off = len(_MAGIC)
        footer: dict[str, tuple[bytes, bytes, list[bytes]]] = {}
        for table in sorted(tables):
            keys, data = tables[table]
            if isinstance(data, dict):
                recs, offs_be, klens_be = _EXT.frame_rows_dict(
                    table.encode(), keys, data, off)
            else:
                recs, offs_be, klens_be = _EXT.frame_rows(
                    table.encode(), keys, data, off)
            f.write(recs)
            off += len(recs)
            n += len(keys)
            footer[table] = (offs_be, klens_be, keys)
        footer_start = off
        blooms: dict[str, "np.ndarray | None"] = {}
        for table in sorted(footer):
            offs_be, klens_be, keys = footer[table]
            tb = table.encode()
            f.write(_U16.pack(len(tb)) + tb + _U32.pack(len(keys)))
            f.write(klens_be)
            f.write(offs_be)
            f.write(b"".join(keys))
            hs = _bloom_hashes_for_keys(keys)
            blooms[table] = (None if hs is None
                             else _bloom_bits_from_hashes(hs))
        _write_bloom_and_trailer(f, len(footer), footer_start, blooms)
    _durable_rename(tmp, path)
    return n


def write_sstable(path: str, rows: Iterable[Row],
                  codec: str | None = None) -> int:
    """Write rows (pre-sorted by (table, key)) to a new sstable at `path`.

    Returns the number of rows written. Writes via a temp file + atomic
    rename so a crash mid-write never corrupts the previous generation.
    ``codec`` "tsst4" writes format v4 (compressed blocks); None/"none"
    writes the WRITE_FORMAT legacy layout byte-identically.
    """
    tmp = path + ".tmp"
    n = 0
    index: dict[str, tuple[list[bytes], list[int]]] = {}
    with open(tmp, "wb") as f:
        bw = _BodyWriter(f, codec)
        for table, key, cells in rows:
            tb = table.encode()
            parts = [_U16.pack(len(tb)), tb, _U16.pack(len(key)), key,
                     _U32.pack(len(cells))]
            for fam, qual, value in cells:
                parts += [_U16.pack(len(fam)), fam, _U16.pack(len(qual)),
                          qual, _U32.pack(len(value)), value]
            off = bw.write_record(table, b"".join(parts))
            keys, offs = index.setdefault(table, ([], []))
            keys.append(key)
            offs.append(off)
            n += 1
        _finish_file(f, index, bw.finish(), bw=bw)
    _durable_rename(tmp, path)
    return n


def _frame_record(table_b: bytes, key: bytes,
                  cells: dict) -> bytes:
    """One record from a cell dict ({(fam, qual): value}, no Nones),
    cells sorted — same wire layout as write_sstable's loop."""
    triples = sorted((f, q, v) for (f, q), v in cells.items())
    parts = [_U16.pack(len(table_b)), table_b, _U16.pack(len(key)), key,
             _U32.pack(len(triples))]
    for fam, qual, value in triples:
        parts += [_U16.pack(len(fam)), fam, _U16.pack(len(qual)), qual,
                  _U32.pack(len(value)), value]
    return b"".join(parts)


def merge_sstables(path: str, gens: "list[SSTable]",
                   frozen: dict, codec: str | None = None) -> int:
    """Collapse sstable generations (OLDEST FIRST) + a frozen memtable
    tier into one new sstable at ``path`` — the full-merge leg of
    checkpoint (storage/kv.py), rebuilt as a COPY-MERGE.

    ``frozen``: {table: (rows, row_tombs, has_cell_tombs)} with rows =
    {key: {(fam, qual): value-or-None}} (None = tombstone masking a
    lower generation) and row_tombs masking whole lower-tier rows.

    Keys present in exactly one generation and untouched by the frozen
    tier — at scale, nearly all of them (time-major ingest puts each
    row-hour in one spill) — have their record bytes copied VERBATIM,
    contiguous runs as single slices, so the merge runs at IO speed.
    Only multi-source keys and frozen rows are decoded and re-framed
    (tombstones applied). The previous streamed per-row merge paid a
    per-key binary search per generation plus Python framing for every
    row: 20.7 us/row, 145 s for a 7M-row merge measured at the 1B
    400M-point mark; the copy path is two orders cheaper.
    Returns rows written. Same tmp + fsync + atomic-rename durability
    contract as write_sstable. ``codec`` selects the OUTPUT format;
    compaction re-encodes as it merges, so mixed-format generation
    sets converge on the writer's configured codec (v4 sources feeding
    a v4 output decode + re-compress block-wise; the unique-key record
    bytes themselves still relocate verbatim, never re-frame).
    """
    names = set(frozen)
    for g in gens:
        names.update(g.tables())
    tmp = path + ".tmp"
    n = 0
    index: dict[str, tuple[list[bytes], list[int]]] = {}
    blooms: dict[str, "np.ndarray | None"] = {}
    with open(tmp, "wb") as f:
        bw = _BodyWriter(f, codec)
        for name in sorted(names):
            rows_f, row_tombs, has_tombs = frozen.get(
                name, ({}, set(), False))
            tb = name.encode()
            extents = [g.record_extents(name) for g in gens]
            # Multi-source keys: seen in >1 generation, or overlaid by
            # a frozen row. (Running set-union dup detection; the
            # per-table transient is ~O(total keys).)
            seen: set[bytes] = set()
            dup: set[bytes] = set()
            for keys, _, _ in extents:
                ks = set(keys)
                dup |= seen & ks
                seen |= ks
            dup.update(k for k in rows_f if k in seen)
            pairs: list[tuple[bytes, int]] = []
            # 1) Verbatim copy of single-source, frozen-untouched runs.
            # Vectorized segmentation: a per-key Python loop (set
            # probes + numpy scalar int conversions + a tuple genexpr)
            # cost ~2.2 us/key — 39 s of a 127 s profile at 17.5M rows.
            # Here the skipped keys (dup/row-tomb, both small sets) are
            # located by bisect, file-contiguity breaks (key order !=
            # file order in a previously-merged generation) come from
            # one numpy compare, and each surviving segment costs one
            # slice write + one vector add, with C-speed zip for the
            # footer pairs.
            skip = dup | row_tombs
            for (keys, starts, ends), g in zip(extents, gens):
                m = len(keys)
                if m == 0:
                    continue
                excl = set()
                if skip:
                    for k in skip:
                        p = bisect_left(keys, k)
                        if p < m and keys[p] == k:
                            excl.add(p)
                breaks = np.nonzero(starts[1:] != ends[:-1])[0] + 1
                cuts = np.unique(np.concatenate([
                    np.array([0, m], np.int64), breaks,
                    np.fromiter(excl, np.int64, len(excl)),
                    np.fromiter((p + 1 for p in excl), np.int64,
                                len(excl))]))
                for a, b in zip(cuts[:-1].tolist(), cuts[1:].tolist()):
                    if a in excl:
                        continue
                    lo, hi = int(starts[a]), int(ends[b - 1])
                    run_off = bw.write_run(name, g.raw_bytes(lo, hi),
                                           starts[a:b] - lo)
                    pairs.extend(zip(
                        keys[a:b],
                        (starts[a:b] + (run_off - lo)).tolist()))
            # 2) Multi-source keys: overlay oldest -> newest -> frozen.
            for k in dup:
                merged: dict = {}
                if k not in row_tombs:
                    for g in gens:
                        cells = g.get(name, k)
                        if cells:
                            for fam, q, v in cells:
                                merged[(fam, q)] = v
                row = rows_f.get(k)
                if row:
                    for ck, v in row.items():
                        if v is None:
                            merged.pop(ck, None)
                        else:
                            merged[ck] = v
                if not merged:
                    continue
                rec = _frame_record(tb, k, merged)
                pairs.append((k, bw.write_record(name, rec)))
            # 3) Frozen-only rows (C-framed when tombstone-free).
            fr_only = sorted(k for k in rows_f
                             if k not in dup and rows_f[k])
            if fr_only and _EXT is not None and not has_tombs:
                base = bw.raw_off
                recs, offs_be, _ = _EXT.frame_rows_dict(
                    tb, fr_only, rows_f, base)
                abs_offs = np.frombuffer(offs_be, ">u8").astype(
                    np.int64)
                bw.write_run(name, recs, abs_offs - base)
                pairs.extend(zip(fr_only, abs_offs.tolist()))
            else:
                for k in fr_only:
                    cells = {ck: v for ck, v in rows_f[k].items()
                             if v is not None}
                    if not cells:
                        continue
                    rec = _frame_record(tb, k, cells)
                    pairs.append((k, bw.write_record(name, rec)))
            if not pairs:
                continue
            # Timsort exploits the concatenated sorted runs.
            pairs.sort()
            index[name] = ([p[0] for p in pairs], [p[1] for p in pairs])
            n += len(pairs)
            # Bloom for the merged table: OR the source generations'
            # fixed-size blooms (records relocate verbatim, so their
            # identities carry over; keys a tombstone just dropped
            # leave stale bits — false positives only) and hash in the
            # frozen tier's keys. Any bloomless source (v1/v2 file,
            # short keys) makes the output bloomless: a bloom that
            # does not cover every key would hide rows from pruned
            # scans.
            bloom: "np.ndarray | None" = np.zeros(BLOOM_BITS // 8,
                                                 np.uint8)
            for g in gens:
                if g.key_count(name) == 0:
                    continue
                gb = g.bloom_bits(name)
                if gb is None:
                    bloom = None
                    break
                np.bitwise_or(bloom, gb, out=bloom)
            if bloom is not None and rows_f:
                hs = _bloom_hashes_for_keys(rows_f)
                if hs is None:
                    bloom = None
                else:
                    np.bitwise_or(bloom, _bloom_bits_from_hashes(hs),
                                  out=bloom)
            blooms[name] = bloom
        _finish_file(f, index, bw.finish(), blooms, bw=bw)
    _durable_rename(tmp, path)
    return n


class SSTable:
    """mmap-backed reader over one sstable generation."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, "rb")
        size = os.fstat(self._f.fileno()).st_size
        self._mm = mmap.mmap(self._f.fileno(), size, access=mmap.ACCESS_READ)
        # table -> (sorted keys, parallel row offsets)
        self._index: dict[str, tuple[list[bytes], list[int]]] = {}
        # table -> packed BLOOM_BITS bit array (absent = no pruning)
        self._blooms: dict[str, np.ndarray] = {}
        self._all_starts = None  # record_extents' sorted-start cache
        # v4 state: raw-space block starts (python list for bisect),
        # parallel file offsets, and a tiny decoded-block FIFO (scans
        # walk blocks sequentially, so a handful of slots turns the
        # per-row decode into one vectorized pass per block).
        self._blk_raw: list[int] | None = None
        self._blk_file: list[int] | None = None
        self._blk_cache: dict[int, bytes] = {}
        self.format = 3
        head = self._mm[:len(_MAGIC)]
        if head == _MAGIC_V4:
            self.format = 4
            self._load_footer(v3=True, v4=True)
        elif head == _MAGIC:
            self._load_footer(v3=True)
        elif head == _MAGIC_V2:
            self.format = 2
            self._load_footer(v3=False)
        elif head == _MAGIC_V1:
            self.format = 1
            self._build_index_v1()
        else:
            raise IOError(f"{path}: bad sstable magic")

    def _load_footer(self, v3: bool, v4: bool = False) -> None:
        mm = self._mm
        if v4:
            (ntables, footer_start, bloom_start, blocks_start,
             raw_end) = _TRAILER_V4.unpack_from(
                mm, len(mm) - _TRAILER_V4.size)
            self._data_end = raw_end
            self._footer_file_start = footer_start
            # Blocks index: raw-space starts + file offsets.
            (nblocks,) = _U32.unpack_from(mm, blocks_start)
            off = blocks_start + 4
            self._blk_raw = np.frombuffer(
                mm, ">u8", nblocks, off).astype(np.int64).tolist()
            off += 8 * nblocks
            self._blk_file = np.frombuffer(
                mm, ">u8", nblocks, off).astype(np.int64).tolist()
            # Footer: one zlib unit of the v3 footer bytes.
            fb_raw, fb_enc = _U32.unpack_from(mm, footer_start)[0], \
                _U32.unpack_from(mm, footer_start + 4)[0]
            fbuf = zlib.decompress(
                mm[footer_start + 8:footer_start + 8 + fb_enc])
            if len(fbuf) != fb_raw:
                raise IOError(f"{self.path}: footer decompressed to "
                              f"{len(fbuf)} bytes, expected {fb_raw}")
            src, off = fbuf, 0
        elif v3:
            ntables, footer_start, bloom_start = _TRAILER_V3.unpack_from(
                mm, len(mm) - _TRAILER_V3.size)
            self._data_end = footer_start
            src, off = mm, footer_start
        else:
            ntables, footer_start = _TRAILER.unpack_from(
                mm, len(mm) - _TRAILER.size)
            bloom_start = None
            self._data_end = footer_start
            src, off = mm, footer_start
        for _ in range(ntables):
            (tlen,) = _U16.unpack_from(src, off)
            off += 2
            table = src[off:off + tlen].decode()
            off += tlen
            (nkeys,) = _U32.unpack_from(src, off)
            off += 4
            lens_be = src[off:off + 4 * nkeys]
            off += 4 * nkeys
            offs = np.frombuffer(src, ">u8", nkeys, off).tolist()
            off += 8 * nkeys
            blob_len = int(np.frombuffer(lens_be, ">u4").sum())
            keys = _slice_varlen(src[off:off + blob_len], lens_be)
            off += blob_len
            self._index[table] = (keys, offs)
        if bloom_start is not None:
            off = bloom_start
            for _ in range(ntables):
                (tlen,) = _U16.unpack_from(mm, off)
                off += 2
                table = mm[off:off + tlen].decode()
                off += tlen
                k, nbits = _BLOOM_HDR.unpack_from(mm, off)
                off += _BLOOM_HDR.size
                nbytes = nbits >> 3
                if k:
                    # Copied out of the mmap (a frombuffer VIEW would
                    # pin the map open past close()); 128 KiB per
                    # table.
                    bits = np.frombuffer(mm, np.uint8, nbytes,
                                         off).copy()
                    off += nbytes
                    # Foreign geometry (a build with different BLOOM
                    # consts) reads fine but cannot be probed or
                    # OR-merged — treat as bloomless.
                    if k == BLOOM_K and nbits == BLOOM_BITS:
                        self._blooms[table] = bits

    def _build_index_v1(self) -> None:
        self._data_end = len(self._mm)
        mm, off, end = self._mm, len(_MAGIC_V1), len(self._mm)
        while off < end:
            start = off
            (tlen,) = _U16.unpack_from(mm, off)
            off += 2
            table = mm[off:off + tlen].decode()
            off += tlen
            (klen,) = _U16.unpack_from(mm, off)
            off += 2
            key = bytes(mm[off:off + klen])
            off += klen
            (ncells,) = _U32.unpack_from(mm, off)
            off += 4
            for _ in range(ncells):
                (flen,) = _U16.unpack_from(mm, off)
                off += 2 + flen
                (qlen,) = _U16.unpack_from(mm, off)
                off += 2 + qlen
                (vlen,) = _U32.unpack_from(mm, off)
                off += 4 + vlen
            keys, offs = self._index.setdefault(table, ([], []))
            keys.append(key)
            offs.append(start)

    def close(self) -> None:
        self._mm.close()
        self._f.close()

    def tables(self) -> list[str]:
        return list(self._index)

    def key_count(self, table: str) -> int:
        idx = self._index.get(table)
        return len(idx[0]) if idx else 0

    def key_bounds(self, table: str) -> tuple[bytes, bytes] | None:
        """(smallest, largest) row key stored for ``table``, or None
        when the table is absent — a batch existence prefilter: keys
        outside this range cannot be in the sstable, which lets
        time-ordered ingest (new base-times sort after every spilled
        key) skip the per-key bisect entirely."""
        idx = self._index.get(table)
        if not idx or not idx[0]:
            return None
        keys = idx[0]
        return keys[0], keys[-1]

    def bloom_bits(self, table: str) -> "np.ndarray | None":
        """Packed bloom bit array for ``table`` (the copy-merge ORs
        these), or None when the table has no usable bloom."""
        return self._blooms.get(table)

    def bloom_may_contain(self, table: str,
                          h1s: "np.ndarray") -> bool:
        """Can this generation hold ANY series whose identity hash is
        in ``h1s`` (uint64 array of series_hash values)? True when the
        table has no bloom (v1/v2 file, short keys, foreign geometry)
        — absence of evidence never prunes."""
        bits = self._blooms.get(table)
        if bits is None or len(h1s) == 0:
            return True
        pos = _bloom_positions(h1s)
        got = (bits[(pos >> np.uint64(3)).astype(np.int64)]
               >> (pos & np.uint64(7)).astype(np.uint8)) & 1
        return bool(got.all(axis=1).any())

    def bloom_may_contain_hash(self, table: str, h1: int) -> bool:
        """Scalar bloom probe for ONE series-identity hash — the
        point-get prefilter (_lower_tier_has skips this generation's
        key bisect on False). Pure-int arithmetic, exactly
        _bloom_positions' Kirsch-Mitzenmacher derivation, so it can
        never disagree with the vectorized scan-path probe. True when
        the table has no bloom."""
        bits = self._blooms.get(table)
        if bits is None:
            return True
        h2 = (((h1 >> 16) * 0x9E3779B1 + 0x7FEB352D)
              & 0xFFFFFFFF) | 1
        for k in range(BLOOM_K):
            pos = (h1 + k * h2) % BLOOM_BITS
            if not (bits[pos >> 3] >> (pos & 7)) & 1:
                return False
        return True

    def bloom_check(self, table: str) -> "int | None":
        """fsck probe: how many of the table's indexed keys are NOT
        covered by its bloom (must be 0 — a false negative silently
        hides rows from pruned scans). None when the table has no
        bloom."""
        bits = self._blooms.get(table)
        if bits is None:
            return None
        idx = self._index.get(table)
        if not idx or not idx[0]:
            return 0
        hs = _bloom_hashes_for_keys(idx[0])
        if hs is None:
            # Short keys under a bloom: every such key is invisible to
            # bloom-pruned scans — count them all as misses.
            return sum(1 for k in idx[0] if len(k) < _IDENT_HI)
        pos = _bloom_positions(np.asarray(hs, np.uint64))
        got = (bits[(pos >> np.uint64(3)).astype(np.int64)]
               >> (pos & np.uint64(7)).astype(np.uint8)) & 1
        return int((~got.all(axis=1)).sum())

    def has_key(self, table: str, key: bytes) -> bool:
        idx = self._index.get(table)
        if not idx:
            return False
        keys, _ = idx
        i = bisect_left(keys, key)
        return i < len(keys) and keys[i] == key

    # -- v4 block access ------------------------------------------------

    @property
    def block_count(self) -> int:
        return len(self._blk_raw) if self._blk_raw is not None else 0

    def block_header(self, j: int) -> tuple[int, int, int]:
        """(codec tag, raw_len, enc_len) of block ``j``."""
        return _BLOCK_HDR.unpack_from(self._mm, self._blk_file[j])

    def block_raw_span(self, j: int) -> tuple[int, int]:
        """[raw_start, raw_end) of block ``j`` in raw space."""
        lo = self._blk_raw[j]
        hi = self._blk_raw[j + 1] if j + 1 < len(self._blk_raw) \
            else self._data_end
        return lo, hi

    def block_enc(self, j: int) -> memoryview:
        """The encoded payload bytes of block ``j`` (no copy)."""
        tag, raw_len, enc_len = self.block_header(j)
        start = self._blk_file[j] + _BLOCK_HDR.size
        return memoryview(self._mm)[start:start + enc_len]

    def _block_raw(self, j: int) -> bytes:
        """Decoded raw record bytes of block ``j``, behind a small
        FIFO cache (scans touch blocks in order; dict ops are
        GIL-atomic, so concurrent scans at worst decode twice)."""
        got = self._blk_cache.get(j)
        if got is not None:
            return got
        tag, raw_len, enc_len = self.block_header(j)
        with _M_DECODE.time():
            raw = _codecs.decode_block(tag, self.block_enc(j), raw_len)
        if len(self._blk_cache) >= 8:
            try:
                self._blk_cache.pop(next(iter(self._blk_cache)))
            except (StopIteration, KeyError):
                pass
        self._blk_cache[j] = raw
        return raw

    def _record_buf(self, off: int):
        """(buffer, position) holding the record at raw offset ``off``
        — the mmap itself on raw formats, the decoded enclosing block
        on v4."""
        if self._blk_raw is None:
            return self._mm, off
        j = bisect_right(self._blk_raw, off) - 1
        return self._block_raw(j), off - self._blk_raw[j]

    def raw_bytes(self, lo: int, hi: int) -> bytes:
        """Raw record bytes [lo, hi) in raw space — what the copy-merge
        relocates. v4 concatenates decoded block slices."""
        if self._blk_raw is None:
            return self._mm[lo:hi]
        if hi <= lo:
            return b""
        j = bisect_right(self._blk_raw, lo) - 1
        parts = []
        while lo < hi:
            blo, bhi = self.block_raw_span(j)
            raw = self._block_raw(j)
            parts.append(raw[lo - blo:min(hi, bhi) - blo])
            lo = bhi
            j += 1
        return parts[0] if len(parts) == 1 else b"".join(parts)

    def codec_stats(self) -> "tuple[int, int] | None":
        """(raw_bytes, stored_bytes) of the record section — the
        compression ratio source. None on non-v4 files."""
        if self._blk_raw is None:
            return None
        return (self._data_end - len(_MAGIC_V4),
                self._footer_file_start - len(_MAGIC_V4))

    def block_audit(self, log=None) -> int:
        """fsck's block check: every block's codec tag must be known,
        its payload must decode, and the decoded size must match the
        header's uncompressed size. Returns the error count."""
        errors = 0
        say = log if log is not None else (lambda *_: None)
        if self._blk_raw is None:
            return 0
        for j in range(self.block_count):
            lo, hi = self.block_raw_span(j)
            try:
                tag, raw_len, enc_len = self.block_header(j)
            except struct.error:
                errors += 1
                say(f"ERROR: {self.path}: block {j}: truncated header")
                continue
            if raw_len != hi - lo:
                errors += 1
                say(f"ERROR: {self.path}: block {j}: header raw_len "
                    f"{raw_len} != index span {hi - lo}")
                continue
            try:
                raw = _codecs.decode_block(tag, self.block_enc(j),
                                           raw_len)
            except _codecs.BlockCodecError as e:
                errors += 1
                say(f"ERROR: {self.path}: block {j} "
                    f"(tag={tag}): {e}")
                continue
            del raw
        return errors

    def _read_row(self, off: int) -> list[tuple[bytes, bytes, bytes]]:
        mm, off = self._record_buf(off)
        return self._parse_row(mm, off)

    @staticmethod
    def _parse_row(mm, off: int) -> list[tuple[bytes, bytes, bytes]]:
        (tlen,) = _U16.unpack_from(mm, off)
        off += 2 + tlen
        (klen,) = _U16.unpack_from(mm, off)
        off += 2 + klen
        (ncells,) = _U32.unpack_from(mm, off)
        off += 4
        cells = []
        for _ in range(ncells):
            (flen,) = _U16.unpack_from(mm, off)
            off += 2
            fam = bytes(mm[off:off + flen])
            off += flen
            (qlen,) = _U16.unpack_from(mm, off)
            off += 2
            qual = bytes(mm[off:off + qlen])
            off += qlen
            (vlen,) = _U32.unpack_from(mm, off)
            off += 4
            value = bytes(mm[off:off + vlen])
            off += vlen
            cells.append((fam, qual, value))
        return cells

    def get(self, table: str,
            key: bytes) -> list[tuple[bytes, bytes, bytes]] | None:
        """Cells of one row, or None when the key is absent."""
        idx = self._index.get(table)
        if not idx:
            return None
        keys, offs = idx
        i = bisect_left(keys, key)
        if i >= len(keys) or keys[i] != key:
            return None
        return self._read_row(offs[i])

    def scan_keys(self, table: str, start: bytes,
                  stop: bytes | None) -> list[bytes]:
        idx = self._index.get(table)
        if not idx:
            return []
        keys, _ = idx
        lo = bisect_left(keys, start)
        hi = bisect_left(keys, stop) if stop else len(keys)
        return keys[lo:hi]

    def record_extents(self, table: str) -> tuple[
            "list[bytes]", "np.ndarray", "np.ndarray"]:
        """(sorted keys, record starts, record ends) for one table.

        Records carry no embedded offsets, so a [start, end) byte
        slice relocates verbatim into another file — the basis of the
        copy-merge compaction (merge_sstables), which moves unique-key
        records at IO speed instead of decode/re-frame speed. Every
        writer appends records back-to-back, but NOT necessarily in
        key order (merge_sstables scatters re-framed rows after the
        copy runs), so each record's end is the smallest record start
        greater than its own — computed against the file's full start
        set, with the record section's end as the sentinel.
        """
        idx = self._index.get(table)
        if not idx or not idx[0]:
            e = np.empty(0, np.int64)
            return [], e, e
        keys, offs = idx
        starts = np.asarray(offs, dtype=np.int64)
        all_starts = self._all_starts
        if all_starts is None:
            all_starts = np.sort(np.concatenate(
                [np.asarray(o, dtype=np.int64)
                 for _, o in self._index.values()]
                + [np.asarray([self._data_end], dtype=np.int64)]))
            self._all_starts = all_starts
        ends = all_starts[np.searchsorted(all_starts, starts, "right")]
        return keys, starts, ends

    def iter_rows_range(self, table: str, start: bytes,
                        stop: bytes | None,
                        skip: "set[bytes] | None" = None) -> Iterator[
            tuple[bytes, list[tuple[bytes, bytes, bytes]]]]:
        """Rows with start <= key < stop (stop None = to the end), in
        key order — the range form of the read path. One bisect pair
        per CALL instead of one per key: the cold scan used to probe
        every generation per row-hour (2.35M get() calls over a 1-week
        scan of the 1B store, ~5 s of the 17 s wall). ``skip`` (e.g.
        the caller's row-tombstone set) suppresses rows BEFORE the
        record decode — masked rows cost a set probe, not a full
        _read_row."""
        idx = self._index.get(table)
        if not idx:
            return
        keys, offs = idx
        lo = bisect_left(keys, start)
        hi = bisect_left(keys, stop) if stop else len(keys)
        if self._blk_raw is not None and hi - lo > 1:
            yield from self._stream_rows(keys, offs, lo, hi, skip)
            return
        if skip:
            for i in range(lo, hi):
                if keys[i] not in skip:
                    yield keys[i], self._read_row(offs[i])
        else:
            for i in range(lo, hi):
                yield keys[i], self._read_row(offs[i])

    def _stream_rows(self, keys, offs, lo: int, hi: int, skip):
        """Chunked/streamed decode for v4 range sweeps (replica
        refresh refolds, rollup catch-up scans, full-store sketch
        rebuilds): rows are grouped by their enclosing block and each
        block decodes ONCE into a LOCAL buffer, dropped as the sweep
        advances — peak decode memory is one block (vs filling and
        churning the 8-slot cache), the per-row block bisect
        disappears, and the point-get cache keeps its query working
        set (a whole-generation sweep never evicts it). A cached
        block is reused but a streamed decode is never inserted."""
        j = -1
        braw: bytes | None = None
        blo = bhi = 0
        for i in range(lo, hi):
            if skip and keys[i] in skip:
                continue
            off = offs[i]
            if not blo <= off < bhi or braw is None:
                j = bisect_right(self._blk_raw, off) - 1
                blo, bhi = self.block_raw_span(j)
                braw = self._blk_cache.get(j)
                if braw is None:
                    tag, raw_len, _enc = self.block_header(j)
                    with _M_DECODE.time():
                        braw = _codecs.decode_block(
                            tag, self.block_enc(j), raw_len)
                    _M_STREAM.inc()
            yield keys[i], self._parse_row(braw, off - blo)

    def iter_rows(self, table: str) -> Iterator[
            tuple[bytes, list[tuple[bytes, bytes, bytes]]]]:
        idx = self._index.get(table)
        if not idx:
            return
        keys, offs = idx
        if self._blk_raw is not None and len(keys) > 1:
            yield from self._stream_rows(keys, offs, 0, len(keys),
                                         None)
            return
        for key, off in zip(keys, offs):
            yield key, self._read_row(off)
