"""Immutable sorted-table file: the spill tier under the memtable.

The reference delegates at-rest storage to HBase HFiles; here a
checkpoint spills the memtable into immutable generation files, after
which the WAL is truncated — bounding both recovery time and memtable
RAM for long-running daemons (SURVEY §5.4, §7.2: "enough LSM to sustain
ingest while scans run, without rebuilding HBase").

File layout v2 (all integers big-endian):
    magic  b"TSST2"
    record*  :=  [u16 table_len][table][u16 key_len][key][u32 ncells]
                 ([u16 fam_len][fam][u16 q_len][q][u32 v_len][v])*
    records sorted by (table, key); one record per row.
    footer   :=  per table:
                   [u16 table_len][table][u32 nkeys]
                   [key_lens: nkeys x u32][offsets: nkeys x u64]
                   [keys blob]
    trailer  :=  [u32 ntables][u64 footer_start]

The footer exists because opening a file by scanning every row record
cost ~3 us/row in Python — 10+ s per 4.4M-row generation, paid on every
checkpoint swap-in AND at every daemon start. v2 opens with two numpy
frombuffer calls and one C pass over the key blob. v1 files (magic
TSST1, no footer) are still read via the legacy full scan.

The reader mmaps the file and keeps only (key -> offset) indexes in
RAM; cell payloads are decoded lazily per row, so a spilled store
serves gets and scans without rehydrating the dataset.
"""

from __future__ import annotations

import mmap
import os
import struct
from bisect import bisect_left
from typing import Iterable, Iterator

import numpy as np

from opentsdb_tpu.utils.nativeext import ext as _EXT

_MAGIC_V1 = b"TSST1"
_MAGIC = b"TSST2"
_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_TRAILER = struct.Struct(">IQ")   # ntables, footer_start

# row := (table, key, [(family, qualifier, value), ...])
Row = tuple[str, bytes, list[tuple[bytes, bytes, bytes]]]


def _slice_varlen(blob: bytes, lens_be: bytes) -> list[bytes]:
    if _EXT is not None:
        return _EXT.slice_varlen(blob, lens_be)
    lens = np.frombuffer(lens_be, ">u4")
    ends = np.cumsum(lens)
    starts = ends - lens
    return [blob[a:b] for a, b in zip(starts.tolist(), ends.tolist())]


def _finish_file(f, index: dict[str, tuple[list[bytes], list[int]]],
                 footer_start: int) -> None:
    """Write the v2 footer + trailer and make the file durable."""
    for table in sorted(index):
        keys, offs = index[table]
        tb = table.encode()
        f.write(_U16.pack(len(tb)) + tb + _U32.pack(len(keys)))
        f.write(np.fromiter(map(len, keys), ">u4", len(keys)).tobytes())
        f.write(np.asarray(offs, ">u8").tobytes())
        f.write(b"".join(keys))
    f.write(_TRAILER.pack(len(index), footer_start))
    f.flush()
    os.fsync(f.fileno())


def _durable_rename(tmp: str, path: str) -> None:
    os.replace(tmp, path)
    # Make the rename itself durable before the caller truncates its
    # WAL: without the directory fsync a power loss could surface the
    # OLD generation alongside an already-truncated WAL.
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def write_sstable_bulk(path: str,
                       tables: dict[str, tuple[list[bytes], object]],
                       ) -> int:
    """write_sstable for pre-materialized data: per table, a SORTED key
    list and either a parallel list of cell lists OR the memtable row
    dict itself (key -> {(fam, qual): value}, no tombstones). With the
    native extension the whole record section frames in one C pass per
    table (the per-row Python framing was ~5 us/row — the dominant cost
    of checkpoint spills at scale); without it, falls back to the
    streaming writer."""
    if _EXT is None:
        def rows():
            for table in sorted(tables):
                keys, data = tables[table]
                if isinstance(data, dict):
                    for k in keys:
                        yield table, k, sorted(
                            (f, q, v)
                            for (f, q), v in data[k].items())
                else:
                    for k, c in zip(keys, data):
                        yield table, k, c
        return write_sstable(path, rows())
    tmp = path + ".tmp"
    n = 0
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        off = len(_MAGIC)
        footer: dict[str, tuple[bytes, bytes, list[bytes]]] = {}
        for table in sorted(tables):
            keys, data = tables[table]
            if isinstance(data, dict):
                recs, offs_be, klens_be = _EXT.frame_rows_dict(
                    table.encode(), keys, data, off)
            else:
                recs, offs_be, klens_be = _EXT.frame_rows(
                    table.encode(), keys, data, off)
            f.write(recs)
            off += len(recs)
            n += len(keys)
            footer[table] = (offs_be, klens_be, keys)
        footer_start = off
        for table in sorted(footer):
            offs_be, klens_be, keys = footer[table]
            tb = table.encode()
            f.write(_U16.pack(len(tb)) + tb + _U32.pack(len(keys)))
            f.write(klens_be)
            f.write(offs_be)
            f.write(b"".join(keys))
        f.write(_TRAILER.pack(len(footer), footer_start))
        f.flush()
        os.fsync(f.fileno())
    _durable_rename(tmp, path)
    return n


def write_sstable(path: str, rows: Iterable[Row]) -> int:
    """Write rows (pre-sorted by (table, key)) to a new sstable at `path`.

    Returns the number of rows written. Writes via a temp file + atomic
    rename so a crash mid-write never corrupts the previous generation.
    """
    tmp = path + ".tmp"
    n = 0
    index: dict[str, tuple[list[bytes], list[int]]] = {}
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        off = len(_MAGIC)
        for table, key, cells in rows:
            tb = table.encode()
            parts = [_U16.pack(len(tb)), tb, _U16.pack(len(key)), key,
                     _U32.pack(len(cells))]
            for fam, qual, value in cells:
                parts += [_U16.pack(len(fam)), fam, _U16.pack(len(qual)),
                          qual, _U32.pack(len(value)), value]
            rec = b"".join(parts)
            f.write(rec)
            keys, offs = index.setdefault(table, ([], []))
            keys.append(key)
            offs.append(off)
            off += len(rec)
            n += 1
        _finish_file(f, index, off)
    _durable_rename(tmp, path)
    return n


def _frame_record(table_b: bytes, key: bytes,
                  cells: dict) -> bytes:
    """One record from a cell dict ({(fam, qual): value}, no Nones),
    cells sorted — same wire layout as write_sstable's loop."""
    triples = sorted((f, q, v) for (f, q), v in cells.items())
    parts = [_U16.pack(len(table_b)), table_b, _U16.pack(len(key)), key,
             _U32.pack(len(triples))]
    for fam, qual, value in triples:
        parts += [_U16.pack(len(fam)), fam, _U16.pack(len(qual)), qual,
                  _U32.pack(len(value)), value]
    return b"".join(parts)


def merge_sstables(path: str, gens: "list[SSTable]",
                   frozen: dict) -> int:
    """Collapse sstable generations (OLDEST FIRST) + a frozen memtable
    tier into one new sstable at ``path`` — the full-merge leg of
    checkpoint (storage/kv.py), rebuilt as a COPY-MERGE.

    ``frozen``: {table: (rows, row_tombs, has_cell_tombs)} with rows =
    {key: {(fam, qual): value-or-None}} (None = tombstone masking a
    lower generation) and row_tombs masking whole lower-tier rows.

    Keys present in exactly one generation and untouched by the frozen
    tier — at scale, nearly all of them (time-major ingest puts each
    row-hour in one spill) — have their record bytes copied VERBATIM,
    contiguous runs as single slices, so the merge runs at IO speed.
    Only multi-source keys and frozen rows are decoded and re-framed
    (tombstones applied). The previous streamed per-row merge paid a
    per-key binary search per generation plus Python framing for every
    row: 20.7 us/row, 145 s for a 7M-row merge measured at the 1B
    400M-point mark; the copy path is two orders cheaper.
    Returns rows written. Same tmp + fsync + atomic-rename durability
    contract as write_sstable.
    """
    names = set(frozen)
    for g in gens:
        names.update(g.tables())
    tmp = path + ".tmp"
    n = 0
    index: dict[str, tuple[list[bytes], list[int]]] = {}
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        off = len(_MAGIC)
        for name in sorted(names):
            rows_f, row_tombs, has_tombs = frozen.get(
                name, ({}, set(), False))
            tb = name.encode()
            extents = [g.record_extents(name) for g in gens]
            # Multi-source keys: seen in >1 generation, or overlaid by
            # a frozen row. (Running set-union dup detection; the
            # per-table transient is ~O(total keys).)
            seen: set[bytes] = set()
            dup: set[bytes] = set()
            for keys, _, _ in extents:
                ks = set(keys)
                dup |= seen & ks
                seen |= ks
            dup.update(k for k in rows_f if k in seen)
            pairs: list[tuple[bytes, int]] = []
            # 1) Verbatim copy of single-source, frozen-untouched runs.
            # Vectorized segmentation: a per-key Python loop (set
            # probes + numpy scalar int conversions + a tuple genexpr)
            # cost ~2.2 us/key — 39 s of a 127 s profile at 17.5M rows.
            # Here the skipped keys (dup/row-tomb, both small sets) are
            # located by bisect, file-contiguity breaks (key order !=
            # file order in a previously-merged generation) come from
            # one numpy compare, and each surviving segment costs one
            # slice write + one vector add, with C-speed zip for the
            # footer pairs.
            skip = dup | row_tombs
            for (keys, starts, ends), g in zip(extents, gens):
                mm = g._mm
                m = len(keys)
                if m == 0:
                    continue
                excl = set()
                if skip:
                    for k in skip:
                        p = bisect_left(keys, k)
                        if p < m and keys[p] == k:
                            excl.add(p)
                breaks = np.nonzero(starts[1:] != ends[:-1])[0] + 1
                cuts = np.unique(np.concatenate([
                    np.array([0, m], np.int64), breaks,
                    np.fromiter(excl, np.int64, len(excl)),
                    np.fromiter((p + 1 for p in excl), np.int64,
                                len(excl))]))
                for a, b in zip(cuts[:-1].tolist(), cuts[1:].tolist()):
                    if a in excl:
                        continue
                    lo, hi = int(starts[a]), int(ends[b - 1])
                    f.write(mm[lo:hi])
                    pairs.extend(zip(
                        keys[a:b],
                        (starts[a:b] + (off - lo)).tolist()))
                    off += hi - lo
            # 2) Multi-source keys: overlay oldest -> newest -> frozen.
            for k in dup:
                merged: dict = {}
                if k not in row_tombs:
                    for g in gens:
                        cells = g.get(name, k)
                        if cells:
                            for fam, q, v in cells:
                                merged[(fam, q)] = v
                row = rows_f.get(k)
                if row:
                    for ck, v in row.items():
                        if v is None:
                            merged.pop(ck, None)
                        else:
                            merged[ck] = v
                if not merged:
                    continue
                rec = _frame_record(tb, k, merged)
                f.write(rec)
                pairs.append((k, off))
                off += len(rec)
            # 3) Frozen-only rows (C-framed when tombstone-free).
            fr_only = sorted(k for k in rows_f
                             if k not in dup and rows_f[k])
            if fr_only and _EXT is not None and not has_tombs:
                recs, offs_be, _ = _EXT.frame_rows_dict(
                    tb, fr_only, rows_f, off)
                f.write(recs)
                pairs.extend(zip(
                    fr_only,
                    np.frombuffer(offs_be, ">u8").astype(
                        np.int64).tolist()))
                off += len(recs)
            else:
                for k in fr_only:
                    cells = {ck: v for ck, v in rows_f[k].items()
                             if v is not None}
                    if not cells:
                        continue
                    rec = _frame_record(tb, k, cells)
                    f.write(rec)
                    pairs.append((k, off))
                    off += len(rec)
            if not pairs:
                continue
            # Timsort exploits the concatenated sorted runs.
            pairs.sort()
            index[name] = ([p[0] for p in pairs], [p[1] for p in pairs])
            n += len(pairs)
        _finish_file(f, index, off)
    _durable_rename(tmp, path)
    return n


class SSTable:
    """mmap-backed reader over one sstable generation."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, "rb")
        size = os.fstat(self._f.fileno()).st_size
        self._mm = mmap.mmap(self._f.fileno(), size, access=mmap.ACCESS_READ)
        # table -> (sorted keys, parallel row offsets)
        self._index: dict[str, tuple[list[bytes], list[int]]] = {}
        self._all_starts = None  # record_extents' sorted-start cache
        head = self._mm[:len(_MAGIC)]
        if head == _MAGIC:
            self._load_footer()
        elif head == _MAGIC_V1:
            self._build_index_v1()
        else:
            raise IOError(f"{path}: bad sstable magic")

    def _load_footer(self) -> None:
        mm = self._mm
        ntables, footer_start = _TRAILER.unpack_from(
            mm, len(mm) - _TRAILER.size)
        self._data_end = footer_start
        off = footer_start
        for _ in range(ntables):
            (tlen,) = _U16.unpack_from(mm, off)
            off += 2
            table = mm[off:off + tlen].decode()
            off += tlen
            (nkeys,) = _U32.unpack_from(mm, off)
            off += 4
            lens_be = mm[off:off + 4 * nkeys]
            off += 4 * nkeys
            offs = np.frombuffer(mm, ">u8", nkeys, off).tolist()
            off += 8 * nkeys
            blob_len = int(np.frombuffer(lens_be, ">u4").sum())
            keys = _slice_varlen(mm[off:off + blob_len], lens_be)
            off += blob_len
            self._index[table] = (keys, offs)

    def _build_index_v1(self) -> None:
        self._data_end = len(self._mm)
        mm, off, end = self._mm, len(_MAGIC_V1), len(self._mm)
        while off < end:
            start = off
            (tlen,) = _U16.unpack_from(mm, off)
            off += 2
            table = mm[off:off + tlen].decode()
            off += tlen
            (klen,) = _U16.unpack_from(mm, off)
            off += 2
            key = bytes(mm[off:off + klen])
            off += klen
            (ncells,) = _U32.unpack_from(mm, off)
            off += 4
            for _ in range(ncells):
                (flen,) = _U16.unpack_from(mm, off)
                off += 2 + flen
                (qlen,) = _U16.unpack_from(mm, off)
                off += 2 + qlen
                (vlen,) = _U32.unpack_from(mm, off)
                off += 4 + vlen
            keys, offs = self._index.setdefault(table, ([], []))
            keys.append(key)
            offs.append(start)

    def close(self) -> None:
        self._mm.close()
        self._f.close()

    def tables(self) -> list[str]:
        return list(self._index)

    def key_count(self, table: str) -> int:
        idx = self._index.get(table)
        return len(idx[0]) if idx else 0

    def key_bounds(self, table: str) -> tuple[bytes, bytes] | None:
        """(smallest, largest) row key stored for ``table``, or None
        when the table is absent — a batch existence prefilter: keys
        outside this range cannot be in the sstable, which lets
        time-ordered ingest (new base-times sort after every spilled
        key) skip the per-key bisect entirely."""
        idx = self._index.get(table)
        if not idx or not idx[0]:
            return None
        keys = idx[0]
        return keys[0], keys[-1]

    def has_key(self, table: str, key: bytes) -> bool:
        idx = self._index.get(table)
        if not idx:
            return False
        keys, _ = idx
        i = bisect_left(keys, key)
        return i < len(keys) and keys[i] == key

    def _read_row(self, off: int) -> list[tuple[bytes, bytes, bytes]]:
        mm = self._mm
        (tlen,) = _U16.unpack_from(mm, off)
        off += 2 + tlen
        (klen,) = _U16.unpack_from(mm, off)
        off += 2 + klen
        (ncells,) = _U32.unpack_from(mm, off)
        off += 4
        cells = []
        for _ in range(ncells):
            (flen,) = _U16.unpack_from(mm, off)
            off += 2
            fam = bytes(mm[off:off + flen])
            off += flen
            (qlen,) = _U16.unpack_from(mm, off)
            off += 2
            qual = bytes(mm[off:off + qlen])
            off += qlen
            (vlen,) = _U32.unpack_from(mm, off)
            off += 4
            value = bytes(mm[off:off + vlen])
            off += vlen
            cells.append((fam, qual, value))
        return cells

    def get(self, table: str,
            key: bytes) -> list[tuple[bytes, bytes, bytes]] | None:
        """Cells of one row, or None when the key is absent."""
        idx = self._index.get(table)
        if not idx:
            return None
        keys, offs = idx
        i = bisect_left(keys, key)
        if i >= len(keys) or keys[i] != key:
            return None
        return self._read_row(offs[i])

    def scan_keys(self, table: str, start: bytes,
                  stop: bytes | None) -> list[bytes]:
        idx = self._index.get(table)
        if not idx:
            return []
        keys, _ = idx
        lo = bisect_left(keys, start)
        hi = bisect_left(keys, stop) if stop else len(keys)
        return keys[lo:hi]

    def record_extents(self, table: str) -> tuple[
            "list[bytes]", "np.ndarray", "np.ndarray"]:
        """(sorted keys, record starts, record ends) for one table.

        Records carry no embedded offsets, so a [start, end) byte
        slice relocates verbatim into another file — the basis of the
        copy-merge compaction (merge_sstables), which moves unique-key
        records at IO speed instead of decode/re-frame speed. Every
        writer appends records back-to-back, but NOT necessarily in
        key order (merge_sstables scatters re-framed rows after the
        copy runs), so each record's end is the smallest record start
        greater than its own — computed against the file's full start
        set, with the record section's end as the sentinel.
        """
        idx = self._index.get(table)
        if not idx or not idx[0]:
            e = np.empty(0, np.int64)
            return [], e, e
        keys, offs = idx
        starts = np.asarray(offs, dtype=np.int64)
        all_starts = self._all_starts
        if all_starts is None:
            all_starts = np.sort(np.concatenate(
                [np.asarray(o, dtype=np.int64)
                 for _, o in self._index.values()]
                + [np.asarray([self._data_end], dtype=np.int64)]))
            self._all_starts = all_starts
        ends = all_starts[np.searchsorted(all_starts, starts, "right")]
        return keys, starts, ends

    def iter_rows_range(self, table: str, start: bytes,
                        stop: bytes | None,
                        skip: "set[bytes] | None" = None) -> Iterator[
            tuple[bytes, list[tuple[bytes, bytes, bytes]]]]:
        """Rows with start <= key < stop (stop None = to the end), in
        key order — the range form of the read path. One bisect pair
        per CALL instead of one per key: the cold scan used to probe
        every generation per row-hour (2.35M get() calls over a 1-week
        scan of the 1B store, ~5 s of the 17 s wall). ``skip`` (e.g.
        the caller's row-tombstone set) suppresses rows BEFORE the
        record decode — masked rows cost a set probe, not a full
        _read_row."""
        idx = self._index.get(table)
        if not idx:
            return
        keys, offs = idx
        lo = bisect_left(keys, start)
        hi = bisect_left(keys, stop) if stop else len(keys)
        if skip:
            for i in range(lo, hi):
                if keys[i] not in skip:
                    yield keys[i], self._read_row(offs[i])
        else:
            for i in range(lo, hi):
                yield keys[i], self._read_row(offs[i])

    def iter_rows(self, table: str) -> Iterator[
            tuple[bytes, list[tuple[bytes, bytes, bytes]]]]:
        idx = self._index.get(table)
        if not idx:
            return
        keys, offs = idx
        for key, off in zip(keys, offs):
            yield key, self._read_row(off)
