"""Immutable sorted-table file: the spill tier under the memtable.

The reference delegates at-rest storage to HBase HFiles; here a
checkpoint spills the memtable into immutable generation files, after
which the WAL is truncated — bounding both recovery time and memtable
RAM for long-running daemons (SURVEY §5.4, §7.2: "enough LSM to sustain
ingest while scans run, without rebuilding HBase").

File layout v2 (all integers big-endian):
    magic  b"TSST2"
    record*  :=  [u16 table_len][table][u16 key_len][key][u32 ncells]
                 ([u16 fam_len][fam][u16 q_len][q][u32 v_len][v])*
    records sorted by (table, key); one record per row.
    footer   :=  per table:
                   [u16 table_len][table][u32 nkeys]
                   [key_lens: nkeys x u32][offsets: nkeys x u64]
                   [keys blob]
    trailer  :=  [u32 ntables][u64 footer_start]

The footer exists because opening a file by scanning every row record
cost ~3 us/row in Python — 10+ s per 4.4M-row generation, paid on every
checkpoint swap-in AND at every daemon start. v2 opens with two numpy
frombuffer calls and one C pass over the key blob. v1 files (magic
TSST1, no footer) are still read via the legacy full scan.

The reader mmaps the file and keeps only (key -> offset) indexes in
RAM; cell payloads are decoded lazily per row, so a spilled store
serves gets and scans without rehydrating the dataset.
"""

from __future__ import annotations

import mmap
import os
import struct
from bisect import bisect_left
from typing import Iterable, Iterator

import numpy as np

from opentsdb_tpu.utils.nativeext import ext as _EXT

_MAGIC_V1 = b"TSST1"
_MAGIC = b"TSST2"
_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_TRAILER = struct.Struct(">IQ")   # ntables, footer_start

# row := (table, key, [(family, qualifier, value), ...])
Row = tuple[str, bytes, list[tuple[bytes, bytes, bytes]]]


def _slice_varlen(blob: bytes, lens_be: bytes) -> list[bytes]:
    if _EXT is not None:
        return _EXT.slice_varlen(blob, lens_be)
    lens = np.frombuffer(lens_be, ">u4")
    ends = np.cumsum(lens)
    starts = ends - lens
    return [blob[a:b] for a, b in zip(starts.tolist(), ends.tolist())]


def _finish_file(f, index: dict[str, tuple[list[bytes], list[int]]],
                 footer_start: int) -> None:
    """Write the v2 footer + trailer and make the file durable."""
    for table in sorted(index):
        keys, offs = index[table]
        tb = table.encode()
        f.write(_U16.pack(len(tb)) + tb + _U32.pack(len(keys)))
        f.write(np.fromiter(map(len, keys), ">u4", len(keys)).tobytes())
        f.write(np.asarray(offs, ">u8").tobytes())
        f.write(b"".join(keys))
    f.write(_TRAILER.pack(len(index), footer_start))
    f.flush()
    os.fsync(f.fileno())


def _durable_rename(tmp: str, path: str) -> None:
    os.replace(tmp, path)
    # Make the rename itself durable before the caller truncates its
    # WAL: without the directory fsync a power loss could surface the
    # OLD generation alongside an already-truncated WAL.
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def write_sstable_bulk(path: str,
                       tables: dict[str, tuple[list[bytes], object]],
                       ) -> int:
    """write_sstable for pre-materialized data: per table, a SORTED key
    list and either a parallel list of cell lists OR the memtable row
    dict itself (key -> {(fam, qual): value}, no tombstones). With the
    native extension the whole record section frames in one C pass per
    table (the per-row Python framing was ~5 us/row — the dominant cost
    of checkpoint spills at scale); without it, falls back to the
    streaming writer."""
    if _EXT is None:
        def rows():
            for table in sorted(tables):
                keys, data = tables[table]
                if isinstance(data, dict):
                    for k in keys:
                        yield table, k, sorted(
                            (f, q, v)
                            for (f, q), v in data[k].items())
                else:
                    for k, c in zip(keys, data):
                        yield table, k, c
        return write_sstable(path, rows())
    tmp = path + ".tmp"
    n = 0
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        off = len(_MAGIC)
        footer: dict[str, tuple[bytes, bytes, list[bytes]]] = {}
        for table in sorted(tables):
            keys, data = tables[table]
            if isinstance(data, dict):
                recs, offs_be, klens_be = _EXT.frame_rows_dict(
                    table.encode(), keys, data, off)
            else:
                recs, offs_be, klens_be = _EXT.frame_rows(
                    table.encode(), keys, data, off)
            f.write(recs)
            off += len(recs)
            n += len(keys)
            footer[table] = (offs_be, klens_be, keys)
        footer_start = off
        for table in sorted(footer):
            offs_be, klens_be, keys = footer[table]
            tb = table.encode()
            f.write(_U16.pack(len(tb)) + tb + _U32.pack(len(keys)))
            f.write(klens_be)
            f.write(offs_be)
            f.write(b"".join(keys))
        f.write(_TRAILER.pack(len(footer), footer_start))
        f.flush()
        os.fsync(f.fileno())
    _durable_rename(tmp, path)
    return n


def write_sstable(path: str, rows: Iterable[Row]) -> int:
    """Write rows (pre-sorted by (table, key)) to a new sstable at `path`.

    Returns the number of rows written. Writes via a temp file + atomic
    rename so a crash mid-write never corrupts the previous generation.
    """
    tmp = path + ".tmp"
    n = 0
    index: dict[str, tuple[list[bytes], list[int]]] = {}
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        off = len(_MAGIC)
        for table, key, cells in rows:
            tb = table.encode()
            parts = [_U16.pack(len(tb)), tb, _U16.pack(len(key)), key,
                     _U32.pack(len(cells))]
            for fam, qual, value in cells:
                parts += [_U16.pack(len(fam)), fam, _U16.pack(len(qual)),
                          qual, _U32.pack(len(value)), value]
            rec = b"".join(parts)
            f.write(rec)
            keys, offs = index.setdefault(table, ([], []))
            keys.append(key)
            offs.append(off)
            off += len(rec)
            n += 1
        _finish_file(f, index, off)
    _durable_rename(tmp, path)
    return n


class SSTable:
    """mmap-backed reader over one sstable generation."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, "rb")
        size = os.fstat(self._f.fileno()).st_size
        self._mm = mmap.mmap(self._f.fileno(), size, access=mmap.ACCESS_READ)
        # table -> (sorted keys, parallel row offsets)
        self._index: dict[str, tuple[list[bytes], list[int]]] = {}
        head = self._mm[:len(_MAGIC)]
        if head == _MAGIC:
            self._load_footer()
        elif head == _MAGIC_V1:
            self._build_index_v1()
        else:
            raise IOError(f"{path}: bad sstable magic")

    def _load_footer(self) -> None:
        mm = self._mm
        ntables, footer_start = _TRAILER.unpack_from(
            mm, len(mm) - _TRAILER.size)
        off = footer_start
        for _ in range(ntables):
            (tlen,) = _U16.unpack_from(mm, off)
            off += 2
            table = mm[off:off + tlen].decode()
            off += tlen
            (nkeys,) = _U32.unpack_from(mm, off)
            off += 4
            lens_be = mm[off:off + 4 * nkeys]
            off += 4 * nkeys
            offs = np.frombuffer(mm, ">u8", nkeys, off).tolist()
            off += 8 * nkeys
            blob_len = int(np.frombuffer(lens_be, ">u4").sum())
            keys = _slice_varlen(mm[off:off + blob_len], lens_be)
            off += blob_len
            self._index[table] = (keys, offs)

    def _build_index_v1(self) -> None:
        mm, off, end = self._mm, len(_MAGIC_V1), len(self._mm)
        while off < end:
            start = off
            (tlen,) = _U16.unpack_from(mm, off)
            off += 2
            table = mm[off:off + tlen].decode()
            off += tlen
            (klen,) = _U16.unpack_from(mm, off)
            off += 2
            key = bytes(mm[off:off + klen])
            off += klen
            (ncells,) = _U32.unpack_from(mm, off)
            off += 4
            for _ in range(ncells):
                (flen,) = _U16.unpack_from(mm, off)
                off += 2 + flen
                (qlen,) = _U16.unpack_from(mm, off)
                off += 2 + qlen
                (vlen,) = _U32.unpack_from(mm, off)
                off += 4 + vlen
            keys, offs = self._index.setdefault(table, ([], []))
            keys.append(key)
            offs.append(start)

    def close(self) -> None:
        self._mm.close()
        self._f.close()

    def tables(self) -> list[str]:
        return list(self._index)

    def key_count(self, table: str) -> int:
        idx = self._index.get(table)
        return len(idx[0]) if idx else 0

    def key_bounds(self, table: str) -> tuple[bytes, bytes] | None:
        """(smallest, largest) row key stored for ``table``, or None
        when the table is absent — a batch existence prefilter: keys
        outside this range cannot be in the sstable, which lets
        time-ordered ingest (new base-times sort after every spilled
        key) skip the per-key bisect entirely."""
        idx = self._index.get(table)
        if not idx or not idx[0]:
            return None
        keys = idx[0]
        return keys[0], keys[-1]

    def has_key(self, table: str, key: bytes) -> bool:
        idx = self._index.get(table)
        if not idx:
            return False
        keys, _ = idx
        i = bisect_left(keys, key)
        return i < len(keys) and keys[i] == key

    def _read_row(self, off: int) -> list[tuple[bytes, bytes, bytes]]:
        mm = self._mm
        (tlen,) = _U16.unpack_from(mm, off)
        off += 2 + tlen
        (klen,) = _U16.unpack_from(mm, off)
        off += 2 + klen
        (ncells,) = _U32.unpack_from(mm, off)
        off += 4
        cells = []
        for _ in range(ncells):
            (flen,) = _U16.unpack_from(mm, off)
            off += 2
            fam = bytes(mm[off:off + flen])
            off += flen
            (qlen,) = _U16.unpack_from(mm, off)
            off += 2
            qual = bytes(mm[off:off + qlen])
            off += qlen
            (vlen,) = _U32.unpack_from(mm, off)
            off += 4
            value = bytes(mm[off:off + vlen])
            off += vlen
            cells.append((fam, qual, value))
        return cells

    def get(self, table: str,
            key: bytes) -> list[tuple[bytes, bytes, bytes]] | None:
        """Cells of one row, or None when the key is absent."""
        idx = self._index.get(table)
        if not idx:
            return None
        keys, offs = idx
        i = bisect_left(keys, key)
        if i >= len(keys) or keys[i] != key:
            return None
        return self._read_row(offs[i])

    def scan_keys(self, table: str, start: bytes,
                  stop: bytes | None) -> list[bytes]:
        idx = self._index.get(table)
        if not idx:
            return []
        keys, _ = idx
        lo = bisect_left(keys, start)
        hi = bisect_left(keys, stop) if stop else len(keys)
        return keys[lo:hi]

    def iter_rows(self, table: str) -> Iterator[
            tuple[bytes, list[tuple[bytes, bytes, bytes]]]]:
        idx = self._index.get(table)
        if not idx:
            return
        keys, offs = idx
        for key, off in zip(keys, offs):
            yield key, self._read_row(off)
