"""Immutable sorted-table file: the spill tier under the memtable.

The reference delegates at-rest storage to HBase HFiles; here a checkpoint
merges the memtable (and the previous generation, if any) into ONE sorted
immutable file per store, after which the WAL is truncated — bounding both
recovery time and memtable RAM for long-running daemons (SURVEY §5.4,
§7.2: "enough LSM to sustain ingest while scans run, without rebuilding
HBase").

File layout (all integers big-endian):
    magic  b"TSST1"
    record*  :=  [u16 table_len][table][u16 key_len][key][u32 ncells]
                 ([u16 fam_len][fam][u16 q_len][q][u32 v_len][v])*
    records sorted by (table, key); one record per row.

The reader mmaps the file and keeps only (key -> offset) indexes in RAM;
cell payloads are decoded lazily per row, so a spilled store serves gets
and scans without rehydrating the dataset.
"""

from __future__ import annotations

import mmap
import os
import struct
from bisect import bisect_left
from typing import Iterable, Iterator

_MAGIC = b"TSST1"
_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")

# row := (table, key, [(family, qualifier, value), ...])
Row = tuple[str, bytes, list[tuple[bytes, bytes, bytes]]]


def write_sstable(path: str, rows: Iterable[Row]) -> int:
    """Write rows (pre-sorted by (table, key)) to a new sstable at `path`.

    Returns the number of rows written. Writes via a temp file + atomic
    rename so a crash mid-write never corrupts the previous generation.
    """
    tmp = path + ".tmp"
    n = 0
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        for table, key, cells in rows:
            tb = table.encode()
            parts = [_U16.pack(len(tb)), tb, _U16.pack(len(key)), key,
                     _U32.pack(len(cells))]
            for fam, qual, value in cells:
                parts += [_U16.pack(len(fam)), fam, _U16.pack(len(qual)),
                          qual, _U32.pack(len(value)), value]
            f.write(b"".join(parts))
            n += 1
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # Make the rename itself durable before the caller truncates its WAL:
    # without the directory fsync a power loss could surface the OLD
    # generation alongside an already-truncated WAL.
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return n


class SSTable:
    """mmap-backed reader over one sstable generation."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, "rb")
        size = os.fstat(self._f.fileno()).st_size
        self._mm = mmap.mmap(self._f.fileno(), size, access=mmap.ACCESS_READ)
        if self._mm[:len(_MAGIC)] != _MAGIC:
            raise IOError(f"{path}: bad sstable magic")
        # table -> (sorted keys, parallel row offsets)
        self._index: dict[str, tuple[list[bytes], list[int]]] = {}
        self._build_index()

    def _build_index(self) -> None:
        mm, off, end = self._mm, len(_MAGIC), len(self._mm)
        while off < end:
            start = off
            (tlen,) = _U16.unpack_from(mm, off)
            off += 2
            table = mm[off:off + tlen].decode()
            off += tlen
            (klen,) = _U16.unpack_from(mm, off)
            off += 2
            key = bytes(mm[off:off + klen])
            off += klen
            (ncells,) = _U32.unpack_from(mm, off)
            off += 4
            for _ in range(ncells):
                (flen,) = _U16.unpack_from(mm, off)
                off += 2 + flen
                (qlen,) = _U16.unpack_from(mm, off)
                off += 2 + qlen
                (vlen,) = _U32.unpack_from(mm, off)
                off += 4 + vlen
            keys, offs = self._index.setdefault(table, ([], []))
            keys.append(key)
            offs.append(start)

    def close(self) -> None:
        self._mm.close()
        self._f.close()

    def tables(self) -> list[str]:
        return list(self._index)

    def key_count(self, table: str) -> int:
        idx = self._index.get(table)
        return len(idx[0]) if idx else 0

    def key_bounds(self, table: str) -> tuple[bytes, bytes] | None:
        """(smallest, largest) row key stored for ``table``, or None
        when the table is absent — a batch existence prefilter: keys
        outside this range cannot be in the sstable, which lets
        time-ordered ingest (new base-times sort after every spilled
        key) skip the per-key bisect entirely."""
        idx = self._index.get(table)
        if not idx or not idx[0]:
            return None
        keys = idx[0]
        return keys[0], keys[-1]

    def has_key(self, table: str, key: bytes) -> bool:
        idx = self._index.get(table)
        if not idx:
            return False
        keys, _ = idx
        i = bisect_left(keys, key)
        return i < len(keys) and keys[i] == key

    def _read_row(self, off: int) -> list[tuple[bytes, bytes, bytes]]:
        mm = self._mm
        (tlen,) = _U16.unpack_from(mm, off)
        off += 2 + tlen
        (klen,) = _U16.unpack_from(mm, off)
        off += 2 + klen
        (ncells,) = _U32.unpack_from(mm, off)
        off += 4
        cells = []
        for _ in range(ncells):
            (flen,) = _U16.unpack_from(mm, off)
            off += 2
            fam = bytes(mm[off:off + flen])
            off += flen
            (qlen,) = _U16.unpack_from(mm, off)
            off += 2
            qual = bytes(mm[off:off + qlen])
            off += qlen
            (vlen,) = _U32.unpack_from(mm, off)
            off += 4
            value = bytes(mm[off:off + vlen])
            off += vlen
            cells.append((fam, qual, value))
        return cells

    def get(self, table: str,
            key: bytes) -> list[tuple[bytes, bytes, bytes]] | None:
        """Cells of one row, or None when the key is absent."""
        idx = self._index.get(table)
        if not idx:
            return None
        keys, offs = idx
        i = bisect_left(keys, key)
        if i >= len(keys) or keys[i] != key:
            return None
        return self._read_row(offs[i])

    def scan_keys(self, table: str, start: bytes,
                  stop: bytes | None) -> list[bytes]:
        idx = self._index.get(table)
        if not idx:
            return []
        keys, _ = idx
        lo = bisect_left(keys, start)
        hi = bisect_left(keys, stop) if stop else len(keys)
        return keys[lo:hi]

    def iter_rows(self, table: str) -> Iterator[
            tuple[bytes, list[tuple[bytes, bytes, bytes]]]]:
        idx = self._index.get(table)
        if not idx:
            return
        keys, offs = idx
        for key, off in zip(keys, offs):
            yield key, self._read_row(off)
