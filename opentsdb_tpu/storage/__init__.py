"""Embedded ordered-KV storage engine.

Provides exactly the primitives the reference consumes from HBase through
asynchbase (SURVEY.md §2.9/§5.8): ordered scans over [start, stop) with an
optional key regexp, single-key get/put/delete-qualifiers, atomic increment,
compare-and-set, a durability bit, and PleaseThrottle backpressure.
"""

from opentsdb_tpu.storage.kv import Cell, KVStore, MemKVStore
from opentsdb_tpu.storage.sharded import ShardedKVStore

__all__ = ["Cell", "KVStore", "MemKVStore", "ShardedKVStore"]
