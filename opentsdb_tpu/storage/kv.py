"""Ordered key-value store: in-memory memtable + append-only WAL.

This engine stands in for the HBase cluster of the reference deployment. The
API surface is intentionally the exact set of primitives OpenTSDB uses via
asynchbase (reference src/core/TSDB.java:479-494 get/put/delete;
src/uid/UniqueId.java:243,297,326 atomicIncrement/compareAndSet;
src/core/TsdbQuery.java:368-492 ordered scan + key regexp), so the layers
above translate one-to-one while staying storage-agnostic behind ``KVStore``.

Design notes (TPU-first, not an HBase rebuild):
- Rows live in a dict keyed by row key; each row is a dict keyed by
  (family, qualifier). Scans sort lazily: the sorted key index is rebuilt
  only when a scan happens after inserts, keeping the hot ingest path O(1)
  per put — the analog of an LSM memtable without the merge machinery.
- Durability is an append-only WAL with length-prefixed records, replayed on
  open. ``durable=False`` puts skip the WAL (batch-import mode, parity with
  setDurable(false), reference IncomingDataPoints.java:253).
- Backpressure: once the row count crosses ``throttle_rows``, writes raise
  PleaseThrottleError until a flush/compaction shrinks it — the analog of
  HBase's PleaseThrottleException signal.
"""

from __future__ import annotations

import io
import os
import re
import struct
import threading
from bisect import bisect_left
from typing import Iterator, NamedTuple

from opentsdb_tpu.core.errors import PleaseThrottleError

_REC = struct.Struct(">BI")  # op, payload length


class Cell(NamedTuple):
    key: bytes
    family: bytes
    qualifier: bytes
    value: bytes


class KVStore:
    """Abstract ordered-KV interface; see MemKVStore for the semantics."""

    def get(self, table: str, key: bytes,
            family: bytes | None = None) -> list[Cell]:
        raise NotImplementedError

    def has_row(self, table: str, key: bytes) -> bool:
        return bool(self.get(table, key))

    def put(self, table: str, key: bytes, family: bytes, qualifier: bytes,
            value: bytes, durable: bool = True) -> None:
        raise NotImplementedError

    def delete(self, table: str, key: bytes, family: bytes,
               qualifiers: list[bytes]) -> None:
        raise NotImplementedError

    def delete_row(self, table: str, key: bytes) -> None:
        raise NotImplementedError

    def scan(self, table: str, start: bytes, stop: bytes,
             family: bytes | None = None,
             key_regexp: bytes | None = None) -> Iterator[list[Cell]]:
        raise NotImplementedError

    def atomic_increment(self, table: str, key: bytes, family: bytes,
                         qualifier: bytes, amount: int = 1) -> int:
        raise NotImplementedError

    def compare_and_set(self, table: str, key: bytes, family: bytes,
                        qualifier: bytes, expected: bytes | None,
                        value: bytes) -> bool:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def ensure_table(self, table: str) -> None:
        raise NotImplementedError


class _Table:
    __slots__ = ("rows", "sorted_keys", "dirty")

    def __init__(self) -> None:
        self.rows: dict[bytes, dict[tuple[bytes, bytes], bytes]] = {}
        self.sorted_keys: list[bytes] = []
        self.dirty = False  # sorted_keys is stale

    def index(self) -> list[bytes]:
        if self.dirty:
            self.sorted_keys = sorted(self.rows)
            self.dirty = False
        return self.sorted_keys


# WAL opcodes
_OP_PUT = 1
_OP_DELETE = 2
_OP_DELETE_ROW = 3


class MemKVStore(KVStore):
    """In-memory ordered KV with optional WAL persistence.

    Thread-safe: a single lock guards all mutation (ingest is batched above
    this layer, so lock traffic is per-batch, not per-point).
    """

    def __init__(self, wal_path: str | None = None,
                 throttle_rows: int | None = None,
                 fsync: bool = False) -> None:
        self._tables: dict[str, _Table] = {}
        self._lock = threading.RLock()
        self.throttle_rows = throttle_rows
        self._fsync = fsync
        self._wal_path = wal_path
        self._wal: io.BufferedWriter | None = None
        if wal_path:
            valid_bytes = 0
            if os.path.exists(wal_path):
                valid_bytes = self._replay(wal_path)
                if valid_bytes < os.path.getsize(wal_path):
                    # Torn record at the tail (crash mid-write): truncate it
                    # away so appends continue from the last valid boundary —
                    # otherwise the next replay would stop at the garbage and
                    # silently drop everything written after it.
                    with open(wal_path, "r+b") as f:
                        f.truncate(valid_bytes)
            self._wal = open(wal_path, "ab")

    # -- table helpers ----------------------------------------------------

    def _table(self, name: str) -> _Table:
        t = self._tables.get(name)
        if t is None:
            t = self._tables[name] = _Table()
        return t

    def ensure_table(self, table: str) -> None:
        with self._lock:
            self._table(table)

    def row_count(self, table: str) -> int:
        return len(self._table(table).rows)

    def has_row(self, table: str, key: bytes) -> bool:
        return key in self._table(table).rows

    def cell_count(self, table: str, key: bytes) -> int:
        row = self._table(table).rows.get(key)
        return len(row) if row else 0

    # -- WAL --------------------------------------------------------------

    def _wal_append(self, op: int, *parts: bytes) -> None:
        if self._wal is None:
            return
        payload = b"".join(struct.pack(">I", len(p)) + p for p in parts)
        self._wal.write(_REC.pack(op, len(payload)) + payload)
        if self._fsync:
            self._wal.flush()
            os.fsync(self._wal.fileno())

    @staticmethod
    def _split_payload(payload: bytes) -> list[bytes]:
        parts = []
        off = 0
        while off < len(payload):
            (n,) = struct.unpack_from(">I", payload, off)
            off += 4
            parts.append(payload[off:off + n])
            off += n
        return parts

    def _replay(self, path: str) -> int:
        """Apply every complete WAL record; returns the valid byte count."""
        valid = 0
        with open(path, "rb") as f:
            while True:
                hdr = f.read(_REC.size)
                if len(hdr) < _REC.size:
                    break  # truncated tail: stop at last complete record
                op, plen = _REC.unpack(hdr)
                payload = f.read(plen)
                if len(payload) < plen:
                    break
                valid += _REC.size + plen
                parts = self._split_payload(payload)
                table = parts[0].decode()
                if op == _OP_PUT:
                    _, key, fam, qual, value = parts
                    self._apply_put(table, key, fam, qual, value)
                elif op == _OP_DELETE:
                    _, key, fam, *quals = parts
                    self._apply_delete(table, key, fam, quals)
                elif op == _OP_DELETE_ROW:
                    _, key = parts
                    self._apply_delete_row(table, key)
        return valid

    def flush(self) -> None:
        """Force WAL to stable storage (reference: HBaseClient.flush)."""
        with self._lock:
            if self._wal is not None:
                self._wal.flush()
                os.fsync(self._wal.fileno())

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                self.flush()
                self._wal.close()
                self._wal = None

    # -- mutation ---------------------------------------------------------

    def _apply_put(self, table: str, key: bytes, family: bytes,
                   qualifier: bytes, value: bytes) -> None:
        t = self._table(table)
        row = t.rows.get(key)
        if row is None:
            row = t.rows[key] = {}
            t.dirty = True
        row[(family, qualifier)] = value

    def _apply_delete(self, table: str, key: bytes, family: bytes,
                      qualifiers: list[bytes]) -> None:
        t = self._table(table)
        row = t.rows.get(key)
        if row is None:
            return
        for q in qualifiers:
            row.pop((family, q), None)
        if not row:
            del t.rows[key]
            t.dirty = True

    def _apply_delete_row(self, table: str, key: bytes) -> None:
        t = self._table(table)
        if t.rows.pop(key, None) is not None:
            t.dirty = True

    def _check_throttle(self, table: str, key: bytes) -> None:
        # Only throttle puts that would create a NEW row: updates to
        # existing rows (including compaction rewrites, which relieve
        # pressure) must keep flowing or backpressure can never clear.
        if self.throttle_rows is not None and \
                len(self._table(table).rows) >= self.throttle_rows and \
                key not in self._table(table).rows:
            raise PleaseThrottleError(
                f"table '{table}' holds >= {self.throttle_rows} rows")

    def put(self, table: str, key: bytes, family: bytes, qualifier: bytes,
            value: bytes, durable: bool = True) -> None:
        with self._lock:
            self._check_throttle(table, key)
            if durable:
                self._wal_append(_OP_PUT, table.encode(), key, family,
                                 qualifier, value)
            self._apply_put(table, key, family, qualifier, value)

    def delete(self, table: str, key: bytes, family: bytes,
               qualifiers: list[bytes]) -> None:
        with self._lock:
            self._wal_append(_OP_DELETE, table.encode(), key, family,
                             *qualifiers)
            self._apply_delete(table, key, family, qualifiers)

    def delete_row(self, table: str, key: bytes) -> None:
        with self._lock:
            self._wal_append(_OP_DELETE_ROW, table.encode(), key)
            self._apply_delete_row(table, key)

    # -- reads ------------------------------------------------------------

    def get(self, table: str, key: bytes,
            family: bytes | None = None) -> list[Cell]:
        with self._lock:
            row = self._table(table).rows.get(key)
            if not row:
                return []
            cells = [Cell(key, f, q, v) for (f, q), v in row.items()
                     if family is None or f == family]
            cells.sort(key=lambda c: (c.family, c.qualifier))
            return cells

    def scan(self, table: str, start: bytes, stop: bytes,
             family: bytes | None = None,
             key_regexp: bytes | None = None) -> Iterator[list[Cell]]:
        """Yield one sorted cell-list per row with key in [start, stop).

        ``key_regexp`` applies a DOTALL bytes regex to the whole key —
        parity with the HBase KeyRegexpFilter used for tag filtering
        (reference TsdbQuery.createAndSetFilter :433-492).

        Snapshot semantics: keys are snapshotted at call time; rows deleted
        mid-scan are skipped, rows mutated mid-scan show their new cells —
        the same weak guarantees an HBase scanner gives across RPC batches.
        """
        pattern = re.compile(key_regexp, re.S) if key_regexp else None
        with self._lock:
            index = self._table(table).index()
            lo = bisect_left(index, start)
            hi = bisect_left(index, stop) if stop else len(index)
            keys = index[lo:hi]
        for key in keys:
            if pattern is not None and not pattern.match(key):
                continue
            with self._lock:
                row = self._table(table).rows.get(key)
                if not row:
                    continue
                cells = [Cell(key, f, q, v) for (f, q), v in row.items()
                         if family is None or f == family]
            cells.sort(key=lambda c: (c.family, c.qualifier))
            if cells:
                yield cells

    # -- atomics ----------------------------------------------------------

    def atomic_increment(self, table: str, key: bytes, family: bytes,
                         qualifier: bytes, amount: int = 1) -> int:
        """Increment an 8-byte big-endian counter cell, returning the new
        value (initialized from 0 like HBase's ICV)."""
        with self._lock:
            row = self._table(table).rows.get(key)
            cur = row.get((family, qualifier)) if row else None
            value = (struct.unpack(">q", cur)[0] if cur else 0) + amount
            packed = struct.pack(">q", value)
            self._wal_append(_OP_PUT, table.encode(), key, family, qualifier,
                             packed)
            self._apply_put(table, key, family, qualifier, packed)
            return value

    def compare_and_set(self, table: str, key: bytes, family: bytes,
                        qualifier: bytes, expected: bytes | None,
                        value: bytes) -> bool:
        """Atomic CAS: write only if the cell currently equals ``expected``
        (None = cell must not exist). Returns success."""
        with self._lock:
            row = self._table(table).rows.get(key)
            cur = row.get((family, qualifier)) if row else None
            if cur != expected:
                return False
            self._wal_append(_OP_PUT, table.encode(), key, family, qualifier,
                             value)
            self._apply_put(table, key, family, qualifier, value)
            return True
