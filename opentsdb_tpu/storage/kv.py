"""Ordered key-value store: in-memory memtable + append-only WAL.

This engine stands in for the HBase cluster of the reference deployment. The
API surface is intentionally the exact set of primitives OpenTSDB uses via
asynchbase (reference src/core/TSDB.java:479-494 get/put/delete;
src/uid/UniqueId.java:243,297,326 atomicIncrement/compareAndSet;
src/core/TsdbQuery.java:368-492 ordered scan + key regexp), so the layers
above translate one-to-one while staying storage-agnostic behind ``KVStore``.

Design notes (TPU-first, not an HBase rebuild):
- Rows live in a dict keyed by row key; each row is a dict keyed by
  (family, qualifier). Scans sort lazily: the sorted key index is rebuilt
  only when a scan happens after inserts, keeping the hot ingest path O(1)
  per put — the analog of an LSM memtable without the merge machinery.
- Durability is an append-only WAL with length-prefixed records, replayed on
  open. ``durable=False`` puts skip the WAL (batch-import mode, parity with
  setDurable(false), reference IncomingDataPoints.java:253).
- Backpressure: once the row count crosses ``throttle_rows``, writes raise
  PleaseThrottleError until a flush/compaction shrinks it — the analog of
  HBase's PleaseThrottleException signal.
- Checkpoint/resume (SURVEY §5.4): ``checkpoint()`` merges the memtable
  (plus the previous spill generation) into one immutable sorted sstable
  (storage/sstable.py), then truncates the WAL — bounding recovery time
  and memtable RAM. On open: load sstable, then replay the WAL suffix.
  Reads merge the tiers, memtable winning; deletes over spilled rows
  leave tombstones (cell tombstone = None value; row tombstones in
  ``_Table.row_tombs``) so compaction's put-then-delete-originals cycle
  stays correct across the spill boundary.
- Checkpoint does NOT stall ingest: under the lock it only freezes the
  current memtable as an immutable middle tier and rotates the WAL
  (pre-checkpoint records move to ``<wal>.old``); the dataset merge and
  sstable write run outside the lock while writes land in a fresh
  memtable + fresh WAL; a second brief lock swaps generations and
  removes ``<wal>.old``. Crash at any point recovers by replaying
  ``<wal>.old`` then the WAL over whichever sstable generation survived
  — replay is idempotent (puts rewrite equal values, deletes re-create
  tombstones, counter increments are logged as absolute values).
"""

from __future__ import annotations

import fcntl
import io
import logging
import os
import re
import struct
import threading
import zlib
from time import perf_counter as _perf
from bisect import bisect_left
from typing import Iterator, NamedTuple

import numpy as np

from opentsdb_tpu.core.const import TIMESTAMP_BYTES, UID_WIDTH
from opentsdb_tpu.core.errors import (PleaseThrottleError,
                                       ReadOnlyStoreError)
from opentsdb_tpu.fault.faultpoints import fire as _fault
from opentsdb_tpu.obs import trace as _trace
from opentsdb_tpu.obs.registry import METRICS as _metrics
from opentsdb_tpu.storage.sstable import (SSTable, merge_sstables,
                                          write_sstable_bulk)
from opentsdb_tpu.utils.nativeext import ext as _EXT

_REC = struct.Struct(">BI")  # op, payload length

# Engine instruments (obs/registry.py): registered once at import, so
# the hot paths pay one attribute increment / one perf_counter pair
# per WAL *batch* or checkpoint phase — never per point.
_M_WAL_APPENDS = _metrics.counter("wal.appends")
_M_WAL_BYTES = _metrics.counter("wal.append_bytes")
_M_WAL_APPEND = _metrics.timer("wal.append")
_M_WAL_FSYNC = _metrics.timer("wal.fsync")
# Group commit (Config.wal_group_ms): batches = append calls whose
# flush was deferred to a group leader, points = WAL records inside
# them, fsyncs = covering group flushes, wait_ms = time ack paths
# spent parked in the barrier.
_M_GRP_BATCHES = _metrics.counter("wal.group.batches")
_M_GRP_POINTS = _metrics.counter("wal.group.points")
_M_GRP_FSYNCS = _metrics.counter("wal.group.fsyncs")
_M_GRP_WAIT = _metrics.timer("wal.group.wait_ms")
_M_CKPT_PHASE = {ph: _metrics.timer("checkpoint.phase", {"phase": ph})
                 for ph in ("freeze", "spill", "commit")}

# Row-key byte range holding the base time (data-table layout,
# core/codec.row_key). The incremental dirty-base index slices it per
# NEW ROW so consumers (the rollup planner's dirty-window set, the
# executor's fragment cache) never have to sweep the whole key list;
# keys too short to carry it (UID-table names, stray tool deletes) are
# simply not indexed — matching the sweep's own filter.
_BASE_LO = UID_WIDTH
_BASE_HI = UID_WIDTH + TIMESTAMP_BYTES


class Cell(NamedTuple):
    key: bytes
    family: bytes
    qualifier: bytes
    value: bytes


class KVStore:
    """Abstract ordered-KV interface; see MemKVStore for the semantics."""

    def get(self, table: str, key: bytes,
            family: bytes | None = None) -> list[Cell]:
        raise NotImplementedError

    def has_row(self, table: str, key: bytes) -> bool:
        return bool(self.get(table, key))

    def put(self, table: str, key: bytes, family: bytes, qualifier: bytes,
            value: bytes, durable: bool = True) -> None:
        raise NotImplementedError

    def put_many(self, table: str, family: bytes,
                 cells: list[tuple[bytes, bytes, bytes]],
                 durable: bool = True, sync: bool = True) -> list[bool]:
        """Write (key, qualifier, value) cells; returns, per cell, True
        when the row holds other cells by the time this one lands —
        either it existed before the batch, or an earlier cell of the
        batch already hit it (both mean the caller must queue
        compaction). On PleaseThrottleError mid-batch the exception's
        ``partial_existed`` carries the flags for the cells that DID
        apply. Default loops over put(); MemKVStore overrides with a
        single-lock batch. ``sync=False`` defers the WAL group-commit
        wait (stores without group commit ignore it): the caller must
        issue ``wal_barrier()`` before acknowledging.
        """
        existed: list[bool] = []
        seen: set[bytes] = set()
        for key, qualifier, value in cells:
            try:
                prior = key in seen or self.has_row(table, key)
                self.put(table, key, family, qualifier, value, durable)
            except PleaseThrottleError as e:
                e.partial_existed = existed
                raise
            existed.append(prior)
            seen.add(key)
        return existed

    def put_many_columnar(self, table: str, family: bytes,
                          key_blob: bytes, key_len: int,
                          quals: list[bytes], vals: list[bytes],
                          durable: bool = True,
                          sync: bool = True) -> list[bool]:
        """put_many with columnar inputs: cell i's key is the i-th
        ``key_len``-byte slice of ``key_blob``. Semantics identical to
        ``put_many`` on the zipped triples; exists so the batch ingest
        hot path (core/tsdb.py add_batch) never materializes a
        per-cell tuple list. Default zips and delegates; MemKVStore
        overrides with bulk dict operations and a columnar WAL record."""
        keys = [key_blob[i:i + key_len]
                for i in range(0, key_len * len(quals), key_len)]
        return self.put_many(table, family, list(zip(keys, quals, vals)),
                             durable=durable, sync=sync)

    def delete(self, table: str, key: bytes, family: bytes,
               qualifiers: list[bytes]) -> None:
        raise NotImplementedError

    def delete_row(self, table: str, key: bytes) -> None:
        raise NotImplementedError

    def scan(self, table: str, start: bytes, stop: bytes,
             family: bytes | None = None,
             key_regexp: bytes | None = None) -> Iterator[list[Cell]]:
        raise NotImplementedError

    def scan_raw(self, table: str, start: bytes, stop: bytes,
                 family: bytes | None = None,
                 key_regexp: bytes | None = None,
                 series_hint: "np.ndarray | None" = None,
                 ) -> Iterator[tuple[bytes, list[tuple[bytes, bytes]]]]:
        """Scan for bulk decode: (key, [(qualifier, value), ...]) rows,
        qualifiers sorted — no Cell objects. Default adapts scan();
        stores override with a batched implementation (the columnar
        read path calls this per row-HOUR, so per-row allocation and
        locking overhead multiplies by the whole scanned range).

        ``series_hint``: optional uint64 array of series-identity
        hashes (sstable.series_hash) that is a SUPERSET of the series
        the caller will keep — a pure pruning hint. Stores may use it
        to skip sstable generations (bloom prefilter) or whole shards
        (routing); ignoring it is always correct."""
        for cells in self.scan(table, start, stop, family=family,
                               key_regexp=key_regexp):
            yield cells[0].key, [(c.qualifier, c.value) for c in cells]

    def atomic_increment(self, table: str, key: bytes, family: bytes,
                         qualifier: bytes, amount: int = 1) -> int:
        raise NotImplementedError

    def compare_and_set(self, table: str, key: bytes, family: bytes,
                        qualifier: bytes, expected: bytes | None,
                        value: bytes) -> bool:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def wal_barrier(self, ticket: int | None = None) -> None:
        """Wait for the WAL group-commit flush covering everything
        appended so far (see MemKVStore). Default: no-op — stores
        without group commit are already durable at return from every
        mutation."""

    def ensure_table(self, table: str) -> None:
        raise NotImplementedError


def _merge_unique(a: list[bytes], b: list[bytes]) -> list[bytes]:
    """Merge two sorted unique lists into one, dropping cross-duplicates
    (a key deleted and re-inserted can appear in both runs)."""
    out: list[bytes] = []
    i = j = 0
    na, nb = len(a), len(b)
    while i < na and j < nb:
        ka, kb = a[i], b[j]
        if ka < kb:
            out.append(ka)
            i += 1
        elif kb < ka:
            out.append(kb)
            j += 1
        else:
            out.append(ka)
            i += 1
            j += 1
    out.extend(a[i:])
    out.extend(b[j:])
    return out


class _Table:
    """Row storage + an incremental sorted key index.

    The index is a sqrt-decomposition over two sorted runs: ``base``
    (large, rebuilt rarely) and ``delta`` (small, absorbing recent
    inserts), plus an unsorted ``pending`` set for brand-new keys.
    Inserts are O(1) (set add); a scan absorbs pending into delta
    (O(P log P + D)) and folds delta into base only when delta outgrows
    ~sqrt(base) — so interleaved put/scan traffic no longer pays the old
    O(rows log rows) full re-sort per scan (the dashboard-poll +
    continuous-ingest hot pattern; fills the role of the LSM memtable
    index in front of HBase's store files, reference
    TsdbQuery.java:240-285 scan hot loop). Runs may carry stale (deleted)
    keys; readers filter on ``k in rows`` and a purge rewrites the runs
    when stale entries dominate.
    """

    __slots__ = ("rows", "base", "delta", "pending", "stale", "row_tombs",
                 "tombs", "dirty", "touch")

    def __init__(self) -> None:
        # Cell value None = tombstone masking a spilled sstable cell.
        self.rows: dict[bytes, dict[tuple[bytes, bytes], bytes | None]] = {}
        self.base: list[bytes] = []
        self.delta: list[bytes] = []
        self.pending: set[bytes] = set()
        self.stale = 0  # deleted keys still present in base/delta
        self.row_tombs: set[bytes] = set()  # whole-row masks over the sstable
        # Count of cell tombstones ever written into rows (checkpoint
        # uses it to pick the fast memtable-only spill: a tier with no
        # tombstones cannot mask lower-generation cells, so spilling it
        # as a new generation needs no merge).
        self.tombs = 0
        # Incremental dirty-base index: base-time -> refcount of keys
        # (rows + row_tombs entries, counted separately — a key can be
        # in both) whose base-time bytes name it. Maintained O(1) per
        # row insert/remove so ``dirty_bases`` never sweeps the key
        # list (the planner used to re-sweep the whole memtable under
        # this lock on every rollup-eligible query).
        self.dirty: dict[int, int] = {}
        # Touch sequence per base: the store mutation_seq of the last
        # row-create/remove transition. A create-then-full-delete nets
        # the refcount back to zero — the base reads CLEAN again — but
        # a fragment scanned DURING that window may hold the transient
        # row; the touch value outlives the refcount so such fragments
        # can never validate (fragment-cache contract,
        # MemKVStore.chunk_state).
        self.touch: dict[int, int] = {}

    def note_insert(self, key: bytes) -> None:
        self.pending.add(key)

    def note_delete(self) -> None:
        self.stale += 1

    def dirty_add(self, key: bytes, seq: int) -> None:
        if len(key) >= _BASE_HI:
            b = int.from_bytes(key[_BASE_LO:_BASE_HI], "big")
            d = self.dirty
            d[b] = d.get(b, 0) + 1
            self.touch[b] = seq

    def dirty_sub(self, key: bytes, seq: int) -> None:
        if len(key) >= _BASE_HI:
            b = int.from_bytes(key[_BASE_LO:_BASE_HI], "big")
            d = self.dirty
            n = d.get(b, 0) - 1
            if n <= 0:
                d.pop(b, None)
            else:
                d[b] = n
            self.touch[b] = seq

    def rebuild_dirty(self, seq: int) -> None:
        """Recompute the dirty-base index from scratch (the thaw path,
        where refcount bookkeeping through the merge-back would be
        error-prone for an exceptional branch). Every involved base's
        touch jumps to ``seq`` — conservative invalidation of any
        fragment built across the thaw."""
        d: dict[int, int] = {}
        for ks in (self.rows, self.row_tombs):
            for k in ks:
                if len(k) >= _BASE_HI:
                    b = int.from_bytes(k[_BASE_LO:_BASE_HI], "big")
                    d[b] = d.get(b, 0) + 1
        for b in d:
            self.touch[b] = seq
        self.dirty = d

    def _absorb(self) -> None:
        """Fold pending inserts into delta; compact when thresholds hit.
        Caller holds the store lock."""
        if self.pending:
            new = sorted(self.pending)
            self.pending.clear()
            self.delta = _merge_unique(self.delta, new) if self.delta \
                else new
        if len(self.delta) ** 2 > max(len(self.base), 64):
            self.base = _merge_unique(self.base, self.delta)
            self.delta = []
        if self.stale * 2 > len(self.base) + len(self.delta):
            rows = self.rows
            self.base = [k for k in self.base if k in rows]
            self.delta = [k for k in self.delta if k in rows]
            self.stale = 0

    def range_keys(self, start: bytes, stop: bytes | None) -> list[bytes]:
        """Sorted live keys in [start, stop); stop falsy = to the end.
        Merge-iterates the two runs, skipping stale keys and
        cross-duplicates. Caller holds the store lock."""
        self._absorb()
        a, b = self.base, self.delta
        i, j = bisect_left(a, start), bisect_left(b, start)
        ahi = bisect_left(a, stop) if stop else len(a)
        bhi = bisect_left(b, stop) if stop else len(b)
        rows = self.rows
        out: list[bytes] = []
        while i < ahi and j < bhi:
            ka, kb = a[i], b[j]
            if ka < kb:
                k = ka
                i += 1
            elif kb < ka:
                k = kb
                j += 1
            else:
                k = ka
                i += 1
                j += 1
            if k in rows:
                out.append(k)
        for k in a[i:ahi]:
            if k in rows:
                out.append(k)
        for k in b[j:bhi]:
            if k in rows:
                out.append(k)
        return out


# WAL opcodes
_OP_PUT = 1
_OP_DELETE = 2
_OP_DELETE_ROW = 3
_OP_PUT_BATCH = 4   # one record for a whole put_many batch
# WAL segment epoch header (cluster/epoch.py): a cluster-mode writer
# begins every WAL segment it opens with its epoch, and replay refuses
# any segment whose header epoch is LOWER than one already seen — the
# on-disk artifact of a split brain (a deposed writer's records landing
# after a newer writer's) is cut at the fence line, never applied.
_OP_EPOCH = 5


class MemKVStore(KVStore):
    """In-memory ordered KV with optional WAL persistence.

    Thread-safe: a single lock guards all mutation (ingest is batched above
    this layer, so lock traffic is per-batch, not per-point).
    """

    # Sabotage gate for the crash matrix (fault/harness.py --bug
    # ack-before-fsync): True makes _wal_barrier return immediately,
    # acking group-commit writes before their covering fsync — the
    # exact regression the kv.wal.group.* matrix rows must catch.
    _ACK_BEFORE_FSYNC = False

    def __init__(self, wal_path: str | None = None,
                 throttle_rows: int | None = None,
                 fsync: bool = False, read_only: bool = False,
                 max_generations: int | None = None,
                 writer_epoch: int | None = None,
                 epoch_guard=None) -> None:
        """``max_generations`` overrides the sstable generation cap
        (default ``_MAX_GENERATIONS``); the sharded store staggers it
        per shard so size-tiered collapses don't fire on the same
        checkpoint across shards.

        ``writer_epoch`` (cluster mode, cluster/epoch.py) stamps this
        writer's ownership epoch into every WAL segment it opens and
        arms the replay-side fence; ``epoch_guard`` (an
        ``EpochGuard``) is checked from every mutation entry point and
        from ``checkpoint()`` so a deposed writer raises
        ``FencedWriterError`` instead of split-braining the store.
        Both default off — a non-cluster store's WAL bytes and hot
        path are unchanged.

        ``read_only=True`` opens another daemon's store WITHOUT the
        single-writer lock: a replica that serves reads over the same
        WAL + sstable generations while the writer keeps ingesting —
        the reference's N-TSDs-over-one-shared-store deployment shape
        (reference README:8-17). Replicas never truncate torn WAL
        tails (the writer may be mid-append), never delete
        manifest-stray generation files, and refuse every mutation
        with ReadOnlyStoreError; ``refresh()`` catches the replica up
        to the writer's latest durable state."""
        self._tables: dict[str, _Table] = {}
        self._lock = threading.RLock()
        if max_generations is not None:
            if max_generations < 2:
                raise ValueError(
                    f"max_generations must be >= 2, got {max_generations}")
            self._MAX_GENERATIONS = max_generations
        self.throttle_rows = throttle_rows
        self._fsync = fsync
        self._wal_path = wal_path
        self.read_only = read_only
        # Cluster write tier (cluster/): the epoch this writer owns
        # (None = non-cluster store, no headers, no fence), the
        # mutation-path guard, the highest segment-header epoch the
        # replay stream has produced so far, and the bytes replay
        # refused past a fence line (zombie segments).
        self.writer_epoch = writer_epoch
        self.epoch_guard = epoch_guard
        self._replay_epoch = 0
        self.fenced_bytes_refused = 0
        # Count of replica full rebuilds (each corresponds to a writer
        # checkpoint/rotation); TSDB's refresh timer keys sketch
        # snapshot reloads off it.
        self.rebuilds = 0
        # Replica replay position: {"wal": (inode, replayed bytes),
        # "old": (inode, size) | None} — refresh() replays just the
        # WAL suffix when the writer has only appended, and rebuilds
        # only when the WAL rotated, the manifest changed, or the
        # <wal>.old file appeared/changed (NOT on every poll while a
        # writer's long merge keeps .old on disk).
        self._ro_state: dict | None = None
        self._wal: io.BufferedWriter | None = None
        # Spill tier: a LIST of sstable generations, OLDEST FIRST. A
        # checkpoint normally spills just the frozen memtable as a new
        # generation (O(new rows), not O(total) — full rewrites grew
        # linearly: 28s at 25M points, 114s at 75M); reads overlay
        # generations in order. A full merge (collapse to one
        # generation) runs only when the frozen tier holds tombstones
        # (which must mask lower-generation cells) or the generation
        # count hits _MAX_GENERATIONS.
        self._ssts: list[SSTable] = []
        self._sst_path = wal_path + ".sst" if wal_path else None
        # Write-side sstable codec (Config.sstable_codec): "none"
        # spills the WRITE_FORMAT legacy layout; "tsst4" spills
        # compressed columnar blocks. Read-side is self-describing per
        # file, so mixed-format generation sets are first-class and
        # flipping this only affects FUTURE spills (compaction
        # re-encodes as generations merge).
        self.sstable_codec = "none"
        # WAL group commit (Config.wal_group_ms, set externally like
        # sstable_codec): > 0 defers the per-append flush+fsync into a
        # leader-elected group flush. Append paths bump _grp_written
        # (a ticket counter) UNDER the store lock; ack paths call
        # _wal_barrier(ticket) AFTER releasing it and park on
        # _grp_cond until _grp_flushed covers their ticket. Lock
        # order is store lock -> _grp_cond everywhere.
        self._wal_group_ms = 0.0
        self._grp_cond = threading.Condition()
        self._grp_written = 0     # tickets issued (appends recorded)
        self._grp_flushed = 0     # tickets covered by an fsync
        self._grp_leader = False  # a leader is collecting/flushing
        self._grp_file_epoch = 0  # bumped per WAL rotation
        # Last byte offset covered by a group fsync — bounds the torn
        # span the kv.wal.group.fsync faultpoint may cut (never into
        # previously durable bytes).
        self._grp_synced_pos = 0
        # Flush failures SWALLOWED on put_many's exceptional exit (the
        # in-flight throttle error wins) — the one case where a flush
        # failure cannot propagate to the caller. Ordinary flush
        # failures raise loudly and are not counted here; nonzero means
        # acknowledged cells whose WAL records may not have reached the
        # OS with no exception having told anyone.
        self.wal_swallowed_flush_errors = 0
        # Monotonic mutation counter (bumped per mutating CALL, not per
        # cell, plus checkpoint tier transitions): consumers that derive
        # state from memtable contents (the rollup tier's dirty-window
        # set) key their caches on it — unchanged seq means the
        # memtable cannot have changed.
        self.mutation_seq = 0
        # Rollup-tier hook: when set, checkpoint() records the row keys
        # of every spilled frozen tier (including row tombstones) so
        # the materialized-summary fold covers exactly what left the
        # memtable; take_spill_keys() drains the record.
        self.record_spill_keys = False
        self._last_spill_keys: dict[str, list[bytes]] = {}
        # Rollup-tier hook: called as fn(table, key) on every delete /
        # delete_row so the incremental-fold accumulators (rollup/
        # delta.py) learn when a row's point set changed out-of-band;
        # None when no tier is listening.
        self.delete_hook = None
        # Dirty-base refcounts of the UNDRAINED spill record (the
        # frozen tier's dirty index, carried over at phase 3 and summed
        # across checkpoints like _last_spill_keys): spilled keys count
        # as dirty until the rollup fold drains them, so dirty_bases
        # never has to derive bases from the (possibly huge) key list.
        self._spill_dirty: dict[str, dict[int, int]] = {}
        # The fragment cache's invalidation spine: per (table, base),
        # the mutation_seq of the last row-create/remove transition
        # that touched it — folded here from each tier's ``touch`` map
        # when the tier retires (phase-3 drop, empty-checkpoint drop,
        # thaw), so the signal outlives the memtable generation that
        # produced it. A fragment built at store seq E over a CLEAN
        # base range is still exact iff no base in the range carries a
        # stamp > E and E >= _stamp_floor: rows only enter or leave
        # the visible dataset through stamped memtable transitions
        # (puts, deletes, tombstones), every checkpoint merely
        # relocates them between tiers, and a replica rebuild — where
        # what changed is unknown — jumps the floor instead.
        self._base_stamps: dict[str, dict[int, int]] = {}
        self._stamp_floor = 0
        # Lazy snapshots for range queries (rebuilt when mutation_seq
        # moves): table -> (seq, sorted bases, aligned stamps).
        self._stamps_snap: dict[str, tuple[int, np.ndarray,
                                           np.ndarray]] = {}
        self._dirty_snap: dict[str, tuple[int, np.ndarray]] = {}
        # Generations skipped by the series-bloom prefilter (scan_raw
        # with a series_hint), exported as bloom.files_skipped.
        self.bloom_files_skipped = 0
        # Per-generation bisects skipped by the point-get bloom probe
        # (_lower_tier_has), exported as bloom.point_skips.
        self.bloom_point_skips = 0
        # Immutable middle tier while a checkpoint merge is in flight.
        self._frozen: dict[str, _Table] | None = None
        self._lockfd: int | None = None
        if wal_path and not read_only:
            # Create the WAL's parent directory so a fresh --wal path
            # works without operator mkdir (same courtesy as the /q
            # cache dir).
            parent = os.path.dirname(os.path.abspath(wal_path))
            os.makedirs(parent, exist_ok=True)
            # Advisory single-writer lock, held for the store's
            # lifetime and acquired BEFORE any recovery work touches
            # disk: _generation_paths deletes any generation file the
            # manifest doesn't name, so a second opener racing a
            # writer between its generation rename and manifest write
            # would unlink the writer's live spill. A separate .lock
            # file (not the WAL itself) because checkpoint
            # rotates/reopens the WAL, which would drop a lock held on
            # its fd.
            self._lockfd = os.open(wal_path + ".lock",
                                   os.O_CREAT | os.O_RDWR, 0o644)
            try:
                fcntl.flock(self._lockfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(self._lockfd)
                self._lockfd = None
                raise RuntimeError(
                    f"WAL path {wal_path!r} is locked by another "
                    f"MemKVStore (single-writer store; remove "
                    f"{wal_path}.lock only if the owner is dead)")
        try:
            if read_only:
                self._open_tiers_retrying(wal_path)
            else:
                self._open_tiers(wal_path)
        except BaseException:
            # Recovery failed after the flock was acquired (corrupt
            # generation file, WAL replay error): release the lock or
            # an in-process repair-and-retry would be refused with a
            # misleading "locked by another store" forever.
            for sst in self._ssts:
                sst.close()
            self._ssts = []
            if self._lockfd is not None:
                os.close(self._lockfd)
                self._lockfd = None
            raise

    def _open_tiers_retrying(self, wal_path: str | None) -> None:
        """_open_tiers for replicas, retrying on FileNotFoundError: a
        live writer's merge can unlink a dropped generation between
        the replica's manifest read and the file open (found by the
        replica-vs-writer stress test). The manifest converges, so a
        bounded re-read wins the race; skipping the missing file
        instead would silently drop its rows."""
        for _ in range(8):
            for sst in self._ssts:
                sst.close()
            self._tables = {}
            self._ssts = []
            try:
                self._open_tiers(wal_path)
                return
            except FileNotFoundError:
                continue
        raise FileNotFoundError(
            f"generation set for {wal_path!r} kept changing mid-open "
            f"(writer merging continuously?); gave up after 8 tries")

    def _open_tiers(self, wal_path: str | None) -> None:
        """Load sstable generations, replay the WAL(s), open for append
        (the recovery tail of __init__; caller owns lock-fd cleanup on
        failure)."""
        self._replay_epoch = 0
        if self._sst_path:
            for path in self._generation_paths():
                sst = SSTable(path)
                self._ssts.append(sst)
                for name in sst.tables():
                    self._table(name)
        if wal_path:
            # A leftover <wal>.old means a crash interrupted a checkpoint:
            # replay it first (records older than everything in the WAL).
            old_path = wal_path + ".old"
            if os.path.exists(old_path):
                old_valid = self._replay(old_path)
                if old_valid < os.path.getsize(old_path) \
                        and not self.read_only:
                    # Torn tail: truncate, or a later checkpoint would
                    # append live records after the garbage where replay
                    # can never reach them. (A replica never truncates:
                    # the "torn" tail may be the writer mid-append.)
                    with open(old_path, "r+b") as f:
                        f.truncate(old_valid)
            valid_bytes = 0
            ino = -1
            if os.path.exists(wal_path):
                ino = os.stat(wal_path).st_ino
                valid_bytes = self._replay(wal_path)
                if valid_bytes < os.path.getsize(wal_path) \
                        and not self.read_only:
                    # Torn record at the tail (crash mid-write): truncate it
                    # away so appends continue from the last valid boundary —
                    # otherwise the next replay would stop at the garbage and
                    # silently drop everything written after it.
                    with open(wal_path, "r+b") as f:
                        f.truncate(valid_bytes)
            if self.read_only:
                self._ro_state = {"wal": (ino, valid_bytes),
                                  "old": self._stat_old()}
            else:
                self._wal = open(wal_path, "ab")
                self._stamp_epoch_header()

    def _stat_old(self) -> "tuple[int, int] | None":
        try:
            st = os.stat(self._wal_path + ".old")
            return (st.st_ino, st.st_size)
        except OSError:
            return None

    def refresh(self) -> bool:
        """Catch a read-only replica up to the writer's current durable
        state. Returns True when anything changed.

        When the WAL is the same file and has only grown, just the
        suffix replays (cheap steady-state poll). A rotated WAL or a
        changed manifest (the writer checkpointed) triggers a full
        rebuild — which is exactly crash recovery, so it is correct in
        ANY in-flight writer state: mid-checkpoint the replica sees the
        old manifest + <wal>.old + fresh WAL, and replaying .old then
        the WAL over the manifest generations reproduces the data."""
        if not self.read_only:
            raise ValueError("refresh() is for read-only stores")
        if not self._wal_path:
            return False
        # raise/ioerror here simulate a poll hitting writer churn or a
        # flaky volume: the replica must keep serving its coherent
        # pre-refresh view (delay widens the rebuild-vs-writer races).
        _fault("replica.refresh", self._wal_path)
        with self._lock:
            man_now = self._generation_paths()
            if [s.path for s in self._ssts] != man_now:
                self._rebuild_locked()
                return True
            state = self._ro_state or {"wal": (-1, 0), "old": None}
            if self._stat_old() != state["old"]:
                # <wal>.old appeared/changed: a writer checkpoint is in
                # flight (or a new crash remnant) — its records precede
                # the current WAL, so a rebuild is the only correct
                # catch-up. Recording its (inode, size) means a LONG
                # merge (minutes at 1B scale) costs one rebuild, not
                # one per poll.
                self._rebuild_locked()
                return True
            try:
                f = open(self._wal_path, "rb")
            except OSError:
                return False
            with f:
                # fstat on the OPEN fd: a writer rotation between a
                # path-stat and the open would otherwise let the
                # replay seek to the old file's offset inside the NEW
                # file and misparse garbage as records (the WAL frame
                # has no checksum).
                st = os.fstat(f.fileno())
                ino, off = state["wal"]
                if st.st_ino != ino or st.st_size < off:
                    self._rebuild_locked()
                    return True
                if st.st_size == off:
                    return False
                valid = self._replay_file(f, start=off)
            self._ro_state = {"wal": (ino, valid),
                              "old": state["old"]}
            if valid > off:
                # The replayed suffix mutated the memtable outside the
                # put/delete entry points: consumers keying caches on
                # mutation_seq must see it move.
                self.mutation_seq += 1
            return valid > off

    def _rebuild_locked(self) -> None:
        """Full replica reload: fresh tables, current generations,
        .old + WAL replay (the crash-recovery path, minus truncation).
        Caller holds the lock. Open sstable handles for dropped
        generations close afterwards — Linux keeps unlinked files
        readable until the fd closes, so readers racing a writer's
        full merge never see missing data."""
        old_ssts = self._ssts
        old_tables = self._tables
        old_state = self._ro_state
        _fault("replica.rebuild", self._wal_path)
        self._ssts = []
        self._ro_state = None
        try:
            self._open_tiers_retrying(self._wal_path)
        except BaseException:
            # Keep serving the STALE-but-consistent pre-rebuild view
            # (and don't leak its fds): half-loaded tables would serve
            # torn reads to a poller that treats the failure as
            # transient.
            for sst in self._ssts:
                sst.close()
            self._ssts = old_ssts
            self._tables = old_tables
            self._ro_state = old_state
            raise
        self.rebuilds += 1
        self.mutation_seq += 1
        # A rebuild replaced the generation set wholesale; what changed
        # inside it is unknown, so the stamp floor jumps and every
        # fragment cached against an earlier seq is invalid.
        self._stamp_floor = self.mutation_seq
        self._base_stamps = {}
        self._stamps_snap = {}
        self._dirty_snap = {}
        for sst in old_ssts:
            sst.close()

    _MAX_GENERATIONS = 8

    def _generation_paths(self) -> list[str]:
        """Live spill generations, oldest first. The manifest (written
        atomically on every checkpoint) is the source of truth — stray
        generation files it does not name (crash leftovers between a
        full-merge swap and the old-file unlinks) are deleted here,
        because loading them would resurrect cells a merge already
        dropped. No manifest = legacy layout: the single ``<wal>.sst``."""
        man = self._sst_path + ".manifest"
        d = os.path.dirname(os.path.abspath(self._sst_path))
        if not os.path.exists(man):
            return [self._sst_path] if os.path.exists(self._sst_path) \
                else []
        import json as _json
        with open(man) as f:
            names = _json.load(f)
        live = [os.path.join(d, fn) for fn in names]
        if self.read_only:
            # Replicas must never delete (a "stray" may be the live
            # writer's generation mid-rename) — and must NOT filter on
            # existence either: a writer merge can unlink a manifest
            # generation between our manifest read and this point, and
            # silently dropping it would serve reads missing all its
            # rows. Returning the path unfiltered makes the SSTable
            # open raise FileNotFoundError, which the replica's retry
            # turns into a manifest re-read.
            return live
        liveset = set(names)
        base = os.path.basename(self._sst_path)
        for fn in os.listdir(d):
            if (fn == base or fn.startswith(base + ".g")) \
                    and fn not in liveset \
                    and not fn.endswith(".tmp") \
                    and not fn.endswith(".manifest"):
                try:
                    os.unlink(os.path.join(d, fn))
                except OSError:
                    pass
        return [p for p in live if os.path.exists(p)]

    def _write_manifest(self, paths: list[str]) -> None:
        """Atomically record the live generation set (tmp + rename +
        directory fsync, same durability contract as write_sstable)."""
        import json as _json
        man = self._sst_path + ".manifest"
        tmp = man + ".tmp"
        with open(tmp, "w") as f:
            _json.dump([os.path.basename(p) for p in paths], f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, man)
        dfd = os.open(os.path.dirname(os.path.abspath(man)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def _next_generation_path(self) -> str:
        used = set()
        d = os.path.dirname(os.path.abspath(self._sst_path))
        prefix = os.path.basename(self._sst_path) + ".g"
        for fn in os.listdir(d):
            if fn.startswith(prefix) and not fn.endswith(".tmp") \
                    and not fn.endswith(".manifest"):
                try:
                    used.add(int(fn[len(prefix):]))
                except ValueError:
                    continue
        n = 1
        while n in used:
            n += 1
        return self._sst_path + f".g{n}"

    # -- table helpers ----------------------------------------------------

    def _table(self, name: str) -> _Table:
        t = self._tables.get(name)
        if t is None:
            t = self._tables[name] = _Table()
        return t

    def ensure_table(self, table: str) -> None:
        with self._lock:
            self._table(table)

    def _check_writable(self) -> None:
        if self.read_only:
            raise ReadOnlyStoreError(
                f"store on {self._wal_path!r} is a read-only replica")
        if self.epoch_guard is not None:
            # The zombie fence (cluster/epoch.py): raises
            # FencedWriterError once a promotion has bumped the
            # persisted epoch past ours. Stat-cached — nothing
            # measurable on the batched ingest path.
            self.epoch_guard.check()

    def memtable_keys(self, table: str) -> list[bytes]:
        """Row keys in the live memtable only (excludes spilled tiers).
        After crash recovery this is exactly the WAL-replayed set — what
        a checkpoint-snapshot consumer (TSDB sketch rebuild) must re-fold
        on top of its snapshot."""
        with self._lock:
            return list(self._table(table).rows)

    def memtable_row_counts(self, table: str) -> list[int]:
        """Live-memtable row count, one element per shard (one, here) —
        the /stats per-shard memtable gauge."""
        with self._lock:
            return [len(self._table(table).rows)]

    def sstable_format_bytes(self) -> dict[int, int]:
        """On-disk bytes of the live generation set, keyed by sstable
        format version (1-4) — the /stats ``sstable.bytes{format=}``
        gauge and fsck's format-mix report."""
        out: dict[int, int] = {}
        with self._lock:
            gens = list(self._ssts)
        for sst in gens:
            try:
                sz = os.path.getsize(sst.path)
            except OSError:
                continue
            out[sst.format] = out.get(sst.format, 0) + sz
        return out

    def compress_stats(self) -> tuple[int, int]:
        """(uncompressed_record_bytes, stored_record_bytes) summed over
        the v4 generations — ``compress.ratio`` = raw / stored. (0, 0)
        when no generation is compressed."""
        raw = enc = 0
        with self._lock:
            gens = list(self._ssts)
        for sst in gens:
            cs = sst.codec_stats()
            if cs is not None:
                raw += cs[0]
                enc += cs[1]
        return raw, enc

    def encoded_range(self, table: str, start: bytes,
                      stop: bytes | None):
        """The fused decode-aggregate path's source check: when every
        generation holding keys in [start, stop) is format v4, returns
        [(sstable, lo_idx, hi_idx)] ordered by first key. Returns None
        whenever serving the range off raw blocks could diverge from a
        scan: a frozen mid-checkpoint tier, live row tombstones, or a
        non-v4 generation in range. Two residual overlay risks are the
        CALLER's checks: memtable-resident rows (executor chunk_state:
        any dirty base in range declines the fused plan) and duplicate
        keys ACROSS generations (compress/fused.gather verifies the
        copies' qualifier-delta ranges are disjoint — the mid-hour
        checkpoint-boundary straddle, where the overlay is a pure
        union — and declines otherwise)."""
        with self._lock:
            if self._frozen is not None:
                return None
            t = self._tables.get(table)
            if t is not None and t.row_tombs:
                return None
            gens = list(self._ssts)
        spans = []
        for g in gens:
            idx = g._index.get(table)
            if not idx or not idx[0]:
                continue
            keys, _ = idx
            lo = bisect_left(keys, start)
            hi = bisect_left(keys, stop) if stop else len(keys)
            if lo == hi:
                continue
            if g.format != 4:
                return None
            spans.append((g, lo, hi, keys[lo]))
        spans.sort(key=lambda s: s[3])
        return [(g, lo, hi) for g, lo, hi, _ in spans]

    def pending_keys(self, table: str) -> list[bytes]:
        """Row keys (and row tombstones) NOT yet covered by the rollup
        fold: the live memtable, a frozen mid-checkpoint tier, and the
        UNDRAINED spilled-key record. This is the rollup planner's
        dirty-window source. Spilled keys count as pending until the
        fold drains them (take_spill_keys) precisely so no instant
        exists where a spilled-but-unfolded window is in neither this
        set nor the tier's in-flight set — the fold marks its windows
        in flight BEFORE draining (rollup/tier.py fold_after_spill)."""
        with self._lock:
            t = self._table(table)
            out = list(t.rows)
            out.extend(t.row_tombs)
            if self._frozen is not None:
                ft = self._frozen.get(table)
                if ft is not None:
                    out.extend(ft.rows)
                    out.extend(ft.row_tombs)
            out.extend(self._last_spill_keys.get(table, ()))
            return out

    def peek_spill_keys(self) -> dict[str, list[bytes]]:
        """Non-draining copy of the spilled-key record: the rollup fold
        reads it to mark windows in flight while their keys still read
        as pending, THEN drains with take_spill_keys."""
        with self._lock:
            return {name: list(ks)
                    for name, ks in self._last_spill_keys.items()}

    def take_spill_keys(self) -> dict[str, list[bytes]]:
        """Drain the spilled-key record (see record_spill_keys)."""
        with self._lock:
            out, self._last_spill_keys = self._last_spill_keys, {}
            self._spill_dirty = {}
            self.mutation_seq += 1  # the dirty-base set just shrank
            return out

    @property
    def spilled(self) -> bool:
        """Whether any sstable generation exists (data outside the
        WAL-replayable memtable)."""
        return bool(self._ssts)

    @property
    def mutation_seqs(self) -> tuple[int, ...]:
        """Per-shard mutation sequence vector (a single store is one
        shard). The sharded store's summed ``mutation_seq`` makes one
        put anywhere invalidate everything derived from it; consumers
        that can revalidate per shard key on this instead."""
        return (self.mutation_seq,)

    def dirty_bases(self, table: str) -> np.ndarray:
        """Sorted unique base times whose rows are NOT fully covered by
        the immutable sstable tiers: live memtable rows + row
        tombstones, the frozen mid-checkpoint tier, and the undrained
        spill record — maintained incrementally (O(1) amortized per
        mutation, see _Table.dirty) so deriving it never sweeps the
        key list. Cached per mutation_seq; the rollup planner's
        dirty-window set and the fragment cache's bypass test both
        read it."""
        with self._lock:
            snap = self._dirty_snap.get(table)
            if snap is not None and snap[0] == self.mutation_seq:
                return snap[1]
            bases = set(self._table(table).dirty)
            if self._frozen is not None:
                ft = self._frozen.get(table)
                if ft is not None:
                    bases.update(ft.dirty)
            sd = self._spill_dirty.get(table)
            if sd:
                bases.update(sd)
            arr = np.fromiter(bases, np.int64, len(bases))
            arr.sort()
            self._dirty_snap[table] = (self.mutation_seq, arr)
            return arr

    def chunk_state(self, table: str, lo: int, hi: int,
                    ) -> tuple[tuple[int, ...], tuple[int, ...],
                               tuple[int, ...], bool]:
        """Fragment-cache validation state for base range [lo, hi):
        ``(seqs, floors, stamps, dirty)`` — one element per shard
        (one, here). A fragment tagged with seq E over this range is
        still exact iff the range is clean (not ``dirty``),
        E >= floor, and no base in the range carries a transition
        stamp > E (``stamps`` is the range's newest stamp across the
        store-level map and every live tier's touch map). Rows only
        enter or leave the visible dataset through stamped memtable
        transitions, so an unchanged stamp range means unchanged
        content — checkpoints merely relocate rows between tiers."""
        d = self.dirty_bases(table)
        dirty = bool(len(d)) and \
            int(np.searchsorted(d, lo)) < int(np.searchsorted(d, hi))
        with self._lock:
            seq = self.mutation_seq
            snap = self._stamps_snap.get(table)
            if snap is None or snap[0] != seq:
                m = dict(self._base_stamps.get(table, {}))
                tiers = [self._table(table)]
                if self._frozen is not None:
                    ft = self._frozen.get(table)
                    if ft is not None:
                        tiers.append(ft)
                for t in tiers:
                    for b, v in t.touch.items():
                        if m.get(b, -1) < v:
                            m[b] = v
                bases = np.fromiter(m.keys(), np.int64, len(m))
                stamps = np.fromiter(m.values(), np.int64, len(m))
                order = np.argsort(bases)
                snap = (seq, bases[order], stamps[order])
                self._stamps_snap[table] = snap
            _, bases, stamps = snap
            a = int(np.searchsorted(bases, lo))
            b = int(np.searchsorted(bases, hi))
            stamp = int(stamps[a:b].max()) if b > a else 0
            return ((seq,), (self._stamp_floor,), (stamp,), dirty)

    def memtable_cells(self, table: str, key: bytes,
                       family: bytes | None = None) -> list[Cell]:
        """Live-memtable cells of one row, WITHOUT merging spilled tiers
        (tombstones excluded). The recovery re-fold reads rows through
        this so cells already covered by the sketch snapshot (sstable
        tier) are not folded twice."""
        with self._lock:
            row = self._table(table).rows.get(key)
            if not row:
                return []
            return [Cell(key, f, q, v) for (f, q), v in row.items()
                    if v is not None and (family is None or f == family)]

    def row_count(self, table: str) -> int:
        with self._lock:
            t = self._table(table)
            keys = set(t.rows)
            ft = self._frozen.get(table) if self._frozen else None
            if ft is not None:
                keys |= set(ft.rows)
            for sst in self._ssts:
                keys.update(sst.scan_keys(table, b"", None))
            return sum(1 for k in keys if self._merged_row(table, k))

    def has_row(self, table: str, key: bytes) -> bool:
        with self._lock:
            return self._has_row_locked(table, key)

    def _has_row_locked(self, table: str, key: bytes) -> bool:
        row = self._table(table).rows.get(key)
        if row:
            # Tombstones (None cells) only exist once a lower tier
            # does; the pure-memtable hot ingest path stays O(1).
            if not self._ssts and self._frozen is None:
                return True
            if any(v is not None for v in row.values()):
                return True
        return self._merged_row(table, key) is not None

    def cell_count(self, table: str, key: bytes) -> int:
        with self._lock:
            row = self._merged_row(table, key)
            return len(row) if row else 0

    def _merged_row(self, table: str,
                    key: bytes) -> dict[tuple[bytes, bytes], bytes] | None:
        """Lower tiers (sstable, then frozen memtable) overlaid with the
        live memtable's cells/tombstones. Caller holds the lock."""
        t = self._table(table)
        if not self._ssts and self._frozen is None:
            # No lower tiers => no tombstones possible; serve the row
            # as-is (the default-config hot path allocates nothing).
            return t.rows.get(key) or None
        ft = self._frozen.get(table) if self._frozen else None
        merged: dict[tuple[bytes, bytes], bytes] = {}
        sst_masked = key in t.row_tombs or (
            ft is not None and key in ft.row_tombs)
        if not sst_masked:
            # Overlay generations oldest -> newest (generations never
            # hold tombstones — a tombstoned frozen tier forces a full
            # merge — so plain dict overlay is the whole story).
            for sst in self._ssts:
                cells = sst.get(table, key)
                if cells:
                    for f, q, v in cells:
                        merged[(f, q)] = v
        if ft is not None and key not in t.row_tombs:
            row = ft.rows.get(key)
            if row:
                for ck, v in row.items():
                    if v is None:
                        merged.pop(ck, None)
                    else:
                        merged[ck] = v
        row = t.rows.get(key)
        if row:
            for ck, v in row.items():
                if v is None:
                    merged.pop(ck, None)
                else:
                    merged[ck] = v
        return merged or None

    def _lower_tier_has(self, t: _Table, table: str, key: bytes) -> bool:
        """Does any tier below the live memtable hold this key? (Decides
        whether a delete must leave tombstones.)

        Consults each generation's series bloom BEFORE the key bisect:
        generations whose bloom excludes the key's series identity
        cannot hold the key (blooms cover every indexed key — fsck
        audits the no-false-negative invariant), so point deletes over
        high-generation-count stores skip most bisects. The probe hash
        is the same crc32 chain the bloom writer uses, so present keys
        always pass; a stale bit (tombstoned key) only costs one
        needless bisect."""
        ft = self._frozen.get(table) if self._frozen else None
        if ft is not None and (key in ft.rows):
            return True
        if not self._ssts:
            return False
        h = None
        if len(key) >= _BASE_HI:
            h = zlib.crc32(key[_BASE_HI:], zlib.crc32(key[:_BASE_LO]))
        for sst in self._ssts:
            if h is not None and not sst.bloom_may_contain_hash(table, h):
                self.bloom_point_skips += 1
                continue
            if sst.has_key(table, key):
                return True
        return False

    # -- WAL --------------------------------------------------------------

    def _wal_append(self, op: int, *parts: bytes,
                    flush: bool = True) -> None:
        if self._wal is None:
            return
        payload = b"".join(struct.pack(">I", len(p)) + p for p in parts)
        self._wal.write(_REC.pack(op, len(payload)) + payload)
        # Always push past the USERSPACE buffer before acknowledging:
        # without this, up to 8 KiB of acknowledged writes sit in the
        # Python file object and a SIGTERM/crash loses them silently —
        # found live, with every verification daemon's WAL at 0 bytes
        # after a kill. flush() is process-crash-safe (data reaches the
        # OS page cache); ``fsync`` additionally survives power loss.
        # Batch writers pass flush=False per record and call
        # _wal_flush() ONCE before the batch acknowledges (the ack
        # boundary, not the record, is the durability promise).
        _M_WAL_APPENDS.inc()
        _M_WAL_BYTES.inc(_REC.size + len(payload))
        if flush:
            if self._wal_group_ms > 0:
                self._grp_note(1)
            else:
                self._wal_flush()
                _fault("kv.wal.append", self._wal_path,
                       _REC.size + len(payload))

    def _wal_flush(self) -> None:
        self._wal.flush()
        # Between the userspace flush and the (optional) fsync: crash
        # here loses nothing on process death but everything on power
        # loss — the gap the fsync=True deployments buy away; ioerror
        # simulates the fsync itself failing (ENOSPC/EIO). The trace
        # span brackets the faultpoint too, so an armed delay here
        # stretches exactly the wal.fsync span of a traced ingest.
        with _trace.span("wal.fsync"):
            _fault("kv.wal.fsync", self._wal_path)
            if self._fsync:
                with _M_WAL_FSYNC.time():
                    os.fsync(self._wal.fileno())
        # In group mode every direct (non-deferred) flush runs under
        # the store lock — checkpoint rotation, close(), flush() — and
        # covers every record written so far: mark all issued tickets
        # durable so parked barriers wake instead of re-flushing.
        if self._wal_group_ms > 0:
            self._grp_sync_locked()

    # -- WAL group commit (Config.wal_group_ms) ---------------------------
    #
    # Appends keep writing into the WAL's userspace buffer under the
    # store lock, but the per-append flush+fsync is deferred: each
    # append takes a ticket (_grp_written), and the ACK path — after
    # releasing the store lock — parks in _wal_barrier until a group
    # flush covers its ticket. The first parked thread elects itself
    # leader, lingers up to wal_group_ms collecting followers, then
    # performs ONE flush+fsync for everything written so far. The
    # durability contract is unchanged (nothing acks before its
    # covering fsync); only the fsync count changes.

    def _grp_note(self, points: int) -> None:
        """Record a deferred-flush append (called under the store
        lock). Fires the write-side faultpoint with NO path/bytes
        context on purpose: the deferred record may still sit in the
        userspace buffer, so a torn cut here could reach into bytes an
        earlier group fsync already made durable — the site therefore
        degrades torn to a plain crash."""
        _fault("kv.wal.group.write")
        with self._grp_cond:
            self._grp_written += 1
        _M_GRP_BATCHES.inc()
        _M_GRP_POINTS.inc(points)

    def _grp_ticket(self) -> int:
        """Ticket for _wal_barrier, captured while the store lock is
        still held (every _grp_written bump happens under it). 0 =
        group mode off, nothing to wait for."""
        if self._wal_group_ms > 0 and self._wal is not None:
            return self._grp_written
        return 0

    def _grp_sync_locked(self) -> None:
        """After a direct full flush under the store lock: every
        issued ticket is covered — advance the flushed watermark and
        the durable byte position, and wake parked barriers."""
        pos = 0
        if self._wal is not None:
            try:
                pos = self._wal.tell()
            except ValueError:
                pos = 0
        with self._grp_cond:
            self._grp_flushed = self._grp_written
            self._grp_synced_pos = max(self._grp_synced_pos, pos)
            self._grp_cond.notify_all()

    def _grp_rotated_locked(self) -> None:
        """The WAL was just rotated to a fresh segment (store lock
        held): reset the durable position for the new file and bump
        the file epoch so a stale leader mid-flush on the old fd
        cannot clobber the new file's position."""
        with self._grp_cond:
            self._grp_file_epoch += 1
            self._grp_synced_pos = 0

    def _wal_group_flush(self) -> None:
        """The leader's covering flush (+fsync), run WITHOUT the store
        lock — BufferedWriter serializes internally against concurrent
        buffered appends. Raises ValueError/OSError if a rotation
        closed the file underneath us (the barrier handles it)."""
        wal = self._wal
        if wal is None:
            return
        with self._grp_cond:
            epoch = self._grp_file_epoch
            synced = self._grp_synced_pos
        # Position BEFORE the userspace flush: <= the on-disk size
        # after it, so the torn span below can never cut into bytes a
        # previous group fsync already covered (acked records all sit
        # at or below _grp_synced_pos).
        tell_pos = wal.tell()
        wal.flush()
        with _trace.span("wal.fsync"):
            _fault("kv.wal.group.fsync", self._wal_path,
                   max(tell_pos - synced, 1))
            if self._fsync:
                with _M_WAL_FSYNC.time():
                    os.fsync(wal.fileno())
        with self._grp_cond:
            if self._grp_file_epoch == epoch:
                self._grp_synced_pos = max(self._grp_synced_pos,
                                           tell_pos)
        _M_GRP_FSYNCS.inc()

    def _wal_barrier(self, ticket: int) -> None:
        """Park until a group flush covers ``ticket`` (leader-elected:
        the first uncovered caller lingers wal_group_ms to collect
        followers, then flushes for everyone). Call AFTER releasing
        the store lock — lock order is store lock -> _grp_cond."""
        if not ticket or MemKVStore._ACK_BEFORE_FSYNC:
            return
        t0 = _perf()
        cond = self._grp_cond
        linger = self._wal_group_ms / 1000.0
        while True:
            with cond:
                if self._grp_flushed >= ticket:
                    break
                if self._grp_leader:
                    # A leader is collecting or flushing; the timeout
                    # is belt-and-braces against a lost notify.
                    cond.wait(0.05)
                    continue
                self._grp_leader = True
                if linger > 0:
                    cond.wait(linger)
                target = self._grp_written
            err = None
            try:
                self._wal_group_flush()
            except BaseException as e:
                err = e
            with cond:
                self._grp_leader = False
                if err is None:
                    self._grp_flushed = max(self._grp_flushed, target)
                covered = self._grp_flushed >= ticket
                cond.notify_all()
            if err is not None:
                # A rotation/close can legitimately yank the file out
                # from under an elected leader — but only after its
                # own full flush covered every issued ticket.
                if covered and isinstance(err, (ValueError, OSError)):
                    break
                raise err
        _M_GRP_WAIT.observe((_perf() - t0) * 1000.0)

    def wal_barrier(self, ticket: int | None = None) -> None:
        """Block until every WAL record appended so far (or, with a
        ``ticket`` from a mutation's return, up to that ticket) is
        covered by a group flush. No-op outside group mode; safe to
        call without the store lock. Batch ingest calls this ONCE per
        wire batch (put_many(..., sync=False) per series, then one
        barrier) instead of once per series."""
        if self._wal_group_ms <= 0 or self._wal is None:
            return
        if ticket is None:
            with self._grp_cond:
                ticket = self._grp_written
        self._wal_barrier(ticket)

    @property
    def wal_group_ms(self) -> float:
        return self._wal_group_ms

    @wal_group_ms.setter
    def wal_group_ms(self, ms: float) -> None:
        """Set externally like sstable_codec (make_tsdb plumbs
        Config.wal_group_ms here). Enabling seeds the durable byte
        position from the current WAL end: everything already on disk
        (replayed history) must never fall inside a torn group span."""
        self._wal_group_ms = float(ms)
        if self._wal_group_ms > 0 and self._wal is not None:
            with self._grp_cond:
                try:
                    self._grp_synced_pos = max(self._grp_synced_pos,
                                               self._wal.tell())
                except ValueError:
                    pass

    def _stamp_epoch_header(self, force: bool = False) -> None:
        """Begin (or continue) this writer's ownership span in the WAL
        with an ``_OP_EPOCH`` record. ``force`` stamps unconditionally
        — a freshly rotated segment always needs a header; otherwise
        the stamp is skipped when the replayed stream already ended
        inside this writer's epoch (a clean same-epoch restart keeps
        appending without a redundant header). Opening with a replayed
        epoch ABOVE our own means this process was deposed while down:
        refuse to take the WAL at all."""
        if self._wal is None or self.writer_epoch is None:
            return
        if self._replay_epoch > self.writer_epoch:
            from opentsdb_tpu.core.errors import FencedWriterError
            raise FencedWriterError(
                f"WAL at {self._wal_path!r} already carries epoch "
                f"{self._replay_epoch}, this writer owns "
                f"{self.writer_epoch}: superseded while down",
                self.writer_epoch, self._replay_epoch)
        if force or self._replay_epoch < self.writer_epoch:
            self._wal_append(_OP_EPOCH,
                             struct.pack(">Q", self.writer_epoch))
            self._replay_epoch = self.writer_epoch

    # _REC frames the payload with a u32 length, capping one record at
    # 4 GiB. Batches whose blobs approach that are split into multiple
    # _OP_PUT_BATCH records (replay applies them in order, so the split
    # is invisible); the margin below the u32 limit leaves room for the
    # length arrays + header.
    _WAL_BATCH_LIMIT = 1 << 30

    @staticmethod
    def _batch_splits(cell_bytes: "np.ndarray") -> list[tuple[int, int]]:
        """[(start, stop)) cell ranges whose ACTUAL blob bytes each fit
        _WAL_BATCH_LIMIT (cumulative-sum greedy, so size-skewed batches
        can't overflow a chunk; a lone cell above the limit still gets
        its own record — only a single >4 GiB cell is unframeable). The
        common case (total under the limit) returns one full range."""
        n = len(cell_bytes)
        limit = MemKVStore._WAL_BATCH_LIMIT
        csum = np.cumsum(cell_bytes, dtype=np.int64)
        if n <= 1 or csum[-1] <= limit:
            return [(0, n)]
        out = []
        lo = 0
        base = 0
        while lo < n:
            # Furthest stop with csum[stop-1] - base <= limit; always
            # advance at least one cell.
            hi = int(np.searchsorted(csum, base + limit, side="right"))
            hi = max(hi, lo + 1)
            out.append((lo, hi))
            base = int(csum[hi - 1])
            lo = hi
        return out

    def _wal_append_batch(self, table: bytes, family: bytes,
                          cells: list[tuple[bytes, bytes, bytes]]) -> None:
        """One COLUMNAR WAL record for a whole put_many batch, then
        flush.

        The per-cell _OP_PUT framing (4 struct.packs + join + write per
        cell) was the single largest cost of sustained ingest at scale
        — 20.5 s of a 37 s / 4M-point profile, ~5 µs per cell — because
        a sparse-per-series workload materializes ~0.2-0.5 row-hour
        cells per point. Layout: header, three >u4 length arrays, then
        the key/qualifier/value blobs — three C-level joins and one
        write instead of any per-cell framing (the interleaved
        len-prefixed variant still cost 1.3 us/cell in the join). The
        torn-tail truncation in _replay gives a partially-written batch
        record the same crash semantics as a torn _OP_PUT."""
        if self._wal is None:
            return
        t_app0 = _perf()
        n = len(cells)
        ks, qs, vs = zip(*cells)
        kl = np.fromiter(map(len, ks), ">u4", n)
        ql = np.fromiter(map(len, qs), ">u4", n)
        vl = np.fromiter(map(len, vs), ">u4", n)
        blob = int(kl.sum()) + int(ql.sum()) + int(vl.sum())
        splits = ([(0, n)] if blob <= self._WAL_BATCH_LIMIT else
                  self._batch_splits(kl.astype(np.int64)
                                     + ql.astype(np.int64)
                                     + vl.astype(np.int64)))
        for lo, hi in splits:
            payload = b"".join((
                struct.pack(">IHH", hi - lo, len(table), len(family)),
                table, family,
                kl[lo:hi].tobytes(), ql[lo:hi].tobytes(),
                vl[lo:hi].tobytes(),
                b"".join(ks[lo:hi]), b"".join(qs[lo:hi]),
                b"".join(vs[lo:hi])))
            self._wal.write(_REC.pack(_OP_PUT_BATCH, len(payload))
                            + payload)
            _M_WAL_APPENDS.inc()
            _M_WAL_BYTES.inc(_REC.size + len(payload))
        if self._wal_group_ms > 0:
            self._grp_note(n)
            _M_WAL_APPEND.observe((_perf() - t_app0) * 1000.0)
            return
        self._wal_flush()
        _M_WAL_APPEND.observe((_perf() - t_app0) * 1000.0)
        _fault("kv.wal.append", self._wal_path,
               _REC.size + len(payload))

    def _wal_append_batch_columnar(self, table: bytes, family: bytes,
                                   key_blob: bytes, n: int, key_len: int,
                                   quals: list[bytes],
                                   vals: list[bytes]) -> None:
        """Same _OP_PUT_BATCH record as _wal_append_batch, but the key
        blob is written as-is (the caller already holds the keys as one
        contiguous buffer) — no per-key slicing or re-join."""
        if self._wal is None:
            return
        t_app0 = _perf()
        ql = np.fromiter(map(len, quals), ">u4", n)
        vl = np.fromiter(map(len, vals), ">u4", n)
        blob = n * key_len + int(ql.sum()) + int(vl.sum())
        splits = ([(0, n)] if blob <= self._WAL_BATCH_LIMIT else
                  self._batch_splits(ql.astype(np.int64)
                                     + vl.astype(np.int64) + key_len))
        for lo, hi in splits:
            payload = b"".join((
                struct.pack(">IHH", hi - lo, len(table), len(family)),
                table, family,
                np.full(hi - lo, key_len, ">u4").tobytes(),
                ql[lo:hi].tobytes(), vl[lo:hi].tobytes(),
                key_blob[lo * key_len:hi * key_len],
                b"".join(quals[lo:hi]), b"".join(vals[lo:hi])))
            self._wal.write(_REC.pack(_OP_PUT_BATCH, len(payload))
                            + payload)
            _M_WAL_APPENDS.inc()
            _M_WAL_BYTES.inc(_REC.size + len(payload))
        if self._wal_group_ms > 0:
            self._grp_note(n)
            _M_WAL_APPEND.observe((_perf() - t_app0) * 1000.0)
            return
        self._wal_flush()
        _M_WAL_APPEND.observe((_perf() - t_app0) * 1000.0)
        _fault("kv.wal.append", self._wal_path,
               _REC.size + len(payload))

    @staticmethod
    def _split_payload(payload: bytes) -> list[bytes]:
        parts = []
        off = 0
        while off < len(payload):
            (n,) = struct.unpack_from(">I", payload, off)
            off += 4
            parts.append(payload[off:off + n])
            off += n
        return parts

    def _replay(self, path: str, start: int = 0) -> int:
        """Apply every complete WAL record from byte ``start``; returns
        the valid byte count (absolute, including ``start``)."""
        with open(path, "rb") as f:
            return self._replay_file(f, start)

    def _replay_file(self, f, start: int = 0) -> int:
        """_replay over an already-open file (refresh() verifies the
        fd's inode before seeking — reopening by path would race a
        writer's WAL rotation)."""
        valid = start
        if start:
            f.seek(start)
        while True:
            hdr = f.read(_REC.size)
            if len(hdr) < _REC.size:
                break  # truncated tail: stop at last complete record
            op, plen = _REC.unpack(hdr)
            payload = f.read(plen)
            if len(payload) < plen:
                break
            if op == _OP_EPOCH:
                (e,) = struct.unpack(
                    ">Q", self._split_payload(payload)[0])
                if e < self._replay_epoch:
                    # A segment from a DEPOSED writer landed after a
                    # newer writer's records — the split-brain
                    # artifact the epoch fence exists for. Refuse
                    # everything from the stale header on: for a
                    # writer the torn-tail truncation cuts it off
                    # (those appends were never legitimately acked —
                    # their author had already been superseded); a
                    # replica simply stops its cursor here.
                    try:
                        end = os.fstat(f.fileno()).st_size
                    except OSError:
                        end = valid
                    self.fenced_bytes_refused += max(end - valid, 0)
                    break
                self._replay_epoch = e
                valid += _REC.size + plen
                continue
            valid += _REC.size + plen
            if op == _OP_PUT_BATCH:
                n, tl, fl = struct.unpack_from(">IHH", payload, 0)
                off = 8
                table = payload[off:off + tl].decode()
                off += tl
                fam = payload[off:off + fl]
                off += fl
                lo = off            # the three u32 length arrays
                kl = np.frombuffer(payload, ">u4", n, off)
                ql = np.frombuffer(payload, ">u4", n, off + 4 * n)
                vl = np.frombuffer(payload, ">u4", n, off + 8 * n)
                off += 12 * n
                # Blob starts: keys, then quals, then values.
                ko, qo = off, off + int(kl.sum())
                vo = qo + int(ql.sum())
                if _EXT is not None:
                    # Bulk replay: slice the three blobs in C and
                    # upsert the whole record in one pass. Exactly
                    # _apply_put per cell (set the cell, create the
                    # row + pending entry when absent — no tier
                    # probes, no throttle on replay), so the result
                    # is identical to the loop below; recovery of a
                    # 10M-point WAL drops from ~10 s to ~2 s.
                    mv = memoryview(payload)
                    keys = _EXT.slice_varlen(mv[ko:qo],
                                             mv[lo:lo + 4 * n])
                    quals = _EXT.slice_varlen(
                        mv[qo:vo], mv[lo + 4 * n:lo + 8 * n])
                    vals = _EXT.slice_varlen(
                        mv[vo:vo + int(vl.sum())],
                        mv[lo + 8 * n:lo + 12 * n])
                    t = self._table(table)
                    existed = _EXT.upsert_cells(t.rows, keys, fam, quals,
                                                vals, t.pending)
                    self._dirty_add_new(t, keys, existed)
                    continue
                apply_put = self._apply_put
                for lk, lq, lv in zip(kl.tolist(), ql.tolist(),
                                      vl.tolist()):
                    apply_put(table, payload[ko:ko + lk], fam,
                              payload[qo:qo + lq],
                              payload[vo:vo + lv])
                    ko += lk
                    qo += lq
                    vo += lv
                continue
            parts = self._split_payload(payload)
            table = parts[0].decode()
            if op == _OP_PUT:
                _, key, fam, qual, value = parts
                self._apply_put(table, key, fam, qual, value)
            elif op == _OP_DELETE:
                _, key, fam, *quals = parts
                self._apply_delete(table, key, fam, quals)
            elif op == _OP_DELETE_ROW:
                _, key = parts
                self._apply_delete_row(table, key)
        return valid

    def flush(self) -> None:
        """Force WAL to stable storage (reference: HBaseClient.flush)."""
        with self._lock:
            if self._wal is not None:
                self._wal.flush()
                os.fsync(self._wal.fileno())
                if self._wal_group_ms > 0:
                    self._grp_sync_locked()

    def close(self) -> None:
        with self._lock:
            try:
                if self._wal is not None:
                    try:
                        self.flush()
                    finally:
                        # A failed final fsync (ENOSPC/EIO) must still
                        # release the fds and the flock — the error
                        # propagates, but a store that stays locked
                        # wedges every later open in this process.
                        self._wal.close()
                        self._wal = None
            finally:
                for sst in self._ssts:
                    sst.close()
                self._ssts = []
                if self._lockfd is not None:
                    os.close(self._lockfd)  # releases the flock
                    self._lockfd = None

    def _simulate_crash(self) -> None:
        """TEST HOOK: release the single-writer lock WITHOUT flushing
        or closing, the way process death does (the OS drops a dead
        process's flock; unflushed state is simply lost). Crash-
        recovery tests reopen the wal path after calling this."""
        with self._lock:
            if self._lockfd is not None:
                os.close(self._lockfd)
                self._lockfd = None

    # -- cluster promotion / demotion (cluster/) --------------------------

    def _try_take_lock(self) -> bool:
        """Non-blocking attempt at the single-writer flock (the
        promoted-over-a-zombie recovery path). Returns True when
        held after the call."""
        if self._lockfd is not None:
            return True
        lockfd = os.open(self._wal_path + ".lock",
                         os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(lockfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(lockfd)
            return False
        self._lockfd = lockfd
        logging.getLogger(__name__).info(
            "re-acquired single-writer lock at %s.lock",
            self._wal_path)
        return True

    def promote_writable(self, writer_epoch: int,
                         epoch_guard=None) -> None:
        """Take write ownership of this replica's store (replica
        promotion, cluster/promote.py). The caller has already bumped
        the persisted epoch (``bump_epoch``); this is the storage
        half:

        1. Try the advisory single-writer flock — but do NOT let a
           wedged-but-alive zombie (which still holds it) block the
           takeover: in cluster mode the EPOCH is the authority, the
           flock is best-effort courtesy. A deposed-but-locked zombie
           is fenced by its guard on the next mutation, and its
           appends land on an unlinked inode (step 3).
        2. Re-run the WRITER recovery path over the store (torn tails
           truncated, .old + WAL replayed — the exact crash-recovery
           code, correct in any in-flight writer state).
        3. Reopen the WAL tail under a GUARANTEED-FRESH inode (the
           PR-1 rotation discipline: pre-promotion records move to
           ``<wal>.old``, tmp + ``os.replace`` mints the new file) and
           stamp the new epoch header — the zombie's still-open fd now
           points at an unlinked inode, so even its pre-fence appends
           can never reach a file anyone replays.
        """
        with self._lock:
            if not self.read_only:
                raise ValueError("promote_writable() is for read-only "
                                 "replica stores")
            if not self._wal_path:
                raise ValueError("an in-memory store cannot be "
                                 "promoted")
            if writer_epoch < 1:
                raise ValueError(f"writer epoch must be >= 1, got "
                                 f"{writer_epoch}")
            lockfd = os.open(self._wal_path + ".lock",
                             os.O_CREAT | os.O_RDWR, 0o644)
            try:
                fcntl.flock(lockfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                # The deposed owner is alive and still holds it. The
                # epoch fence makes proceeding safe; refusing here
                # would make a WEDGED writer (the promotion trigger!)
                # un-deposable.
                os.close(lockfd)
                lockfd = None
                logging.getLogger(__name__).warning(
                    "promoting over a held writer lock at %s.lock — "
                    "epoch fence (epoch %d) deposes the holder",
                    self._wal_path, writer_epoch)
            _fault("cluster.promote.take", self._wal_path)
            old_ssts, old_tables = self._ssts, self._tables
            old_state = self._ro_state
            self._ssts = []
            self._tables = {}
            self._ro_state = None
            self.read_only = False
            self.writer_epoch = int(writer_epoch)
            try:
                # Writer-path recovery (NOT the replica's): truncates
                # torn tails, replays .old + WAL, opens for append,
                # stamps the epoch into the current segment.
                self._open_tiers(self._wal_path)
                self._promote_rotate_locked()
            except BaseException:
                # Stay a coherent REPLICA on any failure (fault
                # injected mid-rotation, disk full): close whatever
                # half-opened, restore the pre-promotion view, release
                # the lock — the caller retries or picks another
                # target.
                for sst in self._ssts:
                    sst.close()
                if self._wal is not None:
                    self._wal.close()
                    self._wal = None
                self._ssts, self._tables = old_ssts, old_tables
                self._ro_state = old_state
                self.read_only = True
                self.writer_epoch = None
                if lockfd is not None:
                    os.close(lockfd)
                raise
            self._lockfd = lockfd
            self.epoch_guard = epoch_guard
            for sst in old_ssts:
                sst.close()
            # The generation set was replaced wholesale (a rebuild, as
            # far as cache consumers can tell): bump the rebuild
            # counter (sketch reload key) and jump the fragment-cache
            # stamp floor.
            self.rebuilds += 1
            self.mutation_seq += 1
            self._stamp_floor = self.mutation_seq
            self._base_stamps = {}
            self._stamps_snap = {}
            self._dirty_snap = {}

    def _promote_rotate_locked(self) -> None:
        """The fresh-inode WAL rotation of a promotion (checkpoint's
        rotation discipline, minus the spill): pre-promotion records
        move to ``<wal>.old`` — appended when a crash remnant already
        exists, renamed otherwise — and the fresh segment opens with
        this writer's epoch header. Recovery replays .old then the
        WAL, so a crash anywhere in here loses nothing."""
        _fault("cluster.promote.rotate", self._wal_path)
        if self._wal is not None:
            # Cover every deferred group-commit ticket before the fd
            # goes away (close() only reaches the page cache; parked
            # barriers must see their fsync happen, not vanish).
            self._wal_flush()
            self._wal.close()
            self._wal = None
        old_path = self._wal_path + ".old"
        if os.path.exists(self._wal_path):
            # COPY into .old, never rename: a rename keeps the old
            # inode LINKED (at .old — a file recovery replays), so a
            # zombie's still-open fd would keep appending into the
            # replay stream. Copying leaves the zombie's inode with no
            # name the moment the replace below lands; records it
            # appends after our read vanish with it. A crash between
            # copy and replace duplicates the WAL into .old — replay
            # is an upsert, so the double-apply is idempotent (the
            # same property checkpoint's crash-recovered .old append
            # relies on).
            with open(old_path, "ab") as dst, \
                    open(self._wal_path, "rb") as src:
                # Streamed, not one read(): a plain writer defaults to
                # manual checkpoints, so the WAL at failover time can
                # be the whole ingest history — materializing it as
                # one bytes object could OOM the promotion candidate
                # under exactly the load that killed the writer.
                import shutil as _shutil
                _shutil.copyfileobj(src, dst, 1 << 20)
                dst.flush()
                os.fsync(dst.fileno())
            # tmp-then-replace, not unlink-then-create: the tmp's
            # inode is allocated while the old WAL is still linked,
            # so the filesystem cannot recycle the number (the PR-1
            # replica-cursor lesson).
            tmp = self._wal_path + ".rotate"
            self._wal = open(tmp, "wb")
            os.replace(tmp, self._wal_path)
        else:
            self._wal = open(self._wal_path, "ab")
        self._grp_rotated_locked()
        self._stamp_epoch_header(force=True)
        self._wal_flush()

    def demote_readonly(self) -> None:
        """Deposed writer → tailing replica, in place: drop the WAL
        fd and the flock, flip read-only, and rebuild the view through
        the replica recovery path (which never truncates — the new
        writer owns the files now). The caller (TSDB.demote) holds
        the checkpoint lock so no spill is in flight."""
        with self._lock:
            if self.read_only:
                return
            if self._wal is not None:
                try:
                    self._wal.flush()
                except OSError:
                    pass  # likely an unlinked inode already; fine
                self._wal.close()
                self._wal = None
            if self._lockfd is not None:
                os.close(self._lockfd)
                self._lockfd = None
            # A frozen middle tier (fence tripped mid-checkpoint) is
            # fully covered by <wal>.old — the rotation preceded the
            # freeze — so the rebuild below reproduces it from disk.
            self._frozen = None
            self.read_only = True
            self.writer_epoch = None
            self.epoch_guard = None
            self._rebuild_locked()

    # -- checkpoint / spill ----------------------------------------------

    def checkpoint(self) -> int:
        """Spill the frozen memtable to a new sstable generation, then
        drop the pre-checkpoint WAL records. Returns rows written
        (0 = not persistent / already in progress).

        Normally an O(frozen-rows) memtable-only spill: the new
        generation is appended to the tier list and reads overlay it
        (full rewrites grew linearly with history — 28 s at 25M points,
        114 s at 75M — which dominated sustained ingest). When the
        generation count hits _MAX_GENERATIONS, a SIZE-TIERED partial
        merge collapses only the newest age-contiguous suffix of
        generations (plus frozen) whose combined size the next-older
        generation does not dwarf — so the largest, oldest generations
        are left untouched and write amplification stays logarithmic
        instead of rewriting the whole history every cap-hit (268 s of
        the 828 s 1B-run wall was the two full collapses). A FULL
        merge (every generation + frozen) runs only when the frozen
        tier holds tombstones: tombstones must mask cells in EVERY
        lower generation, and a partial merge would drop them for the
        kept prefix, resurrecting the masked cells.

        Three phases, designed so ingest/queries never wait on the merge:
          1. (brief lock) freeze the memtable as an immutable middle tier,
             rotate the WAL: pre-checkpoint records move to <wal>.old,
             writes continue into a fresh WAL.
          2. (no lock) stream the spill into a temp file, fsync,
             atomically rename to the new generation.
          3. (brief lock) open the new generation, write the manifest
             (the authoritative generation set — stray files from a
             crash between manifest write and unlinks are deleted at
             next load), discard the frozen tier, unlink <wal>.old.
        Crash-safe: <wal>.old survives until the new generation is durable
        (sstable.write_sstable fsyncs the file AND its directory before
        phase 3); recovery replays <wal>.old then the WAL, which is
        idempotent over any manifest state.
        """
        if self._sst_path is None or self.read_only:
            return 0
        if self.epoch_guard is not None:
            # Fence BEFORE the rotation: a deposed writer's checkpoint
            # renames WAL files BY PATH and rewrites the manifest —
            # the single most destructive thing a zombie can do to the
            # store its successor now owns. force=True: a checkpoint
            # is rare enough to afford a fresh read of the epoch file.
            self.epoch_guard.check(force=True)
        if self._lockfd is None and self.writer_epoch is not None:
            # A promotion over a still-held zombie flock came out
            # lockless (epoch fence was the authority). Re-acquire
            # opportunistically once the zombie exits, so a later
            # NON-cluster writer — to which no epoch fence applies —
            # is refused by the lock like on any other store.
            self._try_take_lock()
        old_path = self._wal_path + ".old"
        t_p1 = _perf()
        with self._lock:
            if self._frozen is not None:
                return 0  # merge already in flight
            self._frozen = self._tables
            self._tables = {name: _Table() for name in self._frozen}
            self.mutation_seq += 1
            if self._wal is not None:
                # Cover every deferred group-commit ticket before the
                # fd goes away — parked barriers wake durable, and a
                # leader racing the close sees its ticket covered.
                if self._wal_group_ms > 0:
                    self._wal_flush()
                self._wal.close()
                if os.path.exists(old_path):
                    # A crash-recovered .old is still live state: append the
                    # current WAL to it rather than clobbering it.
                    with open(old_path, "ab") as dst, \
                            open(self._wal_path, "rb") as src:
                        dst.write(src.read())
                        dst.flush()
                        os.fsync(dst.fileno())
                    # Recreate the WAL under a GUARANTEED-FRESH inode
                    # (empty tmp + os.replace) rather than truncating
                    # in place: replicas key their suffix-replay
                    # position on the WAL's inode, and an in-place 'wb'
                    # kept the inode while resetting the offset — once
                    # the regrown WAL crossed a replica's stale offset,
                    # its replay seeked mid-record and could misparse
                    # arbitrary bytes as records (frames carry no
                    # checksum). tmp-then-replace, not unlink-then-
                    # create: the tmp's inode is allocated while the
                    # old WAL is still linked, so the filesystem cannot
                    # hand the replacement the just-freed inode number
                    # (tmpfs recycles eagerly). A crash in between
                    # surfaces either WAL state; recovery replays
                    # <wal>.old (which holds every record) first.
                    tmp = self._wal_path + ".rotate"
                    self._wal = open(tmp, "wb")
                    os.replace(tmp, self._wal_path)
                else:
                    os.replace(self._wal_path, old_path)
                    self._wal = open(self._wal_path, "ab")
                self._grp_rotated_locked()
                # A cluster-mode writer begins the fresh segment with
                # its epoch header (replay-side fence anchor).
                self._stamp_epoch_header(force=True)
            frozen = self._frozen
            spill_keys = None
            if self.record_spill_keys:
                # Keys leaving the memtable this checkpoint (row
                # tombstones included: a delete of spilled data must
                # reach the rollup fold too, or stale summaries would
                # keep serving the deleted points).
                spill_keys = {
                    name: list(ft.rows) + list(ft.row_tombs)
                    for name, ft in frozen.items()
                    if ft.rows or ft.row_tombs}
            gens = list(self._ssts)
            tombstoned = any(ft.row_tombs or ft.tombs
                             for ft in frozen.values())
            if tombstoned:
                keep: list[SSTable] = []
                merge_gens = gens
            elif len(gens) + 1 >= self._MAX_GENERATIONS:
                keep, merge_gens = self._select_merge_suffix(gens)
            else:
                keep, merge_gens = gens, []
            use_merge = tombstoned or bool(merge_gens)
            empty = not any(ft.rows or ft.row_tombs
                            for ft in frozen.values())
            out_path = self._next_generation_path()
        _M_CKPT_PHASE["freeze"].observe((_perf() - t_p1) * 1000.0)

        if empty:
            # Nothing to spill, but the WAL rotation above must still
            # conclude: a WAL whose records net out to an empty
            # memtable (put-then-delete churn on unspilled rows) holds
            # no state the generations don't — dropping <wal>.old loses
            # nothing, and skipping here would let idle/churn daemons'
            # timer checkpoints grow the WAL without bound while an
            # empty generation file accreted per call.
            with self._lock:
                self._fold_touch_locked(self._frozen)
                self._frozen = None
                self.mutation_seq += 1
                if os.path.exists(old_path):
                    os.unlink(old_path)
            return 0

        if use_merge:
            # Copy-merge collapse (sstable.merge_sstables): unique-key
            # records relocate verbatim at IO speed; only multi-source
            # keys and the frozen tier re-frame (tombstones applied
            # there). The streamed per-row merge this replaces cost
            # 20.7 us/row — 145 s at the 7M-row mark of the 1B run.
            frozen_payload = {
                name: (ft.rows, ft.row_tombs, bool(ft.tombs))
                for name, ft in frozen.items()}
        else:
            def spill_tables():
                # Memtable-only: by the tombstone test above the frozen
                # tier holds no tombstones, so every cell value is
                # real bytes and no lower-generation read is needed.
                # Sorted keys + the row dict itself: write_sstable_bulk
                # frames records straight off the memtable in C — the
                # per-row Python framing/materialization was ~5 us/row,
                # most of a 22 s spill at 4.4M rows.
                return {name: ([k for k in sorted(ft.rows) if ft.rows[k]],
                               ft.rows)
                        for name, ft in frozen.items()}

        try:
            # End of phase 1: the WAL is rotated (<wal>.old holds every
            # pre-checkpoint record), the memtable is frozen, nothing
            # spilled yet. Crash here must recover purely from
            # .old + WAL replay; raise exercises the thaw path below.
            _fault("kv.checkpoint.freeze", self._wal_path)
            # kwarg only when compressing: the default spill call shape
            # stays identical (tests stub these writers by signature).
            kw = {"codec": self.sstable_codec} \
                if self.sstable_codec not in (None, "none") else {}
            with _M_CKPT_PHASE["spill"].time():
                n = (merge_sstables(out_path, merge_gens, frozen_payload,
                                    **kw)
                     if use_merge
                     else write_sstable_bulk(out_path, spill_tables(),
                                             **kw))
        except Exception:
            # Disk full or similar mid-merge: thaw the frozen tier back
            # under the live memtable so the store isn't wedged (a stuck
            # _frozen would make every future checkpoint a no-op and let
            # the WAL grow without bound). <wal>.old stays on disk; the
            # next checkpoint appends the live WAL to it, and recovery
            # replays .old + WAL, so durability is unaffected.
            with self._lock:
                self._thaw_frozen_locked()
            raise

        t_p3 = _perf()
        with self._lock:
            # Phase 3 failures (sstable open, manifest tmp write right
            # after a near-full-disk spill) get the SAME recovery as a
            # spill failure: drop the new generation and thaw — a stuck
            # _frozen would no-op every later checkpoint and grow the
            # WAL without bound, with durability intact but the daemon
            # degraded until restart.
            new_sst = None
            unlink_new = True
            try:
                if self.epoch_guard is not None:
                    # Re-fence at the COMMIT: a promotion that landed
                    # while phase 2 streamed must stop this checkpoint
                    # before it rewrites the manifest and unlinks
                    # <wal>.old out from under the new owner. The
                    # exception path below already knows how to back a
                    # failed commit out (unlink the new generation,
                    # thaw the frozen tier).
                    self.epoch_guard.check(force=True)
                new_sst = SSTable(out_path)
                # The new generation is durable but the manifest does
                # not name it yet: crash leaves it a stray the next
                # load deletes (.old still replays everything); raise
                # exercises the unlink-and-thaw recovery below.
                _fault("kv.checkpoint.commit", out_path)
                # The new generation replaces exactly the merged
                # age-contiguous suffix (all of them on a full merge,
                # none on a plain spill), preserving overlay order:
                # everything in `keep` is strictly older than what the
                # new generation holds.
                dropped = merge_gens
                self._ssts = keep + [new_sst]
                # Manifest BEFORE unlinking: a crash in between leaves
                # stray files the next load deletes (they are never
                # opened, so dropped cells cannot resurrect).
                try:
                    self._write_manifest([s.path for s in self._ssts])
                except Exception:
                    old = keep + merge_gens
                    self._ssts = old
                    # The failure point is ambiguous: the new manifest
                    # may already be DURABLE (os.replace landed, the
                    # directory fsync failed). Unlinking the new
                    # generation under a durable manifest that names it
                    # would make every OLD generation a manifest-stray
                    # — deleted at next open, silently losing all
                    # previously spilled rows. Restore the old
                    # manifest first; if even that fails, keep the new
                    # file: both (old manifest, stray new file) and
                    # (new manifest, new file) are consistent states.
                    try:
                        self._write_manifest([s.path for s in old])
                    except Exception:
                        unlink_new = False
                    raise
            except Exception:
                if new_sst is not None:
                    new_sst.close()
                if unlink_new:
                    try:
                        os.unlink(out_path)
                    except OSError:
                        pass
                self._thaw_frozen_locked()
                raise
            self._frozen = None
            self.mutation_seq += 1
            # Manifest durable, dropped generations + <wal>.old not yet
            # unlinked: crash leaves strays (deleted at next load) and
            # an idempotently-replayable .old. Safe for raise too — the
            # commit is complete; only cleanup remains.
            _fault("kv.checkpoint.manifest", self._wal_path)
            # The frozen tier retires: fold its transition stamps into
            # the store-level map so fragments built while (or before)
            # its rows were live keep invalidating — including bases a
            # create-then-delete netted back to clean, which no longer
            # appear in any dirty set but may sit inside a cached
            # fragment.
            self._fold_touch_locked(frozen)
            if spill_keys is not None:
                for name, ks in spill_keys.items():
                    self._last_spill_keys.setdefault(name, []).extend(ks)
                # The frozen tier's dirty index IS the spilled keys'
                # base refcounts (rows + row tombstones): carry it as
                # the undrained-spill dirty set, summed like the key
                # record itself.
                for name, ft in frozen.items():
                    if ft.dirty:
                        sd = self._spill_dirty.setdefault(name, {})
                        for b, c in ft.dirty.items():
                            sd[b] = sd.get(b, 0) + c
            for g in dropped:
                path = g.path
                g.close()
                try:
                    os.unlink(path)
                except OSError:
                    pass
            if os.path.exists(old_path):
                os.unlink(old_path)
        _M_CKPT_PHASE["commit"].observe((_perf() - t_p3) * 1000.0)
        return n

    @staticmethod
    def _select_merge_suffix(gens: "list[SSTable]",
                             ) -> "tuple[list[SSTable], list[SSTable]]":
        """Size-tiered pick at the generation cap: absorb older
        generations into the merge only while each is no larger than
        everything newer already being merged. This yields geometric
        tiers — the oldest, largest generations are kept verbatim and
        the generation count stays bounded (the suffix always absorbs
        at least one existing generation, so each partial merge
        shrinks the count by at least one... or holds it at cap-1 in
        the steady state). Returns (keep-prefix, merge-suffix), both
        age-ordered.

        The frozen tier's sstable footprint is estimated as the size
        of the NEWEST generation (steady-state spill windows are
        equal). Using the rotated <wal>.old size instead degenerated
        in the 1B run: WAL bytes run ~2.3x the sstable bytes for the
        same data, the over-estimate dragged the accumulated big
        generation into EVERY cap-hit, and the per-checkpoint merge
        grew linearly (5.2M -> 11.4M rows over 10 checkpoints —
        quadratic total IO, the exact pathology tiering exists to
        avoid)."""
        def size(g):
            try:
                return os.path.getsize(g.path)
            except OSError:
                # Unreadable: treat as too big to absorb — the loop
                # stops at it. As the SEED that would invert into
                # absorb-everything, so the seed uses 0 instead (the
                # pick then merges just the newest gen + frozen, the
                # minimal safe choice).
                return None
        i = len(gens) - 1          # always absorb the newest
        newest = size(gens[-1])
        acc = 2 * (newest or 0)    # + the frozen tier, estimated equal
        while i > 0:
            s = size(gens[i - 1])
            if s is None or s > acc:
                break
            acc += s
            i -= 1
        return gens[:i], gens[i:]

    def _fold_touch_locked(self, tables: "dict[str, _Table]") -> None:
        """Fold retiring tiers' transition stamps into the store-level
        map (max wins). Caller holds the lock."""
        for name, ft in tables.items():
            if not ft.touch:
                continue
            st = self._base_stamps.setdefault(name, {})
            for b, v in ft.touch.items():
                if st.get(b, -1) < v:
                    st[b] = v

    def _thaw_frozen_locked(self) -> None:
        """Fold the frozen middle tier back under the live memtable
        after a failed checkpoint (caller holds the lock). Live cells
        win; row tombstones written while the merge was in flight keep
        masking the thawed rows."""
        for name, ft in self._frozen.items():
            live = self._tables[name]
            for k, row in ft.rows.items():
                if k in live.row_tombs:
                    continue  # deleted while merge was in flight
                merged = dict(row)
                merged.update(live.rows.get(k, {}))
                live.rows[k] = merged
            live.row_tombs |= ft.row_tombs
            # Tombstone cells travel back with the rows: the counter
            # must too, or the RETRY checkpoint would pick the fast
            # tombstone-free spill and feed None values to
            # write_sstable (and, had that written, resurrect the
            # masked lower-generation cells).
            live.tombs += ft.tombs
            for k in ft.rows:
                live.note_insert(k)
            live.rebuild_dirty(self.mutation_seq + 1)
        self._fold_touch_locked(self._frozen)
        self._frozen = None
        self.mutation_seq += 1

    # -- mutation ---------------------------------------------------------

    def _apply_put(self, table: str, key: bytes, family: bytes,
                   qualifier: bytes, value: bytes) -> None:
        t = self._table(table)
        row = t.rows.get(key)
        if row is None:
            row = t.rows[key] = {}
            t.note_insert(key)
            t.dirty_add(key, self.mutation_seq)
        row[(family, qualifier)] = value

    def _apply_delete(self, table: str, key: bytes, family: bytes,
                      qualifiers: list[bytes]) -> None:
        t = self._table(table)
        spilled = (key not in t.row_tombs
                   and self._lower_tier_has(t, table, key))
        row = t.rows.get(key)
        if row is None:
            if not spilled:
                return
            row = t.rows[key] = {}
            t.note_insert(key)
            t.dirty_add(key, self.mutation_seq)
        for q in qualifiers:
            if spilled:
                row[(family, q)] = None  # tombstone masks the sstable cell
                t.tombs += 1
            else:
                row.pop((family, q), None)
        if not row:
            del t.rows[key]
            t.note_delete()
            t.dirty_sub(key, self.mutation_seq)

    def _apply_delete_row(self, table: str, key: bytes) -> None:
        t = self._table(table)
        if t.rows.pop(key, None) is not None:
            t.note_delete()
            t.dirty_sub(key, self.mutation_seq)
        if self._lower_tier_has(t, table, key) \
                and key not in t.row_tombs:
            t.row_tombs.add(key)
            t.dirty_add(key, self.mutation_seq)

    def _check_throttle(self, table: str, key: bytes) -> None:
        # Only throttle puts that would create a NEW row: updates to
        # existing rows (including compaction rewrites, which relieve
        # pressure) must keep flowing or backpressure can never clear.
        if self.throttle_rows is not None and \
                len(self._table(table).rows) >= self.throttle_rows and \
                key not in self._table(table).rows:
            raise PleaseThrottleError(
                f"table '{table}' holds >= {self.throttle_rows} rows")

    def put(self, table: str, key: bytes, family: bytes, qualifier: bytes,
            value: bytes, durable: bool = True) -> None:
        self._check_writable()
        with self._lock:
            self._check_throttle(table, key)
            self.mutation_seq += 1
            if durable:
                self._wal_append(_OP_PUT, table.encode(), key, family,
                                 qualifier, value)
            self._apply_put(table, key, family, qualifier, value)
            ticket = self._grp_ticket()
        self._wal_barrier(ticket)

    def put_many(self, table: str, family: bytes,
                 cells: list[tuple[bytes, bytes, bytes]],
                 durable: bool = True, sync: bool = True) -> list[bool]:
        """Batched put: one lock acquisition and one existence probe per
        distinct key for the whole batch — the ingest hot path writes one
        cell per row-hour, so per-call locking dominated before this.
        Semantics identical to a put() loop (WAL order, throttle check
        per new row, partial application if throttled mid-batch).

        ``sync=False`` (group-commit mode only) returns WITHOUT waiting
        for the covering group fsync: the caller batches several
        put_many calls and then issues ONE ``wal_barrier()`` before
        acknowledging any of them (server/wire.ingest_batch).
        """
        self._check_writable()
        existed: list[bool] = []
        if not cells:
            return existed
        tenc = table.encode()
        ticket = 0
        try:
            with self._lock:
                self.mutation_seq += 1
                t = self._table(table)
                rows = t.rows
                # With no lower tiers the memtable is the whole truth, so
                # existence is one dict probe (the default-config hot
                # path).
                pure_mem = not self._ssts and self._frozen is None
                throttle = self.throttle_rows
                wal = self._wal is not None and durable
                keys = [c[0] for c in cells]
                quals = [c[1] for c in cells]
                vals = [c[2] for c in cells]
                fast = self._try_fast_batch(
                    table, t, family, keys, quals, vals,
                    (lambda: self._wal_append_batch(tenc, family, cells))
                    if wal else None)
                if fast is not None:
                    existed = fast
                else:
                    batch_ok = False
                    try:
                        for key, qualifier, value in cells:
                            row = rows.get(key)
                            if row is None:
                                if throttle is not None \
                                        and len(rows) >= throttle:
                                    err = PleaseThrottleError(
                                        f"table '{table}' holds >= "
                                        f"{throttle} rows")
                                    err.partial_existed = existed
                                    raise err
                                e = (False if pure_mem
                                     else self._has_row_locked(table,
                                                               key))
                            else:
                                e = True if pure_mem \
                                    else self._has_row_locked(table, key)
                            if row is None:
                                row = rows[key] = {}
                                t.note_insert(key)
                                t.dirty_add(key, self.mutation_seq)
                            row[(family, qualifier)] = value
                            existed.append(e)
                        batch_ok = True
                    finally:
                        if wal and existed:
                            # ONE batch WAL record + flush covering
                            # exactly the applied prefix (len(existed)
                            # cells), written in a finally because a
                            # mid-batch throttle has already APPLIED
                            # (and will acknowledge, via
                            # partial_existed) the earlier cells: their
                            # records must reach the OS before the
                            # exception escapes, same promise as the
                            # success path. Writing AFTER the mutations
                            # is equivalent to put()'s
                            # WAL-before-mutation order here: the lock
                            # is held for the whole batch, so no reader
                            # observes mid-batch state, and an
                            # in-process crash loses the unacknowledged
                            # memtable state along with the unwritten
                            # record. The ack boundary, not the record,
                            # is the durability unit. A WAL failure
                            # (e.g. ENOSPC) must not REPLACE an
                            # in-flight exception, though: callers rely
                            # on PleaseThrottleError.partial_existed to
                            # know which cells applied, so the WAL
                            # error surfaces only when the batch itself
                            # succeeded. (A local flag, not
                            # sys.exc_info(): exc_info also sees a
                            # HANDLED exception in any CALLER's except
                            # block, which would silently swallow real
                            # flush failures for callers running retry
                            # loops.)
                            try:
                                self._wal_append_batch(
                                    tenc, family, cells[:len(existed)])
                            except Exception:
                                if batch_ok:
                                    raise
                                # Can't replace the in-flight
                                # exception, but a swallowed WAL
                                # failure means the applied cells'
                                # durability promise is BROKEN until
                                # the next successful flush — leave a
                                # trace.
                                self.wal_swallowed_flush_errors += 1
                                logging.getLogger(__name__).exception(
                                    "WAL batch append failed during "
                                    "exceptional put_many exit; %d "
                                    "applied cells not yet durable",
                                    len(existed))
                ticket = self._grp_ticket()
        except BaseException:
            # An exceptional exit (mid-batch throttle) has already
            # applied — and will acknowledge, via partial_existed — a
            # prefix of the batch: in group mode those records are
            # still unflushed tickets, so attempt the covering barrier
            # before the exception escapes. A barrier failure must not
            # replace the in-flight error (same contract as the WAL
            # append above).
            if sync and self._wal_group_ms > 0:
                try:
                    self.wal_barrier()
                except Exception:
                    self.wal_swallowed_flush_errors += 1
                    logging.getLogger(__name__).exception(
                        "group-commit barrier failed during "
                        "exceptional put_many exit")
            raise
        if sync:
            self._wal_barrier(ticket)
        return existed

    def _dirty_add_new(self, t: _Table, keys: list[bytes],
                       existed: list[bool]) -> None:
        """Index the bases of the rows a bulk upsert CREATED (existed
        False — the C pass reports intra-batch duplicates as existing,
        so each new row counts exactly once)."""
        add = t.dirty_add
        seq = self.mutation_seq
        for k, e in zip(keys, existed):
            if not e:
                add(k, seq)

    def _try_fast_batch(self, table: str, t: _Table, family: bytes,
                        keys: list[bytes], quals: list[bytes],
                        vals: list[bytes], wal_cb) -> "list[bool] | None":
        """The bulk batch-put path shared by put_many and
        put_many_columnar (one copy, so the subtle semantics — throttle
        bound, dup-aware existed flags, pending-index update, WAL
        inside the lock — cannot drift). Caller holds _lock and has
        validated lengths. Returns existed, or None when the batch is
        irregular (possible mid-batch throttle trip, or duplicate keys
        without the C upsert) and must take the per-cell loop.

        Bulk set/dict operations replace that loop, whose per-cell
        function-call overhead (note_insert, dict.get, per-cell WAL
        framing) was ~3.7 us/cell — the dominant cost of at-scale
        ingest. ``wal_cb`` writes the batch's WAL record (None when
        durability is off)."""
        rows = t.rows
        n = len(keys)
        pure_mem = not self._ssts and self._frozen is None
        throttle = self.throttle_rows
        # Conservative bound (assumes every key new): when it holds, a
        # mid-batch throttle trip is impossible.
        throttle_ok = throttle is None or len(rows) + n <= throttle
        if _EXT is not None and pure_mem and throttle_ok:
            # One C pass does the whole upsert + existed flags + the
            # pending-index adds, in lockstep with each row insert
            # (full put_many semantics incl. intra-batch duplicate
            # keys; sound only pure-memtable, where existence ==
            # presence in rows and tombstones can't exist). The
            # throttle bound is conservative (assumes every key new),
            # so a trip is impossible inside the pass.
            existed = _EXT.upsert_cells(
                rows, keys, family, quals, vals, t.pending)
            self._dirty_add_new(t, keys, existed)
            if wal_cb is not None:
                wal_cb()
            return existed
        ks = set(keys)
        # Lower-tier candidate prefilter: a key can only exist below
        # the live memtable if it is in the frozen memtable or inside
        # the sstable's key range. Sound as a filter because the exact
        # probe (_has_row_locked) remains the oracle for every
        # surviving candidate — it only drops keys NO lower tier can
        # hold. Time-ordered ingest (new base-times sort after every
        # spilled key) passes almost nothing through, which keeps
        # post-checkpoint sustained ingest off the 1 us/key bisect.
        lower = set()
        if not pure_mem:
            if self._frozen is not None:
                ft = self._frozen.get(table)
                if ft is not None:
                    lower |= ft.rows.keys() & ks
            for sst in self._ssts:
                bounds = sst.key_bounds(table)
                if bounds is not None:
                    lo, hi = bounds
                    lower |= {k for k in ks if lo <= k <= hi}
        if _EXT is not None and throttle_ok and not lower:
            # No batch key can touch a lower tier, so memtable presence
            # is existence and the C upsert stays sound post-checkpoint
            # (the sustained-ingest steady state). One nuance: a live
            # all-tombstone row reads as existed=True where the exact
            # probe could say False — benign, existed only enqueues a
            # compaction that then no-ops.
            existed = _EXT.upsert_cells(
                rows, keys, family, quals, vals, t.pending)
            self._dirty_add_new(t, keys, existed)
            if wal_cb is not None:
                wal_cb()
            return existed
        if len(ks) != n:
            return None
        dups = rows.keys() & ks
        if throttle is not None and \
                len(rows) + n - len(dups) > throttle:
            return None
        if pure_mem:
            existed = ([False] * n if not dups
                       else [k in dups for k in keys])
        else:
            candidates = dups | lower
            if candidates:
                hrl = self._has_row_locked
                present = {k for k in candidates if hrl(table, k)}
                existed = [k in present for k in keys]
            else:
                existed = [False] * n
        if not dups:
            if _EXT is not None:
                _EXT.rows_update_new(rows, keys, family, quals, vals)
            else:
                rows.update((k, {(family, q): v})
                            for k, q, v in zip(keys, quals, vals))
            t.pending.update(ks)
            for k in ks:
                t.dirty_add(k, self.mutation_seq)
        else:
            for k, q, v in zip(keys, quals, vals):
                row = rows.get(k)
                if row is None:
                    rows[k] = {(family, q): v}
                    t.dirty_add(k, self.mutation_seq)
                else:
                    row[(family, q)] = v
            t.pending.update(ks - dups)
        if wal_cb is not None:
            wal_cb()
        return existed

    def put_many_columnar(self, table: str, family: bytes,
                          key_blob: bytes, key_len: int,
                          quals: list[bytes], vals: list[bytes],
                          durable: bool = True,
                          sync: bool = True) -> list[bool]:
        """Columnar batched put: keys arrive as one contiguous blob that
        flows straight through to the WAL record. Shares the bulk fast
        path with put_many; anything irregular zips the triples and
        delegates to put_many (identical semantics). ``sync=False``:
        see put_many."""
        self._check_writable()
        n = len(quals)
        L = key_len
        if len(vals) != n or len(key_blob) != n * L:
            # Mis-framed inputs must fail loudly HERE: the WAL record
            # trusts n * key_len, so a silent mismatch would corrupt
            # durable state on replay.
            raise ValueError(
                f"columnar batch mismatch: {len(key_blob)} key bytes, "
                f"key_len {L}, {n} quals, {len(vals)} vals")
        if n == 0:
            return []
        if _EXT is not None:
            keys = _EXT.slice_keys(key_blob, L)
        else:
            keys = [key_blob[i:i + L] for i in range(0, n * L, L)]
        with self._lock:
            self.mutation_seq += 1
            t = self._table(table)
            wal = self._wal is not None and durable
            fast = self._try_fast_batch(
                table, t, family, keys, quals, vals,
                (lambda: self._wal_append_batch_columnar(
                    table.encode(), family, key_blob, n, L, quals,
                    vals)) if wal else None)
            ticket = self._grp_ticket()
        if fast is not None:
            if sync:
                self._wal_barrier(ticket)
            return fast
        return self.put_many(table, family, list(zip(keys, quals, vals)),
                             durable=durable, sync=sync)

    def delete(self, table: str, key: bytes, family: bytes,
               qualifiers: list[bytes]) -> None:
        self._check_writable()
        hook = self.delete_hook
        if hook is not None:
            hook(table, key)
        with self._lock:
            self.mutation_seq += 1
            self._wal_append(_OP_DELETE, table.encode(), key, family,
                             *qualifiers)
            self._apply_delete(table, key, family, qualifiers)
            ticket = self._grp_ticket()
        self._wal_barrier(ticket)

    def delete_row(self, table: str, key: bytes) -> None:
        self._check_writable()
        hook = self.delete_hook
        if hook is not None:
            hook(table, key)
        with self._lock:
            self.mutation_seq += 1
            self._wal_append(_OP_DELETE_ROW, table.encode(), key)
            self._apply_delete_row(table, key)
            ticket = self._grp_ticket()
        self._wal_barrier(ticket)

    # -- reads ------------------------------------------------------------

    def get(self, table: str, key: bytes,
            family: bytes | None = None) -> list[Cell]:
        with self._lock:
            row = self._merged_row(table, key)
            if not row:
                return []
            cells = [Cell(key, f, q, v) for (f, q), v in row.items()
                     if family is None or f == family]
            cells.sort(key=lambda c: (c.family, c.qualifier))
            return cells

    def _snapshot_keys(self, table: str, start: bytes,
                       stop: bytes,
                       skip_paths: "set[str] | None" = None,
                       ) -> list[bytes]:
        """Key snapshot across all tiers (live memtable + frozen +
        sstable, tombstone-excluded). Caller holds the lock. One
        definition for scan() and scan_raw() so tier-merge fixes can't
        diverge the two. ``skip_paths``: generations the caller's
        series-bloom prefilter proved irrelevant."""
        t = self._table(table)
        keys = t.range_keys(start, stop)
        ft = self._frozen.get(table) if self._frozen else None
        extra = set()
        if ft is not None:
            extra.update(k for k in ft.range_keys(start, stop)
                         if k not in t.rows and k not in t.row_tombs)
        for sst in self._ssts:
            if skip_paths and sst.path in skip_paths:
                continue
            extra.update(
                k for k in sst.scan_keys(table, start, stop)
                if k not in t.rows and k not in t.row_tombs
                and not (ft is not None and (k in ft.rows
                                             or k in ft.row_tombs)))
        if extra:
            keys = sorted(set(keys) | extra)
        return keys

    def scan(self, table: str, start: bytes, stop: bytes,
             family: bytes | None = None,
             key_regexp: bytes | None = None) -> Iterator[list[Cell]]:
        """Yield one sorted cell-list per row with key in [start, stop).

        ``key_regexp`` applies a DOTALL bytes regex to the whole key —
        parity with the HBase KeyRegexpFilter used for tag filtering
        (reference TsdbQuery.createAndSetFilter :433-492).

        Snapshot semantics: keys are snapshotted at call time; rows deleted
        mid-scan are skipped, rows mutated mid-scan show their new cells —
        the same weak guarantees an HBase scanner gives across RPC batches.
        """
        pattern = re.compile(key_regexp, re.S) if key_regexp else None
        with self._lock:
            keys = self._snapshot_keys(table, start, stop)
        for key in keys:
            if pattern is not None and not pattern.match(key):
                continue
            with self._lock:
                row = self._merged_row(table, key)
                if not row:
                    continue
                cells = [Cell(key, f, q, v) for (f, q), v in row.items()
                         if family is None or f == family]
            cells.sort(key=lambda c: (c.family, c.qualifier))
            if cells:
                yield cells

    def scan_raw(self, table: str, start: bytes, stop: bytes,
                 family: bytes | None = None,
                 key_regexp: bytes | None = None, chunk: int = 1024,
                 series_hint: "np.ndarray | None" = None,
                 ) -> Iterator[tuple[bytes, list[tuple[bytes, bytes]]]]:
        """Batched form of scan() for the columnar decode path: rows as
        (key, sorted [(qualifier, value), ...]), the lock taken once per
        ``chunk`` keys and no Cell allocations. Same snapshot semantics
        as scan(); a 1M-point query scans ~100k+ row-hours, so the
        per-row lock/namedtuple/generator overhead of the cell API was
        the single largest host cost of the cold query path (profiled:
        ~16 us/row, more than the vectorized decode itself).

        ``series_hint`` (see KVStore.scan_raw) prunes generations whose
        series bloom excludes every candidate — on a high-file-count
        store most generations hold disjoint time ranges OF THE SAME
        series, but tag-filtered dashboards and sparse metrics leave
        whole generations with nothing to say. Skips are decided ONCE
        per scan against the then-current generation set and matched
        by path thereafter: a generation swapped in mid-scan is simply
        not skipped (conservative), and one dropped mid-scan vanishes
        from self._ssts like any other scan."""
        pattern = re.compile(key_regexp, re.S) if key_regexp else None
        with self._lock:
            skip_paths: set[str] | None = None
            if series_hint is not None and len(series_hint) \
                    and self._ssts:
                skip_paths = set()
                for sst in self._ssts:
                    if not sst.bloom_may_contain(table, series_hint):
                        skip_paths.add(sst.path)
                        self.bloom_files_skipped += 1
                if not skip_paths:
                    skip_paths = None
            keys = self._snapshot_keys(table, start, stop, skip_paths)
        if pattern is not None:
            keys = [k for k in keys if pattern.match(k)]
        for i in range(0, len(keys), chunk):
            out = []
            with self._lock:
                # Tier state re-checked UNDER THE LOCK each chunk: a
                # concurrent checkpoint() can freeze the live memtable
                # between chunks, and a stale fast-path would then read
                # the freshly-emptied live dict and silently drop rows.
                if not self._ssts and self._frozen is None:
                    # No lower tiers => no tombstones; read the live
                    # memtable dict directly (skips a function call +
                    # tier checks per row — this loop runs per row-hour
                    # over the whole scanned range).
                    rows_get = self._table(table).rows.get
                    for key in keys[i:i + chunk]:
                        row = rows_get(key)
                        if not row:
                            continue
                        items = [(q, v) for (f, q), v in row.items()
                                 if family is None or f == family]
                        if items:
                            items.sort()
                            out.append((key, items))
                elif pattern is not None:
                    # Selective regexp scans touch few rows: per-key
                    # merged reads beat extracting whole key ranges
                    # that the filter would then discard.
                    for key in keys[i:i + chunk]:
                        row = self._merged_row(table, key)
                        if not row:
                            continue
                        items = [(q, v) for (f, q), v in row.items()
                                 if family is None or f == family]
                        if items:
                            items.sort()
                            out.append((key, items))
                else:
                    # Tiered: RANGE-extract each generation once per
                    # chunk (two bisects + a sequential record walk)
                    # instead of probing every generation per key —
                    # per-key sst.get() was ~5 s of a 17 s cold 1-week
                    # scan over the 1B store (2.35M probes). Overlay
                    # order and tombstone semantics are exactly
                    # _merged_row's: generations oldest->newest, then
                    # frozen, then the live memtable; row tombstones
                    # mask all lower tiers.
                    ck = keys[i:i + chunk]
                    lo = ck[0]
                    hi = keys[i + chunk] if i + chunk < len(keys) \
                        else (stop or None)
                    t = self._table(table)
                    ft = self._frozen.get(table) if self._frozen \
                        else None
                    # Row tombstones suppress generation rows BEFORE
                    # the record decode (post-delete_row sweeps can
                    # mask many keys until the next full merge).
                    masked = t.row_tombs
                    if ft is not None and ft.row_tombs:
                        masked = masked | ft.row_tombs
                    merged: dict[bytes, dict] = {}
                    for sst in self._ssts:
                        if skip_paths and sst.path in skip_paths:
                            continue
                        for key, cells in sst.iter_rows_range(
                                table, lo, hi, skip=masked):
                            row = merged.get(key)
                            if row is None:
                                row = merged[key] = {}
                            for f, q, v in cells:
                                row[(f, q)] = v
                    if ft is not None:
                        for key in ft.range_keys(lo, hi):
                            if key in t.row_tombs:
                                continue
                            row = merged.get(key)
                            if row is None:
                                row = merged[key] = {}
                            for ckey, v in ft.rows[key].items():
                                if v is None:
                                    row.pop(ckey, None)
                                else:
                                    row[ckey] = v
                    live_get = t.rows.get
                    for key in ck:
                        row = merged.get(key)
                        lrow = live_get(key)
                        if lrow:
                            if row is None:
                                row = dict(lrow)
                            else:
                                for ckey, v in lrow.items():
                                    if v is None:
                                        row.pop(ckey, None)
                                    else:
                                        row[ckey] = v
                        if not row:
                            continue
                        if family is None:
                            items = [(q, v) for (f, q), v in row.items()
                                     if v is not None]
                        else:
                            items = [(q, v) for (f, q), v in row.items()
                                     if f == family and v is not None]
                        if items:
                            items.sort()
                            out.append((key, items))
            yield from out

    # -- atomics ----------------------------------------------------------

    def atomic_increment(self, table: str, key: bytes, family: bytes,
                         qualifier: bytes, amount: int = 1) -> int:
        """Increment an 8-byte big-endian counter cell, returning the new
        value (initialized from 0 like HBase's ICV)."""
        self._check_writable()
        with self._lock:
            row = self._merged_row(table, key)
            cur = row.get((family, qualifier)) if row else None
            value = (struct.unpack(">q", cur)[0] if cur else 0) + amount
            packed = struct.pack(">q", value)
            self.mutation_seq += 1
            self._wal_append(_OP_PUT, table.encode(), key, family, qualifier,
                             packed)
            self._apply_put(table, key, family, qualifier, packed)
            ticket = self._grp_ticket()
        self._wal_barrier(ticket)
        return value

    def compare_and_set(self, table: str, key: bytes, family: bytes,
                        qualifier: bytes, expected: bytes | None,
                        value: bytes) -> bool:
        """Atomic CAS: write only if the cell currently equals ``expected``
        (None = cell must not exist). Returns success."""
        self._check_writable()
        with self._lock:
            row = self._merged_row(table, key)
            cur = row.get((family, qualifier)) if row else None
            if cur != expected:
                return False
            self.mutation_seq += 1
            self._wal_append(_OP_PUT, table.encode(), key, family, qualifier,
                             value)
            self._apply_put(table, key, family, qualifier, value)
            ticket = self._grp_ticket()
        self._wal_barrier(ticket)
        return True
