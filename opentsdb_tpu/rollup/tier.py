"""RollupTier — the materialized multi-resolution summary tier.

A parallel per-shard storage tier holding one summary record per
(series, coarse window) at each configured resolution (default 1h and
1d), computed at checkpoint-spill time and served by the query
planner's rollup step (rollup/planner.py) so long-range downsampled
queries cost O(windows) instead of O(points).

Layout
------
Each raw shard gets sibling rollup stores::

    <dir>/shard-<i>/rollup-<res>/wal[.sst...]     (sharded stores)
    <wal>.rollup-<res>/wal[.sst...]               (single MemKVStore)

Every rollup store is a plain ``MemKVStore`` — WAL durability, crash
replay, sstable spill, and replica semantics are inherited, not
re-implemented. Rollup rows reuse the raw row-key SHAPE
(``[metric:3][base:4][tagk tagv]*``) with the base-time slot holding a
*superwindow* start (``resolution * pack`` seconds), so the sharded
store's series-hash routing and the scan regexps built for raw keys
apply unchanged; one row packs ``pack`` consecutive windows as cells
(qualifier = (window idx, kind)).

Consistency contract ("stale degrades, never lies")
---------------------------------------------------
A raw point is ALWAYS in at least one of: (a) the memtable/frozen tier
(its row key is in ``store.pending_keys``), (b) a window in the tier's
in-flight set (spilled but the fold hasn't committed), or (c) a rollup
record. The planner treats (a)+(b) windows as *dirty* and stitches
them from raw, so a summary is only ever served for windows whose
every point it covers. Records are REPLACED from a full re-read of the
window's raw rows (never incrementally merged on the write path), so
re-folds after WAL replay, duplicate ingest, out-of-order backfill,
and deletes are all idempotent.

Crash safety: ``ROLLUP.json`` flips to ``pending`` before each
checkpoint's spill and back to ``ok`` only after the fold commits; a
crash in between leaves ``pending`` and the next open schedules a
full rebuild (the catch-up daemon) while queries fall back to raw. A
missing/foreign-config tier rebuilds the same way.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
from typing import Iterable

import numpy as np

from opentsdb_tpu.core import codec, codec_np
from opentsdb_tpu.core.const import MAX_TIMESPAN, TIMESTAMP_BYTES, UID_WIDTH
from opentsdb_tpu.core.errors import IllegalDataError
from opentsdb_tpu.fault.faultpoints import fire as _fault
from opentsdb_tpu.obs.registry import METRICS as _metrics
from opentsdb_tpu.rollup import summary
from opentsdb_tpu.rollup.summary import (QUAL_MOMENTS, QUAL_SKETCH,
                                         REC_DTYPE, REC_SIZE,
                                         ROLLUP_FAMILY)
from opentsdb_tpu.storage.kv import MemKVStore

LOG = logging.getLogger(__name__)

STATE_NAME = "ROLLUP.json"

# Raw data family (core/tsdb.py FAMILY; duplicated to avoid importing
# the TSDB module from the tier it instantiates).
_RAW_FAMILY = b"t"

_FLUSH_CELLS = 1 << 16

# Checkpoint-fold and catch-up latency timers (obs/registry.py): one
# observation per fold / per completed rebuild, exported via /stats
# and /metrics.
_M_FOLD = _metrics.timer("rollup.fold")
_M_CATCHUP = _metrics.timer("rollup.catchup")

# Checkpoint-fold path split (ISSUE-20 delta folds): (metric, coarse
# window) groups served from ingest-time delta accumulators vs groups
# that took the full raw rescan.
_M_FOLD_DELTA = _metrics.counter("rollup.fold.delta")
_M_FOLD_FULL = _metrics.counter("rollup.fold.full")


class _TierClosed(Exception):
    """Internal: the catch-up rebuild was aborted by close()."""


def _u32(v: int) -> bytes:
    return int(v).to_bytes(4, "big")


def _metric_stop(metric_uid: bytes) -> bytes:
    """Smallest key after every row of this metric."""
    n = int.from_bytes(metric_uid, "big") + 1
    if n >= 1 << (8 * len(metric_uid)):
        return b"\xff" * (len(metric_uid) + TIMESTAMP_BYTES + 1)
    return n.to_bytes(len(metric_uid), "big")


def res_label(res: int) -> str:
    if res % 86400 == 0:
        return f"{res // 86400}d"
    if res % 3600 == 0:
        return f"{res // 3600}h"
    return f"{res}s"


class _MapBuffer:
    """Accumulates per-superrow window maps and flushes them as ONE
    map cell per (row, kind) via read-modify-write put_many batches.

    The RMW (merge with the stored map, new windows replacing same-idx
    entries) is safe because every writer — checkpoint folds and the
    catch-up rebuild — serializes on the tier's fold lock; a fold that
    touches a superrow across two of its own flushes reads its first
    flush back from the store's memtable."""

    def __init__(self, tier: "RollupTier",
                 track_emitted: bool = False) -> None:
        self.tier = tier
        # (res, shard) -> {row key -> (moment entries, sketch entries)}
        self.maps: dict[tuple[int, int], dict] = {}
        self.total = 0
        self.written = 0
        # Which window slots this buffer emitted a REAL record for,
        # surviving flushes (maps are cleared at _FLUSH_CELLS, so the
        # in-buffer state can't answer "did this fold cover that
        # window?"): (res, superrow key) -> bitmask of emitted window
        # idxs — a few bytes per superrow where a per-slot tuple set
        # cost ~64 bytes per RECORD (hundreds of MB on big folds).
        # Only folds track it — it gates _zero_leftovers, which the
        # full rebuild never runs.
        self.emitted: dict[tuple[int, bytes], int] | None = (
            {} if track_emitted else None)

    def entries(self, res: int, key: bytes) -> tuple[dict, dict]:
        si = self.tier._shard_of(key)
        rows = self.maps.get((res, si))
        if rows is None:
            rows = self.maps[(res, si)] = {}
        ent = rows.get(key)
        if ent is None:
            ent = rows[key] = ({}, {})
        return ent

    def count(self, n: int) -> None:
        self.total += n
        if self.total >= _FLUSH_CELLS:
            self.flush()

    def flush(self) -> None:
        table, fam = self.tier.table, ROLLUP_FAMILY
        for (res, si), rows in self.maps.items():
            store = self.tier.stores[res][si]
            cells = []
            for key, (moments, sketches) in rows.items():
                cur_m = cur_s = None
                # RMW only for PARTIAL maps (a map covering every
                # window of the superrow replaces outright), decided
                # per kind — moments can be complete while sketches
                # aren't.
                need_m = moments and len(moments) < self.tier.pack
                need_s = sketches and len(sketches) < self.tier.pack
                if need_m or need_s:
                    for c in store.get(table, key, fam):
                        if c.qualifier == QUAL_MOMENTS and need_m:
                            cur_m = c.value
                        elif c.qualifier == QUAL_SKETCH and need_s:
                            cur_s = c.value
                if moments:
                    blob = (summary.merge_moment_map(cur_m, moments)
                            if cur_m else
                            summary.pack_moment_map(moments))
                    cells.append((key, QUAL_MOMENTS, blob))
                    self.written += len(moments)
                if sketches:
                    blob = (summary.merge_sketch_map(cur_s, sketches)
                            if cur_s else
                            summary.pack_sketch_map(sketches))
                    cells.append((key, QUAL_SKETCH, blob))
            if cells:
                store.put_many(table, fam, cells)
        # Partial fold state: some (res, shard) flushes durable, the
        # rest still buffered. A crash here leaves summary rows the
        # pending bracket owes a rebuild for — the exact
        # half-materialized shape the PR-2 review bugs lived in.
        _fault("rollup.fold.flush")
        self.maps = {}
        self.total = 0


class RollupTier:
    def __init__(self, tsdb, config) -> None:
        self._init_layout(tsdb, config)
        if bool(getattr(config, "rollup_delta_fold", True)):
            from opentsdb_tpu.rollup.delta import DeltaFolds
            self.delta = DeltaFolds(
                coarse=self.resolutions[-1],
                cap_points=int(getattr(config, "rollup_delta_points",
                                       1 << 22)))
        store = tsdb.store
        st = self._read_state()
        rebuild = self._needs_rebuild(st)
        if rebuild == "full":
            # A FULL rebuild starts from empty stores; the incremental
            # path keeps them — its windows' records are replaced from
            # raw and everything else is still valid.
            for dirs in self._dirs.values():
                for d in dirs:
                    shutil.rmtree(d, ignore_errors=True)
        try:
            for r in self.resolutions:
                self.stores[r] = []
                for d in self._dirs[r]:
                    s = MemKVStore(wal_path=os.path.join(d, "wal"))
                    # Tier spills ride the same codec knob as the raw
                    # store: under "tsst4" the summary superrows land
                    # in self-describing ROLLSUM blocks (columnar
                    # entry bytes — the block-direct read fast path in
                    # scan_records serves off them without inflating
                    # whole rows).
                    s.sstable_codec = getattr(config, "sstable_codec",
                                              "none")
                    s.ensure_table(self.table)
                    self.stores[r].append(s)
        except BaseException:
            self.close()
            raise
        store.record_spill_keys = True
        if self.delta is not None and hasattr(store, "delete_hook"):
            store.delete_hook = self._delta_delete_hook
        if rebuild != "none":
            windows = (self._incr_windows if rebuild == "incr"
                       else None)
            self._behind = True
            self._full_owed = windows is None
            # Keep the inflight set durable through an INCREMENTAL
            # catch-up: a crash mid-catch-up must redo the same
            # (idempotent) incremental work. A full rebuild persists
            # a bare pending record — no list, no shortcut.
            self._write_state(pending=True, inflight=windows)
            if windows is not None:
                self._inflight = frozenset(windows)
            mode = getattr(config, "rollup_catchup", "background")
            if mode == "sync":
                self._rebuilding = True
                self._rebuild(windows=windows)
            elif mode == "background":
                self._rebuilding = True
                self._rebuild_thread = threading.Thread(
                    target=self._rebuild, daemon=True,
                    name="rollup-catchup",
                    kwargs={"windows": windows})
                self._rebuild_thread.start()
            # "off": stays pending/not-ready; planner serves raw.
        else:
            self._write_state(pending=False)
            self._ready = True

    # Writer tier unless ReadOnlyRollupTier overrides it: consumers
    # (TSDB.refresh_replica, stats) branch on this, not on class.
    read_only = False

    def _init_layout(self, tsdb, config) -> None:
        """Everything shared between the writer tier and the read-only
        replica tier: config validation, per-shard directory layout,
        state-file path, counters, and the planner-facing flags.
        Leaves ``self.stores`` EMPTY — each subclass opens them with
        its own store mode (writable vs read-only replica)."""
        self.tsdb = tsdb
        self.table = config.table
        res = tuple(sorted(int(r) for r in config.rollup_resolutions))
        if not res:
            raise ValueError("rollup_resolutions must not be empty")
        for i, r in enumerate(res):
            if r % MAX_TIMESPAN != 0:
                raise ValueError(
                    f"rollup resolution {r} is not a multiple of the "
                    f"row span ({MAX_TIMESPAN}s)")
            if i and res[i] % res[i - 1] != 0:
                raise ValueError(
                    f"rollup resolutions must nest (each divides the "
                    f"next): {res}")
        self.resolutions = res
        self.pack = int(config.rollup_pack)
        if not 1 <= self.pack <= 0xFFFF:
            raise ValueError(f"rollup_pack out of range: {self.pack}")
        self.digest_k = int(config.rollup_digest_k)
        self.hll_p = int(config.rollup_hll_p)
        self.sketch_min_res = int(config.rollup_sketch_min_res)
        self.moment_k = int(getattr(config, "rollup_moment_k", 0))
        self.moment_min_res = int(getattr(config,
                                          "rollup_moment_min_res", 0))
        self.sketch_byte_budget = int(getattr(config,
                                              "sketch_byte_budget", 0))

        # Checkpoint fold backend. Default is the host NumPy f64
        # pairwise fold (bit-exact across chunkings); Config.
        # rollup_device_fold moves the scatter fold on-device — f64
        # accumulation where the backend keeps it, else an EXPLICITLY
        # relaxed f32 contract. The applied kind is declared in the
        # state file: records folded under different kinds mix
        # accumulation orders inside the same stored rows, so a kind
        # change rebuilds like any layout change (but a legacy state
        # file with no "fold" key means host-f64 — see _needs_rebuild).
        if bool(getattr(config, "rollup_device_fold", False)):
            self.fold_kind = summary.device_fold_kind()
            self._fold_fn = summary.window_summaries_device
        else:
            self.fold_kind = "host-f64"
            self._fold_fn = summary.window_summaries

        store = tsdb.store
        self._sharded = hasattr(store, "shards") and hasattr(store, "_route")
        base_dirs: list[str]
        if self._sharded:
            root = store._dir
            base_dirs = [os.path.join(root, f"shard-{i}")
                         for i in range(store.shard_count)]
            self.state_path = os.path.join(root, STATE_NAME)
        else:
            wal = store._wal_path
            base_dirs = [wal]  # suffixed below, not a directory itself
            self.state_path = wal + ".rollup.json"
        self.shard_count = len(base_dirs)

        # Counters (exported via collect_stats; best-effort, unlocked).
        self.hits: dict[int, int] = {r: 0 for r in res}
        self.misses = 0
        self.fallbacks: dict[str, int] = {}
        self.folds = 0
        self.records_written = 0
        self.rebuilds = 0
        # Fold-path split counters and the delta accumulators
        # themselves; the writer tier attaches DeltaFolds in its
        # __init__ (the read-only replica never folds).
        self.fold_delta = 0
        self.fold_full = 0
        self.delta = None

        self._ready = False
        # True while a full catch-up is owed (crash/foreign state):
        # per-checkpoint folds must not flip the tier ready — only a
        # completed rebuild covers the pre-existing spilled history.
        self._behind = False
        # True while the owed catch-up must be the FULL rebuild
        # (foreign layout, never-built tier, crash mid-full-rebuild).
        # While set, the persisted state must NOT carry an "inflight"
        # list: an incremental catch-up over a half-built tier would
        # silently serve the never-folded remainder stale.
        self._full_owed = False
        self._rebuilding = False
        self._rebuild_error: BaseException | None = None
        self._rebuild_thread: threading.Thread | None = None
        # close() sets this and joins the catch-up thread: letting the
        # thread race the closing stores would discard the whole
        # rebuild into _rebuild_error (hours of work at scale) and
        # possibly trip mid-write fd races inside MemKVStore.close.
        self._stop = threading.Event()
        self._fold_lock = threading.Lock()
        self._defer_lock = threading.Lock()
        self._deferred: list[bytes] = []
        self._inflight: frozenset[int] = frozenset()
        # Debug oracle (Config.rollup_sweep_check): derive the dirty
        # set BOTH ways and fail loudly on divergence. Only meaningful
        # at quiescent instants — the two derivations are separate
        # lock acquisitions, so concurrent ingest between them is a
        # benign difference, and tests quiesce before comparing.
        self.sweep_check = bool(getattr(config, "rollup_sweep_check",
                                        False))

        self._dirs: dict[int, list[str]] = {}
        for r in res:
            if self._sharded:
                self._dirs[r] = [os.path.join(d, f"rollup-{r}")
                                 for d in base_dirs]
            else:
                self._dirs[r] = [f"{base_dirs[0]}.rollup-{r}"]
        self.stores: dict[int, list[MemKVStore]] = {}

        # Per-resolution sketch-column allocation: {res: (digest_k,
        # moment_k, hll_p)}. With Config.sketch_byte_budget set, a
        # Storyboard-style optimizer (sketch/budget.py) spends the
        # budget across resolutions; otherwise the legacy uniform
        # cutoffs apply (digest at res >= sketch_min_res, moment at
        # res >= moment_min_res). Participates in the state file, so
        # a layout change rebuilds and replicas adopt the writer's.
        self.sketch_alloc = self._compute_alloc()
        # Cumulative sketch-column bytes written per (resolution,
        # kind) — process lifetime; /stats `sketch.bytes{kind=}` sums
        # across resolutions, the bench reads the per-res split (the
        # moment-vs-digest size story differs by window density).
        self.sketch_bytes_res: dict[int, dict[str, int]] = {}

    @property
    def sketch_bytes(self) -> dict[str, int]:
        out = {"tdigest": 0, "moment": 0, "hll": 0}
        for kinds in self.sketch_bytes_res.values():
            for k, v in kinds.items():
                out[k] = out.get(k, 0) + v
        return out

    def _compute_alloc(self) -> dict[int, tuple[int, int, int]]:
        if self.sketch_byte_budget > 0:
            from opentsdb_tpu.sketch import budget as _budget
            rows = self._estimate_row_hours()
            records = {r: max(rows // max(r // MAX_TIMESPAN, 1), 1)
                       for r in self.resolutions}
            allocs = _budget.allocate(self.sketch_byte_budget, records,
                                      hll_p=self.hll_p)
            return {r: (a.digest_k, a.moment_k,
                        a.hll_p if a.digest_k else 0)
                    for r, a in allocs.items()}
        out = {}
        for r in self.resolutions:
            dk = self.digest_k if r >= self.sketch_min_res else 0
            mk = self.moment_k if r >= self.moment_min_res else 0
            # HLL registers ride the digest rungs only: a moment-only
            # resolution keeps its ~200 B cells (the kind's whole
            # point); /distinct falls back to presence/exact there.
            out[r] = (dk, mk, self.hll_p if dk else 0)
        return out

    def _estimate_row_hours(self) -> int:
        """Rough raw row-hour count (the budget allocator's record-
        density input): memtable pending keys + sstable index sizes.
        The allocator quantizes, so order of magnitude is enough."""
        store = self.tsdb.store
        n = 0
        try:
            n += len(list(store.pending_keys(self.table)))
        except Exception:
            pass
        shards = getattr(store, "shards", None)
        if isinstance(shards, list):
            subs = shards
        else:
            subs = [store]
        for s in subs:
            for sst in getattr(s, "_ssts", []) or []:
                try:
                    n += sst.key_count(self.table)
                except Exception:
                    pass
        return max(n, 1)

    # -- state file --------------------------------------------------------

    STATE_VERSION = 3

    def _config_dict(self) -> dict:
        return {"version": self.STATE_VERSION,
                "resolutions": list(self.resolutions),
                "pack": self.pack, "digest_k": self.digest_k,
                "hll_p": self.hll_p,
                "sketch_min_res": self.sketch_min_res,
                "moment_k": self.moment_k,
                "moment_min_res": self.moment_min_res,
                "budget": self.sketch_byte_budget,
                # The APPLIED per-res allocation, not just the knobs:
                # a budget re-plan (operator re-budgeted) changes the
                # stored columns and must rebuild like any layout
                # change. Same-budget reopens ADOPT the persisted
                # allocation (_needs_rebuild) so record-count drift
                # around a quantization edge can't flap the layout.
                "alloc": {str(r): list(self.sketch_alloc[r])
                          for r in self.resolutions},
                # Declared numeric contract of the records: which fold
                # backend accumulated them. Compared with a host-f64
                # default so pre-existing state files (no key) stay
                # adopted — see _needs_rebuild / _adopt_state.
                "fold": self.fold_kind}

    @classmethod
    def adopt_config(cls, state_path: str, config) -> bool:
        """Copy an existing tier's layout (ROLLUP.json, the inverse of
        _config_dict) onto ``config`` — the CLI's tier auto-adopt, kept
        HERE so the state-file schema has one owner. Returns False
        (config untouched) for an unreadable, foreign-version, or
        malformed file; the tier then opens on Config defaults and the
        config-mismatch check schedules a rebuild."""
        try:
            with open(state_path) as f:
                rec = json.load(f)
            if rec.get("version") != cls.STATE_VERSION:
                return False
            resolutions = tuple(int(r) for r in rec["resolutions"])
            pack = int(rec["pack"])
            digest_k = int(rec["digest_k"])
            hll_p = int(rec["hll_p"])
            sketch_min_res = int(rec["sketch_min_res"])
            moment_k = int(rec["moment_k"])
            moment_min_res = int(rec["moment_min_res"])
        except (OSError, ValueError, TypeError, KeyError):
            return False
        config.rollup_resolutions = resolutions
        config.rollup_pack = pack
        config.rollup_digest_k = digest_k
        config.rollup_hll_p = hll_p
        config.rollup_sketch_min_res = sketch_min_res
        config.rollup_moment_k = moment_k
        config.rollup_moment_min_res = moment_min_res
        return True

    def _read_state(self) -> dict | None:
        try:
            with open(self.state_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_state(self, pending: bool,
                     inflight: "frozenset[int] | list | None" = None,
                     ) -> None:
        """``inflight``: the hour bases whose spilled rows may be
        drained-but-unfolded — persisted alongside ``pending`` so a
        crash can catch up INCREMENTALLY (refold only these windows)
        instead of rebuilding the whole tier. Invariant maintained by
        begin_spill/fold_after_spill: at any instant the persisted set
        is a superset of every window whose raw rows left
        pending_keys without a durable fold."""
        rec = self._config_dict()
        rec["pending"] = pending
        if pending and inflight is not None:
            rec["inflight"] = sorted(int(b) for b in inflight)
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path)

    def _needs_rebuild(self, st: dict | None) -> str:
        """"none" (tier is complete), "full" (wipe + rebuild), or
        "incr" (pending crash with a usable persisted inflight set —
        refold only those windows; self._incr_windows is set)."""
        self._incr_windows: list[int] | None = None
        if st is None:
            # No state: a store that already spilled data has raw
            # history no fold will ever cover; a fresh store starts
            # complete (its whole history is memtable-dirty).
            return ("full" if getattr(self.tsdb.store, "spilled",
                                      False) else "none")
        cfg = self._config_dict()
        # Same-budget reopen: adopt the persisted allocation before
        # comparing, so a record-count estimate that drifted across a
        # quantization edge can't force a rebuild the operator never
        # asked for (the budget knob itself still does).
        alloc = st.get("alloc")
        if (self.sketch_byte_budget > 0 and isinstance(alloc, dict)
                and st.get("budget") == self.sketch_byte_budget):
            try:
                adopted = {int(r): tuple(int(x) for x in v)
                           for r, v in alloc.items()}
            except (TypeError, ValueError):
                adopted = None
            if adopted is not None and set(adopted) == set(
                    self.resolutions):
                self.sketch_alloc = adopted
                cfg = self._config_dict()
        # "fold" compares against a host-f64 default: legacy state
        # files predate the key and their records ARE host-f64 folds.
        config_ok = (all(st.get(k) == v for k, v in cfg.items()
                         if k not in ("pending", "fold"))
                     and st.get("fold", "host-f64") == self.fold_kind)
        if st.get("pending", True):
            wins = st.get("inflight")
            if (config_ok and isinstance(wins, list)
                    and getattr(self.tsdb.config,
                                "rollup_incremental_catchup", True)):
                self._incr_windows = [int(b) for b in wins]
                return "incr"
            return "full"
        return "none" if config_ok else "full"

    # -- planner surface ---------------------------------------------------

    @property
    def ready(self) -> bool:
        return self._ready

    def wait_ready(self, timeout: float | None = None) -> bool:
        t = self._rebuild_thread
        if t is not None:
            t.join(timeout)
        if self._rebuild_error is not None:
            raise RuntimeError("rollup catch-up failed") \
                from self._rebuild_error
        return self._ready

    def pick_resolution(self, interval: int) -> int | None:
        """Coarsest resolution whose windows nest exactly into the
        downsample buckets."""
        best = None
        for r in self.resolutions:
            if r <= interval and interval % r == 0:
                best = r
        return best

    def sketch_candidates(self, span: int,
                          want_hll: bool = False) -> list[int]:
        """Sketch-bearing resolutions not wider than the range,
        COARSEST FIRST — the planner's candidate order for the ranged
        sketch endpoints (a range wide enough for a resolution may
        still hold no aligned full window of it, so selection falls
        through to the next). ``want_hll`` keeps only resolutions
        whose allocation carries HLL registers (distinct-VALUES
        estimates; moment-only rungs have none and must not serve
        them)."""
        out = []
        for r in reversed(self.resolutions):
            dk, mk, hp = self.sketch_alloc.get(r, (0, 0, 0))
            if r > span or not (dk or mk):
                continue
            if want_hll and not hp:
                continue
            out.append(r)
        return out

    def sketch_res_for_interval(self, interval: int) -> int | None:
        """Coarsest sketch-bearing resolution whose windows nest
        exactly into ``interval`` buckets — the approximate
        percentile-downsample planner's resolution pick (per-bucket
        sketches merge from whole windows only)."""
        best = None
        for r in self.resolutions:
            dk, mk, _ = self.sketch_alloc.get(r, (0, 0, 0))
            if (dk or mk) and r <= interval and interval % r == 0:
                best = r
        return best

    def note_hit(self, res: int) -> None:
        self.hits[res] = self.hits.get(res, 0) + 1

    def note_fallback(self, reason: str) -> None:
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    def note_miss(self) -> None:
        self.misses += 1

    def dirty_hour_bases(self) -> np.ndarray:
        """Sorted hour bases whose raw rows are not (yet) covered by
        rollup records: memtable + frozen rows + the undrained spill
        record, plus windows in flight between a spill and its fold
        commit. Served from the store's incrementally-maintained
        dirty-base index (MemKVStore.dirty_bases, O(1) amortized per
        mutation) — the old implementation re-swept the ENTIRE
        memtable key list under the store lock on every
        rollup-eligible query, so planning cost scaled with memtable
        size under live ingest (the ROADMAP follow-on this closes).
        ``rollup_sweep_check`` keeps the sweep as a cross-check
        oracle."""
        store = self.tsdb.store
        db = getattr(store, "dirty_bases", None)
        if db is None:
            base = self._sweep_dirty_bases()
        else:
            base = db(self.table)
            if self.sweep_check:
                swept = self._sweep_dirty_bases()
                if not np.array_equal(base, swept):
                    raise AssertionError(
                        f"incremental dirty set diverged from the "
                        f"sweep oracle: "
                        f"incremental={base.tolist()} "
                        f"swept={swept.tolist()}")
        infl = self._inflight
        if infl:
            base = np.union1d(
                base, np.fromiter(infl, np.int64, len(infl)))
        return base

    def _sweep_dirty_bases(self) -> np.ndarray:
        """The legacy O(memtable) derivation: sweep every pending key
        and collect base times. Kept as the sweep_check oracle (and
        the fallback for stores without the incremental index).
        Malformed/short keys (a stray delete_row from a tool) carry no
        base time to mark dirty — skip them like the fold paths do."""
        lo, hi = UID_WIDTH, UID_WIDTH + TIMESTAMP_BYTES
        keys = [k for k in self.tsdb.store.pending_keys(self.table)
                if len(k) >= hi]
        if not keys:
            return np.empty(0, np.int64)
        blob = b"".join(k[lo:hi] for k in keys)
        return np.unique(np.frombuffer(blob, ">u4").astype(np.int64))

    def scan_records(self, res: int, metric_uid: bytes, w_lo: int,
                     w_hi: int, key_regexp: bytes | None = None,
                     want_sketches: bool = False) -> dict:
        """All rollup records of ``metric`` with window base in
        [w_lo, w_hi], keyed by series. Returns
        ``{series_key: (bases int64[W], records REC_DTYPE[W],
        sketches [(base, blob)])}`` with zero-count (deleted) records
        dropped. Shards are scanned independently — a series' rows all
        live in one shard, so per-series ordering needs no merge."""
        span = res * self.pack
        start_key = metric_uid + _u32(w_lo - w_lo % span)
        stop_hi = w_hi - w_hi % span + span
        stop_key = (_metric_stop(metric_uid) if stop_hi > 0xFFFFFFFF
                    else metric_uid + _u32(stop_hi))
        # One map cell per (row, kind): a whole superrow of window
        # records decodes with a single frombuffer — the per-window
        # cell layout this replaced made reads sstable-unpack-bound.
        acc: dict[bytes, tuple[list, list, list]] = {}
        for s in self.stores[res]:
            rows = self._block_rows(s, start_key, stop_key, key_regexp)
            if rows is None:
                rows = s.scan_raw(self.table, start_key, stop_key,
                                  family=ROLLUP_FAMILY,
                                  key_regexp=key_regexp)
            for key, items in rows:
                sb = codec.key_base_time(key)
                skey = codec.series_key(key)
                ent = acc.get(skey)
                if ent is None:
                    ent = acc[skey] = ([], [], [])
                for q, v in items:
                    if q == QUAL_MOMENTS:
                        if len(v) % summary.ENTRY_SIZE:
                            continue  # foreign/corrupt: skip
                        e = summary.decode_moment_map(v)
                        wb = sb + e["idx"].astype(np.int64) * res
                        keep = (wb >= w_lo) & (wb <= w_hi)
                        if keep.any():
                            ent[0].append(wb[keep])
                            ent[1].append(e["rec"][keep])
                    elif q == QUAL_SKETCH and want_sketches:
                        for idx, blob in summary.decode_sketch_map(v):
                            wb1 = sb + idx * res
                            if w_lo <= wb1 <= w_hi:
                                ent[2].append((wb1, blob))
        out: dict[bytes, tuple] = {}
        for skey, (bases, recs, sk) in acc.items():
            if not bases and not sk:
                continue
            if bases:
                base_arr = np.concatenate(bases)
                rec = (np.concatenate(recs) if len(recs) > 1
                       else np.asarray(recs[0]))
                live = rec["count"] > 0
                if not live.all():
                    base_arr, rec = base_arr[live], rec[live]
            else:
                base_arr = np.empty(0, np.int64)
                rec = np.empty(0, REC_DTYPE)
            if len(base_arr) or sk:
                out[skey] = (base_arr, rec, sk)
        return out

    def _block_rows(self, s, start_key: bytes, stop_key: bytes,
                    key_regexp: bytes | None):
        """Block-direct read of one tier store's ROLLSUM blocks:
        [(key, [(qual, cell_bytes)])] sorted by key, or None when the
        store must fall back to scan_raw (memtable-resident rows in
        range, a non-ROLLSUM covering block, or duplicate keys across
        generations needing newest-wins overlay).

        Serving is byte-for-byte identical to the row scan: the cell
        bytes come straight off the block's columnar entry matrix —
        the very bytes the row framing would carry — so the moment/
        sketch decode downstream sees the same input. What this skips
        is the whole-row zlib inflate + v3 re-framing + per-row cell
        parse of the generic path (one transposed inflate per block,
        parsed once and cached on the immutable sstable object)."""
        er = getattr(s, "encoded_range", None)
        if er is None:
            return None
        try:
            # Memtable/frozen rows in range overlay the blocks —
            # that's scan_raw's job.
            for k in s.pending_keys(self.table):
                if start_key <= k < stop_key:
                    return None
            spans = er(self.table, start_key, stop_key)
        except Exception:
            return None
        if spans is None:
            return None
        if not spans:
            return []
        if len(spans) > 1:
            allk = [k for sst, lo, hi in spans
                    for k in sst._index[self.table][0][lo:hi]]
            if len(set(allk)) != len(allk):
                return None   # re-folded superrow: newest-wins overlay
        pattern = re.compile(key_regexp, re.S) if key_regexp else None
        out = []
        for sst, lo, hi in spans:
            keys, offs = sst._index[self.table]
            blk_ids = np.unique(
                np.searchsorted(sst._blk_raw,
                                np.asarray(offs[lo:hi], np.int64),
                                "right") - 1)
            for j in blk_ids.tolist():
                rb = self._rollsum_block(sst, j)
                if rb is None or rb.fam != ROLLUP_FAMILY[0] \
                        or rb.table != self.table.encode():
                    return None
                for i in range(rb.n):
                    key = rb.K[i, :rb.klen[i]].tobytes()
                    if not start_key <= key < stop_key:
                        continue
                    if pattern is not None and not pattern.match(key):
                        continue
                    fe = int(rb.first_ent[i])
                    items = [(QUAL_MOMENTS,
                              rb.ent_bytes[fe:fe + rb.nm[i]].tobytes())]
                    if rb.has_sketch[i]:
                        o = int(rb.sk_off[i])
                        items.append(
                            (QUAL_SKETCH,
                             rb.sk_blob[o:o + int(rb.sk_len[i])]))
                    out.append((key, items))
        # Generations may interleave key ranges; the row scan yields a
        # global key-ordered merge, so match it (keys are unique here).
        out.sort(key=lambda kv: kv[0])
        return out

    @staticmethod
    def _rollsum_block(sst, j: int):
        """Parsed ROLLSUM block ``j``, cached on the sstable; None for
        any other tag (caller falls back). The parse holds no views of
        the file mmap (all arrays are freshly inflated), so caching
        cannot pin a closed map."""
        from opentsdb_tpu.compress import codecs as _codecs
        cache = sst.__dict__.setdefault("_rollsum_cache", {})
        if j in cache:
            return cache[j]
        rb = None
        try:
            tag, _raw_len, _enc_len = sst.block_header(j)
            if tag == _codecs.ROLLSUM:
                rb = _codecs.parse_rollsum_block(sst.block_enc(j))
        except Exception:
            rb = None
        cache[j] = rb
        return rb

    # -- checkpoint integration (called by TSDB.checkpoint) ---------------

    def begin_spill(self) -> None:
        """Before the raw spill: remember every currently-dirty window
        as in-flight (the spill moves its rows out of pending_keys, the
        fold hasn't covered them yet) and mark the tier pending on
        disk — WITH the in-flight window list, so a crash catches up
        incrementally (refold just those windows) instead of
        rebuilding the whole tier."""
        bases = self.dirty_hour_bases()
        self._inflight = self._inflight | frozenset(
            int(b) for b in bases)
        if self._full_owed:
            return  # state is already pending (bare: full owed)
        # During an incremental catch-up the state is already pending,
        # but the inflight list must still grow: a checkpoint's
        # spilled keys get deferred to the catch-up thread, and a
        # crash before that fold lands must know these windows are
        # owed too.
        self._write_state(pending=True, inflight=self._inflight)
        if self._rebuilding or self._behind:
            return
        # Bracket opened (pending durable), raw spill not started:
        # crash must catch up at next open even though no data moved.
        _fault("rollup.begin_spill", self.state_path)

    def fold_after_spill(self) -> None:
        """After the raw spill: fold the spilled keys into summary
        records, commit, and clear the in-flight set. During a rebuild
        the keys are deferred — the catch-up pass drains them."""
        store = self.tsdb.store
        # Rows ingested between begin_spill's dirty snapshot and the
        # store's memtable freeze were spilled WITHOUT being in the
        # pre-spill in-flight set. Mark their windows in flight from a
        # non-draining PEEK, while their keys still read as pending
        # (pending_keys includes the undrained spill record), so no
        # instant exists where a spilled-but-unfolded window is in
        # neither set; only then drain.
        peek = getattr(store, "peek_spill_keys", None)
        if peek is not None:
            extra = frozenset(
                int(codec.key_base_time(k))
                for k in peek().get(self.table, ())
                if len(k) >= UID_WIDTH + TIMESTAMP_BYTES)
            if not extra <= self._inflight:
                self._inflight = self._inflight | extra
                # Persist BEFORE draining: once take_spill_keys runs,
                # these keys exist only in this process's memory — a
                # crash must find their windows in the durable
                # inflight set or the incremental catch-up would
                # silently skip them (stale summaries). While a full
                # rebuild is owed the bare pending record stands.
                if not self._full_owed:
                    self._write_state(pending=True,
                                      inflight=self._inflight)
        keys = store.take_spill_keys().get(self.table, [])
        with self._defer_lock:
            if self._rebuilding:
                self._deferred.extend(keys)
                return
            if self._behind:
                # Full catch-up owed but not running (rollup_catchup
                # "off" / crashed): its eventual full scan covers these
                # keys; folding now could flip state to ok early.
                return
        try:
            # Spill record drained, fold not yet run: the spilled keys
            # exist ONLY in this process's memory — crash loses them
            # and the pending bracket must force a full rebuild (the
            # PR-2-era torn-bracket class).
            _fault("rollup.fold.start", self.state_path)
            with _M_FOLD.time():
                self._fold(keys)
        except IllegalDataError as e:
            # Corrupt raw data (the fsck signal): leave the tier
            # not-ready (state stays pending) so the planner serves
            # raw; never wedge the checkpoint itself. The drained keys
            # are lost, so mark a full rebuild owed (_behind): without
            # it the NEXT clean fold would clear _inflight, write
            # pending=false, and flip ready while THESE windows were
            # never folded — stale summaries served, and pending=false
            # on disk means a restart would skip the rebuild too. The
            # rebuild runs at the next open (state is still pending);
            # it aborts on the same corrupt rows until fsck --fix, and
            # queries serve raw throughout.
            LOG.warning("rollup fold skipped (corrupt data): %s", e)
            with self._defer_lock:
                self._behind = True
            self._ready = False
            self.note_fallback("corrupt")
            return
        # Fold durable in the rollup WALs, bracket still pending:
        # crash re-folds idempotently after the rebuild.
        _fault("rollup.fold.commit", self.state_path)
        for stores in self.stores.values():
            for s in stores:
                s.checkpoint()   # bound the rollup WALs
        self._write_state(pending=False)
        self._inflight = frozenset()
        self._ready = True
        # Bracket flipped ok: a crash from here on must NOT rebuild —
        # the tier is complete and the next open serves it as-is.
        _fault("rollup.bracket.flip", self.state_path)
        self.folds += 1

    # -- fold core ---------------------------------------------------------

    def _shard_of(self, key: bytes) -> int:
        if self._sharded:
            return self.tsdb.store._route(self.table, key)
        return 0

    def _fold(self, keys: list[bytes]) -> None:
        """Recompute every rollup record whose window holds one of the
        spilled ``keys`` (replace-from-raw; module docstring). Keys
        whose rows vanished (row tombstones / deletes) get zero
        records so stale summaries cannot outlive their points."""
        if not keys:
            return
        with self._fold_lock:
            coarse = self.resolutions[-1]
            groups: dict[tuple[bytes, int], list[bytes]] = {}
            must: set[bytes] = set()
            for k in keys:
                if len(k) < UID_WIDTH + TIMESTAMP_BYTES:
                    continue
                kb = bytes(k)
                must.add(kb)
                hb = codec.key_base_time(k)
                groups.setdefault(
                    (kb[:UID_WIDTH], hb - hb % coarse), []).append(kb)
            buf = _MapBuffer(self, track_emitted=True)
            seen: set[bytes] = set()
            # Delta fast path (rollup/delta.py): a (metric, coarse
            # window) group whose every spilled series-window is
            # completely buffered emits straight from memory; the rest
            # take the replace-from-raw rescan below. Both paths write
            # through the same buffer under this lock, so the final
            # record bytes are independent of the split.
            per_metric: dict[bytes, set[int]] = {}
            for (muid, cb), ks in groups.items():
                if self.delta is not None and self.delta.serve(
                        self, cb, ks, buf, seen):
                    self.fold_delta += 1
                    _M_FOLD_DELTA.inc()
                    continue
                per_metric.setdefault(muid, set()).add(cb)
                self.fold_full += 1
                _M_FOLD_FULL.inc()
            # Bound one scan chunk to ~4 days of coarse windows.
            chunk = max(1, (4 * 86400) // coarse)
            for metric_uid, cbases in per_metric.items():
                bases = sorted(cbases)
                i = 0
                while i < len(bases):
                    j = i
                    while (j + 1 < len(bases) and j - i + 1 < chunk
                           and bases[j + 1] == bases[j] + coarse):
                        j += 1
                    self._rollup_span(metric_uid, bases[i],
                                      bases[j] + coarse, buf, seen)
                    i = j + 1
            self._zero_leftovers(must - seen, buf)
            buf.flush()
            self.records_written += buf.written

    def _zero_leftovers(self, leftovers: Iterable[bytes],
                        buf: _MapBuffer) -> None:
        """Write count-0 records for spilled rows that no longer hold
        points (deleted): the planner skips them, replacing whatever
        stale summary the window had. Only slots the fold's rescan
        emitted NOTHING for are zeroed — a coarse window (say 1d) of a
        deleted hourly row usually still holds the series' surviving
        hours, and its record was just recomputed from them; zeroing it
        too would drop the whole day from rollup serving while raw
        scans keep returning the survivors ("stale degrades, never
        lies")."""
        zero = np.zeros(1, REC_DTYPE).tobytes()
        empty_sketch = summary.sketch_encode(
            np.empty(0, np.float32), np.empty(0, np.float32), None)
        emitted = buf.emitted
        assert emitted is not None, "_zero_leftovers needs a tracking buffer"
        for k in leftovers:
            skey = codec.series_key(k)
            hb = codec.key_base_time(k)
            for r in self.resolutions:
                wb = hb - hb % r
                span = r * self.pack
                sb = wb - wb % span
                key = skey[:UID_WIDTH] + _u32(sb) + skey[UID_WIDTH:]
                idx = (wb - sb) // r
                if emitted.get((r, key), 0) >> idx & 1:
                    continue
                moments, sketches = buf.entries(r, key)
                moments[idx] = zero
                if self._sketchy(r):
                    sketches[idx] = empty_sketch
                buf.count(1)

    def _sketchy(self, res: int) -> bool:
        dk, mk, _ = self.sketch_alloc.get(res, (0, 0, 0))
        return bool(dk or mk)

    def sketch_kinds(self, res: int) -> tuple[int, int, int]:
        """(digest_k, moment_k, hll_p) the tier stores at ``res``."""
        return self.sketch_alloc.get(res, (0, 0, 0))

    def _zero_unemitted(self, hours, buf: _MapBuffer) -> None:
        """Incremental catch-up's delete pass: zero every previously-
        recorded slot in the affected windows that the rescan emitted
        nothing for (its raw rows are gone — deletes whose spilled
        keys the crash lost). The full rebuild needs no analog: it
        starts from wiped stores."""
        zero = np.zeros(1, REC_DTYPE).tobytes()
        emitted = buf.emitted
        assert emitted is not None, \
            "_zero_unemitted needs a tracking buffer"
        names = self.tsdb.metrics.suggest("", limit=1 << 30)
        uids = [self.tsdb.metrics.get_id(n) for n in names]
        for r in self.resolutions:
            wins = {int(h) - int(h) % r for h in hours}
            if not wins:
                continue
            span = r * self.pack
            ranges: list[list[int]] = []
            for sb in sorted({w - w % span for w in wins}):
                if ranges and sb == ranges[-1][1]:
                    ranges[-1][1] = sb + span
                else:
                    ranges.append([sb, sb + span])
            empty_sketch = (summary.sketch_encode(
                np.empty(0, np.float32), np.empty(0, np.float32),
                None) if self._sketchy(r) else None)
            for uid in uids:
                for lo, hi in ranges:
                    start_key = uid + _u32(max(lo, 0))
                    stop_key = (_metric_stop(uid) if hi > 0xFFFFFFFF
                                else uid + _u32(hi))
                    for s in self.stores[r]:
                        for key, items in s.scan_raw(
                                self.table, start_key, stop_key,
                                family=ROLLUP_FAMILY):
                            sb = codec.key_base_time(key)
                            kb = bytes(key)
                            mask = emitted.get((r, kb), 0)
                            for q, v in items:
                                if (q != QUAL_MOMENTS
                                        or len(v) % summary.ENTRY_SIZE):
                                    continue
                                e = summary.decode_moment_map(v)
                                for idx in e["idx"].tolist():
                                    wb = sb + int(idx) * r
                                    if (wb not in wins
                                            or mask >> int(idx) & 1):
                                        continue
                                    ent = buf.entries(r, kb)
                                    ent[0][int(idx)] = zero
                                    if empty_sketch is not None:
                                        ent[1][int(idx)] = empty_sketch
                                    buf.count(1)

    def _rollup_span(self, metric_uid: bytes, lo: int, hi: int,
                     buf: _MapBuffer, seen: set | None = None,
                     stoppable: bool = False) -> None:
        """Recompute records for every raw point of ``metric`` with row
        base in [lo, hi) — streamed one coarsest window at a time (raw
        keys are base-major within a metric, so a coarse window's rows
        are contiguous in the scan). ``stoppable`` (the rebuild path)
        aborts at coarse-window boundaries once close() set _stop;
        checkpoint folds never abort — their caller owns shutdown
        ordering and an aborted fold would drop spilled keys."""
        coarse = self.resolutions[-1]
        start_key = metric_uid + _u32(max(lo, 0))
        stop_key = (_metric_stop(metric_uid) if hi > 0xFFFFFFFF
                    else metric_uid + _u32(hi))
        rows: list[tuple[bytes, list]] = []
        cur = None
        for key, items in self.tsdb.store.scan_raw(
                self.table, start_key, stop_key, family=_RAW_FAMILY):
            cb = codec.key_base_time(key)
            cb -= cb % coarse
            if cur is not None and cb != cur and rows:
                if stoppable and self._stop.is_set():
                    raise _TierClosed()
                self._summarize_group(rows, buf, seen)
                rows = []
            cur = cb
            rows.append((key, items))
        if rows:
            self._summarize_group(rows, buf, seen)

    def _summarize_group(self, rows: list, buf: _MapBuffer,
                         seen: set | None) -> None:
        """Decode one coarse window's rows into per-series sorted
        columns (the scan_series recipe: one batched decode + one
        lexsort + vectorized dedup) and emit records at every
        resolution."""
        quals: list[bytes] = []
        vals: list[bytes] = []
        bases: list[int] = []
        cell_sid: list[int] = []
        skeys: list[bytes] = []
        skey_index: dict[bytes, int] = {}
        for key, items in rows:
            base = codec.key_base_time(key)
            skey = codec.series_key(key)
            si = skey_index.get(skey)
            if si is None:
                si = skey_index[skey] = len(skeys)
                skeys.append(skey)
            kept = 0
            for q, v in items:
                if len(q) % 2 != 0 or not q:
                    continue
                quals.append(q)
                vals.append(v)
                bases.append(base)
                cell_sid.append(si)
                kept += 1
            if kept and seen is not None:
                seen.add(bytes(key))
        if not quals:
            return
        ts, f, i, isf, cop = codec_np.decode_cells_flat(
            quals, vals, np.asarray(bases, np.int64))
        sid = np.asarray(cell_sid, np.int64)[cop]
        order = np.lexsort((ts, sid))
        ts, f, i, isf, sid = (ts[order], f[order], i[order], isf[order],
                              sid[order])
        if len(ts) > 1:
            dup = (sid[1:] == sid[:-1]) & (ts[1:] == ts[:-1])
            if dup.any():
                same = ((isf[1:] == isf[:-1])
                        & np.where(isf[1:], f[1:] == f[:-1],
                                   i[1:] == i[:-1]))
                if (dup & ~same).any():
                    bad = int(ts[1:][dup & ~same][0])
                    raise IllegalDataError(
                        f"Found out of order or duplicate data: "
                        f"ts={bad} -- run an fsck.")
                keep = np.concatenate(([True], ~dup))
                ts, f, sid = ts[keep], f[keep], sid[keep]
        bounds = np.searchsorted(sid, np.arange(len(skeys) + 1))
        for s, (a, b) in enumerate(zip(bounds[:-1], bounds[1:])):
            if b <= a:
                continue
            self._emit_series(skeys[s], ts[a:b], f[a:b], buf)

    def _emit_series(self, skey: bytes, ts: np.ndarray, vals: np.ndarray,
                     buf: _MapBuffer) -> None:
        head, tail = skey[:UID_WIDTH], skey[UID_WIDTH:]
        for r in self.resolutions:
            wb, recs = self._fold_fn(ts, vals, r)
            blob = recs.tobytes()
            span = r * self.pack
            # Window emission is the fold's per-record hot loop: hoist
            # the row key (and its shard route + map lookup) per
            # superrow run — wb is sorted, so runs are contiguous.
            sbs = wb - wb % span
            idxs = ((wb - sbs) // r).astype(np.int64)
            run_starts = np.concatenate(
                ([0], np.flatnonzero(np.diff(sbs)) + 1, [len(wb)]))
            emitted = buf.emitted
            for a, b in zip(run_starts[:-1], run_starts[1:]):
                key = head + _u32(int(sbs[a])) + tail
                moments = buf.entries(r, key)[0]
                mask = 0
                for j in range(a, b):
                    idx = int(idxs[j])
                    moments[idx] = \
                        blob[j * REC_SIZE:(j + 1) * REC_SIZE]
                    mask |= 1 << idx
                if emitted is not None:
                    ek = (r, key)
                    emitted[ek] = emitted.get(ek, 0) | mask
                buf.count(b - a)
            if self._sketchy(r):
                dk, mk, hp = self.sketch_alloc[r]
                sb_arr, blobs = summary.window_sketches(
                    ts, vals, r, dk, hp, mk,
                    kind_bytes=self.sketch_bytes_res.setdefault(
                        r, {}))
                for j, sblob in enumerate(blobs):
                    w = int(sb_arr[j])
                    sb = w - w % span
                    key = head + _u32(sb) + tail
                    buf.entries(r, key)[1][(w - sb) // r] = sblob
                    buf.count(1)

    # -- catch-up daemon ---------------------------------------------------

    def _rebuild(self, windows: "list[int] | None" = None) -> None:
        """Tier catch-up from the raw store (crash / foreign state
        recovery). Runs on the catch-up thread; checkpoints folding in
        the meantime defer their spilled keys, drained at the end.

        ``windows`` (incremental mode, ROADMAP "Rollup incremental
        catch-up"): the persisted in-flight hour bases of the crashed
        bracket — ONLY those windows refold (every other record was
        durably committed by an earlier fold and records replace from
        raw idempotently), plus a zero pass for previously-recorded
        slots in those windows the rescan no longer emits (deleted
        rows; the crash lost the spilled keys _zero_leftovers would
        have keyed on). None = the full-tier scan."""
        try:
            import time as _time
            t_catchup0 = _time.perf_counter()
            buf = _MapBuffer(self, track_emitted=windows is not None)
            with self._fold_lock:
                names = self.tsdb.metrics.suggest("", limit=1 << 30)
                coarse = self.resolutions[-1]
                spans: list[tuple[int, int]] | None = None
                if windows is not None:
                    cw = sorted({int(b) - int(b) % coarse
                                 for b in windows})
                    spans = []
                    for b in cw:
                        if spans and b == spans[-1][1]:
                            spans[-1] = (spans[-1][0], b + coarse)
                        else:
                            spans.append((b, b + coarse))
                for name in names:
                    if self._stop.is_set():
                        raise _TierClosed()
                    uid = self.tsdb.metrics.get_id(name)
                    for lo, hi in (spans if spans is not None
                                   else [(0, 1 << 33)]):
                        self._rollup_span(uid, lo, hi, buf,
                                          stoppable=True)
                if windows is not None:
                    self._zero_unemitted(windows, buf)
                buf.flush()
                self.records_written += buf.written
            # Completion commits under the TSDB's checkpoint lock: the
            # flag flip + state write must not interleave with a
            # checkpoint's begin_spill/fold_after_spill bracket, or this
            # thread's pending=false + _inflight clear would land while
            # that checkpoint's spill is uncommitted (the same torn
            # bracket TSDB._checkpoint_lock closes for checkpoint vs
            # checkpoint). Lock order everywhere: checkpoint lock, then
            # defer lock, then fold lock — _fold and the rollup-store
            # spills below run with NEITHER outer lock held, so
            # checkpoints keep draining into _deferred instead of
            # blocking behind this thread's longest work.
            # Direct attribute access on purpose: a TSDB-like owner
            # without the lock must fail loudly here, not hand the
            # commit a private lock nobody else holds (which would
            # silently disable the torn-bracket protection).
            ckpt_lock = self.tsdb._checkpoint_lock
            while True:
                if self._stop.is_set():
                    raise _TierClosed()
                with self._defer_lock:
                    keys, self._deferred = self._deferred, []
                if keys:
                    self._fold(keys)
                    continue
                # Bound the rollup WALs BEFORE taking the checkpoint
                # lock: a full-tier spill can run for minutes at scale
                # and is WAL-durable regardless — only the flag flips
                # and the state write belong inside the bracket. A fold
                # sneaking in after these spills just re-checkpoints a
                # small delta on the next pass.
                for stores in self.stores.values():
                    for s in stores:
                        s.checkpoint()
                with ckpt_lock:
                    with self._defer_lock:
                        if self._deferred:
                            continue  # a fold snuck in before the lock
                        # Both flags flip under the defer lock (and with
                        # no checkpoint mid-bracket) so a racing fold
                        # either lands in _deferred (drained here) or
                        # proceeds as a normal fold — never drops keys.
                        self._rebuilding = False
                        self._behind = False
                        self._full_owed = False
                    # Catch-up complete in memory, completion not yet
                    # durable: crash re-runs the whole rebuild at next
                    # open (idempotent, never stale).
                    _fault("rollup.catchup.commit", self.state_path)
                    self._write_state(pending=False)
                    self._inflight = frozenset()
                    self._ready = True
                    self.rebuilds += 1
                _M_CATCHUP.observe(
                    (_time.perf_counter() - t_catchup0) * 1000.0)
                break
        except BaseException as e:
            self._rebuilding = False
            if isinstance(e, _TierClosed) or self._stop.is_set():
                # Orderly close() abort (the stores may already be
                # closing under us): state stays pending and the next
                # open rebuilds — not a failure.
                LOG.info("rollup catch-up aborted by close(); the next "
                         "open rebuilds")
            else:
                self._rebuild_error = e
                LOG.exception(
                    "rollup catch-up failed; tier stays raw-only")

    # -- stats / lifecycle -------------------------------------------------

    def collect_stats(self, collector) -> None:
        collector.record("rollup.ready", int(self._ready))
        # Declared fold backend (gauge-of-1 with a kind tag): lets
        # operators confirm which numeric contract the stored records
        # carry without reading ROLLUP.json.
        collector.record("rollup.fold", 1, f"kind={self.fold_kind}")
        collector.record("rollup.folds", self.folds)
        collector.record("rollup.records", self.records_written)
        collector.record("rollup.rebuilds", self.rebuilds)
        collector.record("rollup.miss", self.misses)
        for r in self.resolutions:
            collector.record("rollup.hit", self.hits.get(r, 0),
                             f"res={res_label(r)}")
        for reason, n in sorted(self.fallbacks.items()):
            collector.record("rollup.fallback", n, f"reason={reason}")
        for kind, n in sorted(self.sketch_bytes.items()):
            collector.record("sketch.bytes", n, f"kind={kind}")

    def flush(self) -> None:
        for stores in self.stores.values():
            for s in stores:
                s.flush()

    def _delta_delete_hook(self, table: str, key: bytes) -> None:
        """Store delete hook: any raw-table delete (operator tools,
        query-path cleanups, sabotage workloads) drops the row's
        window from the delta accumulators. Compaction's preserving
        rewrites are excluded by the accumulator's thread-local
        preserve window (TSDB.compact_row)."""
        if table == self.table and self.delta is not None:
            self.delta.invalidate_key(key)

    def close(self) -> None:
        # Unhook from the raw store first: the store outlives tier
        # swaps (refresh_replica), and a stale hook would pin this
        # tier's accumulators alive.
        try:
            store = self.tsdb.store
            if getattr(store, "delete_hook", None) == \
                    self._delta_delete_hook:
                store.delete_hook = None
        except Exception:   # pragma: no cover - teardown best-effort
            pass
        # Stop + join the catch-up thread BEFORE closing its stores:
        # racing it would discard the whole rebuild into _rebuild_error
        # and close WAL fds out from under its writes.
        stop = getattr(self, "_stop", None)
        if stop is not None:
            stop.set()
        t = getattr(self, "_rebuild_thread", None)
        if t is not None and t.is_alive():
            t.join()
        first: BaseException | None = None
        for stores in getattr(self, "stores", {}).values():
            for s in stores:
                try:
                    s.close()
                except BaseException as e:
                    if first is None:
                        first = e
        if first is not None:
            raise first

    def _simulate_crash(self) -> None:
        """TEST HOOK: drop every rollup store's writer lock the way
        process death does (pairs with the raw store's hook)."""
        for stores in self.stores.values():
            for s in stores:
                s._simulate_crash()


class ReadOnlyRollupTier(RollupTier):
    """Replica-side rollup READS (the ROADMAP "read-only tier" item).

    A replica daemon opens the writer's rollup stores read-only and
    serves the same planner surface — ``scan_records`` /
    ``pick_resolution`` / ``dirty_hour_bases`` — so long-range
    downsamples cost O(windows) on replicas too, not just the writer.
    It never folds, never rebuilds, never writes ROLLUP.json.

    Correctness leans on refresh ORDER plus the writer's spill
    bracket. ``refresh()`` must run AFTER the raw store's refresh:

    1. The raw view is fixed at T_raw; every raw row it considers
       clean (not memtable-resident) was spilled by a checkpoint that
       STARTED before T_raw.
    2. ``begin_spill`` writes ``pending`` durably BEFORE any raw
       spill, and ``pending=false`` lands only after that spill's fold
       is durable in the rollup WALs. So reading ``ok`` at T > T_raw
       proves every spill the raw view contains has a durable fold.
    3. Refreshing the rollup stores after that read therefore captures
       a fold superset of the raw view's spilled data. Newer folds the
       refresh may half-capture only touch windows whose rows are
       still memtable-dirty in the raw view — windows the planner
       stitches from raw anyway.

    A ``pending`` state (writer mid-checkpoint, crashed bracket,
    rebuild in progress) simply parks the tier not-ready: the planner
    degrades to raw, exactly like a writer-side rebuild.
    """

    read_only = True

    def __init__(self, tsdb, config) -> None:
        if not getattr(tsdb.store, "read_only", False):
            raise ValueError("ReadOnlyRollupTier serves a READ-ONLY "
                             "replica store; writers own RollupTier")
        self._init_layout(tsdb, config)
        # Serializes refresh() against itself: a serve-tier replica
        # can have BOTH the WalTailer and the compaction timer driving
        # refresh_replica(), and interleaved open/adopt sequences
        # would race store handles.
        self._refresh_lock = threading.Lock()
        # Stores retired by a layout adoption, closed only at
        # close(): an in-flight query may still be scanning them, and
        # a handful of leaked read-only handles across rare operator
        # layout changes beats serving a 500 from a closed store.
        self._retired: list[MemKVStore] = []
        # Best effort at open: a missing/pending tier leaves the
        # replica serving raw until the tailer's next cycle.
        self.refresh()

    # -- the replica surface ---------------------------------------------

    def refresh(self) -> bool:
        """One catch-up cycle (call AFTER the raw store's refresh; the
        class docstring has the ordering proof). Returns the resulting
        readiness. Any failure — state unreadable, store churn beyond
        the open retries, injected fault — degrades to not-ready
        rather than raising: replicas must keep serving.

        Concurrency contract with in-flight queries: ``self.stores``
        is only ever swapped WHOLE (never mutated in place) and
        replaced stores are parked in ``_retired`` instead of closed,
        so a query that passed the ``ready`` check keeps a coherent
        (possibly one-cycle-stale) view; transient failures keep the
        previous stores serving and merely drop ``ready``."""
        with self._refresh_lock:
            return self._refresh_locked()

    def _refresh_locked(self) -> bool:
        st = self._read_state()
        if st is None or st.get("pending", True):
            self._ready = False
            return False
        try:
            if any(st.get(k) != v
                   for k, v in self._config_dict().items()):
                # The writer changed the tier layout (resolutions,
                # pack, sketch knobs): adopt it and reopen from empty.
                self._adopt_state(st)
            if not self.stores:
                self.stores = self._open_stores()
            else:
                for stores in self.stores.values():
                    for s in stores:
                        s.refresh()
        except Exception as e:
            LOG.warning("replica rollup refresh degraded to raw: %r", e)
            self._ready = False
            return False
        # Re-read the state AFTER the store refresh: a writer that
        # went pending (or started a layout-change rebuild, which
        # rmtrees the dirs) mid-refresh may have fed us partial data —
        # ok-before AND ok-after brackets a coherent capture.
        st2 = self._read_state()
        self._ready = (st2 is not None
                       and not st2.get("pending", True)
                       and st2 == st)
        # Monotonic refresh stamp: record-level caches built over the
        # previous capture (the approx rail cache) must revalidate.
        self.refreshes = getattr(self, "refreshes", 0) + 1
        return self._ready

    def _open_stores(self) -> dict[int, list[MemKVStore]]:
        out: dict[int, list[MemKVStore]] = {}
        try:
            for r in self.resolutions:
                out[r] = []
                for d in self._dirs[r]:
                    s = MemKVStore(wal_path=os.path.join(d, "wal"),
                                   read_only=True)
                    s.ensure_table(self.table)
                    out[r].append(s)
        except BaseException:
            for stores in out.values():
                for s in stores:
                    try:
                        s.close()
                    except Exception:
                        pass
            raise
        return out

    def _adopt_state(self, st: dict) -> None:
        """Re-derive the layout from the writer's new state file (the
        in-place twin of ``adopt_config``): retire the old stores and
        recompute the per-resolution directory lists."""
        self._ready = False
        for stores in self.stores.values():
            self._retired.extend(stores)
        self.stores = {}
        self.resolutions = tuple(int(r) for r in st["resolutions"])
        self.pack = int(st["pack"])
        self.digest_k = int(st["digest_k"])
        self.hll_p = int(st["hll_p"])
        self.sketch_min_res = int(st["sketch_min_res"])
        self.moment_k = int(st.get("moment_k", 0))
        self.moment_min_res = int(st.get("moment_min_res", 0))
        self.sketch_byte_budget = int(st.get("budget", 0))
        # Replicas never fold; adopting the writer's declared fold
        # kind just keeps _config_dict comparisons stable (a legacy
        # file with no key means host-f64).
        self.fold_kind = str(st.get("fold", "host-f64"))
        alloc = st.get("alloc")
        if isinstance(alloc, dict):
            try:
                self.sketch_alloc = {
                    int(r): tuple(int(x) for x in v)
                    for r, v in alloc.items()}
            except (TypeError, ValueError):
                self.sketch_alloc = self._compute_alloc()
        else:
            self.sketch_alloc = self._compute_alloc()
        base = os.path.dirname(self.state_path)
        self._dirs = {}
        for r in self.resolutions:
            if self._sharded:
                self._dirs[r] = [
                    os.path.join(base, f"shard-{i}", f"rollup-{r}")
                    for i in range(self.shard_count)]
            else:
                wal = self.tsdb.store._wal_path
                self._dirs[r] = [f"{wal}.rollup-{r}"]
        self.hits = {r: self.hits.get(r, 0) for r in self.resolutions}

    # -- writer entry points: refuse loudly ------------------------------

    def begin_spill(self) -> None:
        raise RuntimeError("read-only rollup tier cannot spill")

    def fold_after_spill(self) -> None:
        raise RuntimeError("read-only rollup tier cannot fold")

    def close(self) -> None:
        with self._refresh_lock:
            for stores in self.stores.values():
                self._retired.extend(stores)
            self.stores = {}
            retired, self._retired = self._retired, []
        for s in retired:
            try:
                s.close()
            except Exception:
                pass
