"""Rollup record format + batched window-summary math.

One rollup record summarizes one (series, coarse window) of raw points:
count / sum / min / max / first / last, plus — at sketch-bearing
resolutions — serialized t-digest centroids and HyperLogLog registers
over the window's values. Records are mergeable (Storyboard,
arXiv:2002.03063; t-digest, arXiv:1902.04023): moments combine by
sum/min/max, digests by concatenate+recompress, HLLs by register max —
so a planner can answer any window-aligned downsample by combining
whole-window records instead of re-reducing raw points.

Storage layout (tier.py): rollup rows live in a parallel per-shard
MemKVStore tier under ``rollup-<res>/`` with the SAME key shape as raw
rows — ``[metric:3][superwindow_base:4][tagk tagv]*`` — so series
routing, key regexps, and heapq-merge reads work unchanged. One rollup
row PACKS many consecutive windows into one map cell per kind
(qualifier = kind byte; value = idx-keyed entry map), the rollup
analog of the raw tier's 3600-points-per-row packing: a week of
hourly records is a handful of rows — and a handful of CELLS — per
series, not 168 (the generic sstable row format frames every cell
individually, so per-window cells made reads unpack-bound).

Bit-exactness contract: the planner promises rollup-served sum / count /
min / max / avg answers EQUAL the raw scan's (float64 CPU path) when
one bucket == one window. That pins the reduction algorithms here to
the oracle's: per-window ``sum`` must be ``np.sum`` of the time-sorted
float64 values (numpy's pairwise reduction — ``np.add.reduceat`` is
strictly sequential and diverges in the last bits once a segment
reaches numpy's 8-element unroll threshold, so long segments take a
per-segment ``np.sum``), ``avg`` is served as sum/count (bitwise equal
to ``np.mean`` = pairwise-sum / n), and min/max/count are order-free.
Multi-window buckets combine window sums sequentially — associativity
error only, within float64 tolerance of the raw answer.
"""

from __future__ import annotations

import struct

import numpy as np

# One moment record per (series, window). Little-endian packed; decoded
# in bulk with np.frombuffer, so a scan never parses records one by one.
REC_DTYPE = np.dtype([
    ("count", "<u4"),
    ("sum", "<f8"), ("min", "<f8"), ("max", "<f8"),
    ("first", "<f8"), ("last", "<f8"),
    ("first_dt", "<u4"), ("last_dt", "<u4"),   # ts - window_base
])
REC_SIZE = REC_DTYPE.itemsize

# Cell kinds within a rollup row: ONE cell per (superrow, kind) holding
# a whole window map. The qualifier is the single kind byte; the value
# concatenates per-window entries. Packing many windows into one cell
# matters on both sides: the generic sstable row format frames every
# cell individually (~2 us of struct unpacking per cell on read), so a
# per-window-cell layout made the rollup READ leg unpack-bound, and the
# fold paid the same framing per record in its WAL batches.
KIND_MOMENTS = 0
KIND_SKETCH = 1
QUAL_MOMENTS = bytes([KIND_MOMENTS])
QUAL_SKETCH = bytes([KIND_SKETCH])

# Moment-map entry: window idx within the superrow + the record.
ENTRY_DTYPE = np.dtype([("idx", "<u2"), ("rec", REC_DTYPE)])
ENTRY_SIZE = ENTRY_DTYPE.itemsize

_SK_HDR = struct.Struct("<HI")  # sketch-map entry header: idx, blob len

ROLLUP_FAMILY = b"r"


def pack_moment_map(entries: dict[int, bytes]) -> bytes:
    """Serialize {window idx -> REC_SIZE record bytes}, idx-sorted."""
    return b"".join(struct.pack("<H", i) + entries[i]
                    for i in sorted(entries))


def decode_moment_map(blob: bytes) -> np.ndarray:
    """Inverse of pack_moment_map -> ENTRY_DTYPE array (idx-sorted)."""
    return np.frombuffer(blob, ENTRY_DTYPE)


def merge_moment_map(blob: bytes, entries: dict[int, bytes]) -> bytes:
    """RMW merge: new entries REPLACE same-idx entries of the stored
    map (the tier's replace-from-raw write semantics)."""
    merged = {int(e["idx"]): bytes(memoryview(blob)[
        i * ENTRY_SIZE + 2:(i + 1) * ENTRY_SIZE])
        for i, e in enumerate(decode_moment_map(blob))}
    merged.update(entries)
    return pack_moment_map(merged)


def pack_sketch_map(entries: dict[int, bytes]) -> bytes:
    return b"".join(_SK_HDR.pack(i, len(entries[i])) + entries[i]
                    for i in sorted(entries))


def decode_sketch_map(blob: bytes) -> list[tuple[int, bytes]]:
    out = []
    off = 0
    n = len(blob)
    while off + _SK_HDR.size <= n:
        idx, ln = _SK_HDR.unpack_from(blob, off)
        off += _SK_HDR.size
        out.append((idx, blob[off:off + ln]))
        off += ln
    return out


def merge_sketch_map(blob: bytes, entries: dict[int, bytes]) -> bytes:
    merged = dict(decode_sketch_map(blob))
    merged.update(entries)
    return pack_sketch_map(merged)

# The downsample aggregators a moment record reconstructs EXACTLY.
EXACT_DSAGGS = ("sum", "count", "min", "max", "avg")

# numpy switches from the sequential loop to the 8-accumulator unrolled
# pairwise reduction at 8 elements; below that np.add.reduceat computes
# the identical float64 result.
_PAIRWISE_MIN = 8


# ---------------------------------------------------------------------------
# Batched window summaries (segment reductions over decoded columns)
# ---------------------------------------------------------------------------

def window_summaries(ts: np.ndarray, vals: np.ndarray, res: int,
                     ) -> tuple[np.ndarray, np.ndarray]:
    """Summarize one series' sorted points into per-window records.

    Returns (window_bases int64 [W], records REC_DTYPE [W]). One
    vectorized pass: segment boundaries from the base-time diff, then
    ufunc.reduceat reductions — except ``sum`` for segments at numpy's
    pairwise threshold, which re-reduce with np.sum per segment so the
    stored sum is bit-identical to the oracle's bucket sum (module
    docstring).
    """
    n = len(ts)
    if n == 0:
        return (np.empty(0, np.int64), np.empty(0, REC_DTYPE))
    bases = ts - ts % res
    starts = np.concatenate(([0], np.flatnonzero(np.diff(bases)) + 1))
    ends = np.concatenate((starts[1:], [n]))
    rec = np.empty(len(starts), REC_DTYPE)
    rec["count"] = (ends - starts).astype(np.uint32)
    rec["sum"] = np.add.reduceat(vals, starts)
    long = np.flatnonzero(ends - starts >= _PAIRWISE_MIN)
    for i in long:
        rec["sum"][i] = np.sum(vals[starts[i]:ends[i]])
    rec["min"] = np.minimum.reduceat(vals, starts)
    rec["max"] = np.maximum.reduceat(vals, starts)
    rec["first"] = vals[starts]
    rec["last"] = vals[ends - 1]
    wbase = bases[starts]
    rec["first_dt"] = (ts[starts] - wbase).astype(np.uint32)
    rec["last_dt"] = (ts[ends - 1] - wbase).astype(np.uint32)
    return wbase, rec


# ---------------------------------------------------------------------------
# Bucket combination (planner side)
# ---------------------------------------------------------------------------

def combine_buckets(wbase: np.ndarray, rec: np.ndarray, interval: int,
                    dsagg: str) -> tuple[np.ndarray, np.ndarray]:
    """Combine one series' window records (sorted by base, count > 0)
    into downsample buckets of ``interval`` (a multiple of the window
    resolution). Returns (bucket_ts int64, values float64) — exactly
    the per-series output of oracle.downsample(mode='aligned',
    bucket_ts='start') over the same raw points when every bucket is
    one window, and within float64 associativity tolerance otherwise.
    """
    if len(wbase) == 0:
        return (np.empty(0, np.int64), np.empty(0, np.float64))
    bbase = wbase - wbase % interval
    starts = np.concatenate(
        ([0], np.flatnonzero(np.diff(bbase)) + 1))
    counts = np.add.reduceat(rec["count"].astype(np.int64), starts)
    if dsagg == "count":
        vals = counts.astype(np.float64)
    elif dsagg == "sum":
        vals = np.add.reduceat(rec["sum"], starts)
    elif dsagg == "avg":
        vals = np.add.reduceat(rec["sum"], starts) / counts
    elif dsagg == "min":
        vals = np.minimum.reduceat(rec["min"], starts)
    elif dsagg == "max":
        vals = np.maximum.reduceat(rec["max"], starts)
    else:
        raise ValueError(f"rollup cannot reconstruct dsagg {dsagg!r}")
    return bbase[starts], vals


# ---------------------------------------------------------------------------
# Sketch columns: numpy t-digest + HLL (no device round trips at spill)
# ---------------------------------------------------------------------------

def digest_compress(means: np.ndarray, weights: np.ndarray,
                    k: int) -> tuple[np.ndarray, np.ndarray]:
    """k1-scale batch compression (the numpy twin of
    ops.sketches._compress / stats.collector.LatencyDigest): sort by
    mean, cluster by the arcsine scale on cumulative quantiles, segment
    reduce. Returns (means, weights) sorted, <= k centroids, empties
    dropped."""
    keep = weights > 0
    means, weights = means[keep], weights[keep]
    if len(means) <= k:
        order = np.argsort(means, kind="stable")
        return (means[order].astype(np.float32),
                weights[order].astype(np.float32))
    order = np.argsort(means, kind="stable")
    m, w = means[order].astype(np.float64), weights[order].astype(
        np.float64)
    total = max(w.sum(), 1e-30)
    q_mid = np.clip((np.cumsum(w) - w / 2) / total, 1e-9, 1 - 1e-9)
    kk = k / np.pi * np.arcsin(2 * q_mid - 1) + k / 2
    cluster = np.clip(kk.astype(np.int64), 0, k - 1)
    wsum = np.bincount(cluster, weights=w, minlength=k)
    msum = np.bincount(cluster, weights=m * w, minlength=k)
    nz = wsum > 0
    return ((msum[nz] / wsum[nz]).astype(np.float32),
            wsum[nz].astype(np.float32))


def digest_quantile(means: np.ndarray, weights: np.ndarray,
                    qs) -> np.ndarray:
    """Quantiles by interpolating centroid centers (numpy twin of
    ops.sketches.tdigest_quantile, support-clamped)."""
    if len(means) == 0:
        return np.full(len(np.atleast_1d(qs)), np.nan)
    order = np.argsort(means, kind="stable")
    m = means[order].astype(np.float64)
    w = weights[order].astype(np.float64)
    centers = (np.cumsum(w) - w / 2) / max(w.sum(), 1e-30)
    qs = np.clip(np.atleast_1d(np.asarray(qs, np.float64)), 0.0, 1.0)
    return np.interp(qs, centers, m)


def _hll_ranks(items: np.ndarray, p: int,
               ) -> tuple[np.ndarray, np.ndarray]:
    """(register index, rank) per item — the murmur3-finalizer HLL
    update decomposed so batched callers can scatter into MANY
    register sets at once."""
    h = items.astype(np.uint32)
    h ^= h >> np.uint32(16)
    h *= np.uint32(0x85EBCA6B)
    h ^= h >> np.uint32(13)
    h *= np.uint32(0xC2B2AE35)
    h ^= h >> np.uint32(16)
    idx = (h >> np.uint32(32 - p)).astype(np.int64)
    w = (h << np.uint32(p)) >> np.uint32(p)
    bits = np.zeros(len(w), np.int64)
    nz = w > 0
    bits[nz] = np.frexp(w[nz].astype(np.float64))[1]  # floor(log2)+1
    rank = np.where(nz, (32 - p) - (bits - 1), (32 - p) + 1)
    return idx, rank.astype(np.uint8)


def hll_update(regs: np.ndarray, items: np.ndarray) -> None:
    """Fold hashed items into uint8 registers in place (numpy twin of
    ops.sketches.hll_add: same murmur3 finalizer, so host- and
    device-folded registers merge coherently)."""
    p = int(np.log2(len(regs)))
    idx, rank = _hll_ranks(items, p)
    np.maximum.at(regs, idx, rank)


def hll_estimate(regs: np.ndarray) -> float:
    """Cardinality estimate with the small/large-range corrections of
    ops.sketches.hll_estimate."""
    m = len(regs)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv = np.sum(np.exp2(-regs.astype(np.float64)))
    raw = alpha * m * m / inv
    zeros = float(np.sum(regs == 0))
    if raw <= 2.5 * m and zeros > 0:
        est = m * np.log(m / zeros)
    else:
        est = raw
    two32 = 2.0 ** 32
    if est > two32 / 30.0:
        est = -two32 * np.log1p(-est / two32)
    return float(est)


def sketch_encode(means: np.ndarray, weights: np.ndarray,
                  regs: np.ndarray | None,
                  moment_blob: bytes | None = None) -> bytes:
    """Serialize one window's sketch cell: digest centroids + optional
    HLL registers (p=0 marks absent) + — version 2 — an optional
    moment-sketch section (sketch/moment.py wire bytes, u16 length
    prefix). Version 1 cells (pre-moment tiers) decode unchanged."""
    n = len(means)
    p = int(np.log2(len(regs))) if regs is not None else 0
    ver = 2 if moment_blob is not None else 1
    out = (struct.pack("<BHB", ver, n, p)
           + means.astype("<f4").tobytes()
           + weights.astype("<f4").tobytes()
           + (regs.astype(np.uint8).tobytes() if regs is not None
              else b""))
    if moment_blob is not None:
        out += struct.pack("<H", len(moment_blob)) + moment_blob
    return out


def sketch_decode(blob: bytes):
    """Inverse of sketch_encode -> (means, weights, regs | None).
    (The digest/HLL view; sketch_decode_full adds the moment bytes.)"""
    return sketch_decode_full(blob)[:3]


def sketch_decode_full(blob: bytes):
    """-> (means, weights, regs | None, moment_blob | None)."""
    ver, n, p = struct.unpack_from("<BHB", blob, 0)
    if ver not in (1, 2):
        raise ValueError(f"unknown rollup sketch version {ver}")
    off = 4
    means = np.frombuffer(blob, "<f4", n, off)
    weights = np.frombuffer(blob, "<f4", n, off + 4 * n)
    off += 8 * n
    regs = None
    if p:
        regs = np.frombuffer(blob, np.uint8, 1 << p, off)
        off += 1 << p
    moment = None
    if ver >= 2 and off + 2 <= len(blob):
        (mlen,) = struct.unpack_from("<H", blob, off)
        off += 2
        moment = bytes(blob[off:off + mlen]) if mlen else None
    return means, weights, regs, moment


def window_sketches(ts: np.ndarray, vals: np.ndarray, res: int,
                    digest_k: int, hll_p: int, moment_k: int = 0,
                    kind_bytes: dict | None = None):
    """Per-window sketch cells for one series: (bases, [blob]).
    Digest over the window's float32-cast values; HLL over their bit
    patterns (distinct-value estimates; hashable ints for hll_update);
    moment sketch (power + log-power sums, sketch/moment.py) over the
    same float32-cast values so both quantile columns see identical
    quantization. ``kind_bytes`` (mutated in place when given)
    accumulates encoded bytes per column kind — the
    ``sketch.bytes{kind=}`` accounting.
    """
    n = len(ts)
    if n == 0:
        return np.empty(0, np.int64), []
    v32 = vals.astype(np.float32)
    bases = ts - ts % res
    starts = np.concatenate(([0], np.flatnonzero(np.diff(bases)) + 1))
    ends = np.concatenate((starts[1:], [n]))
    W = len(starts)
    # Batched columns (the fold's hot loop at fine resolutions: 17.5M
    # hourly windows on the 100M corpus — per-window python folds cost
    # ~120 us each, reduceat passes ~10 us):
    # - moment power sums: one cumulative-product ladder over ALL
    #   points, segment-reduced per window (+ the log ladder for
    #   all-positive windows);
    # - HLL: hash every value once, scatter ranks into a [W, 2^p]
    #   register block with ONE maximum.at.
    moments = None
    if moment_k:
        v64 = v32.astype(np.float64)
        powers = np.empty((moment_k, n))
        p = v64.copy()
        for i in range(moment_k):
            powers[i] = p
            if i + 1 < moment_k:
                p = p * v64
        msums = np.add.reduceat(powers, starts, axis=1)     # [k, W]
        wmin = np.minimum.reduceat(v64, starts)
        wmax = np.maximum.reduceat(v64, starts)
        counts = (ends - starts).astype(np.float64)
        has_log = wmin > 0
        lsums = None
        if has_log.any():
            lv = np.log(np.maximum(v64, 1e-300))
            lpow = np.empty((moment_k, n))
            p = lv.copy()
            for i in range(moment_k):
                lpow[i] = p
                if i + 1 < moment_k:
                    p = p * lv
            lsums = np.add.reduceat(lpow, starts, axis=1)
        moments = (counts, wmin, wmax, msums, has_log, lsums)
    regs_all = None
    if hll_p:
        idx, rank = _hll_ranks(v32.view(np.uint32), hll_p)
        win_of_point = np.repeat(np.arange(W, dtype=np.int64),
                                 ends - starts)
        regs_all = np.zeros(W << hll_p, np.uint8)
        np.maximum.at(regs_all, (win_of_point << hll_p) + idx, rank)
        regs_all = regs_all.reshape(W, 1 << hll_p)
    blobs = []
    from opentsdb_tpu.sketch.moment import from_arrays
    for j, (s, e) in enumerate(zip(starts, ends)):
        if digest_k:
            m, w = digest_compress(v32[s:e].astype(np.float64),
                                   np.ones(e - s), digest_k)
        else:
            m = w = np.empty(0, np.float32)
        regs = regs_all[j] if regs_all is not None else None
        moment = None
        if moments is not None:
            counts, wmin, wmax, msums, has_log, lsums = moments
            if has_log[j] and lsums is not None:
                sk = from_arrays(counts[j], wmin[j], wmax[j],
                                 msums[:, j], lsums[:, j])
            else:
                sk = from_arrays(counts[j], wmin[j], wmax[j],
                                 msums[:, j])
            moment = sk.encode()
        if kind_bytes is not None:
            kind_bytes["tdigest"] = (kind_bytes.get("tdigest", 0)
                                     + 8 * len(m))
            if regs is not None:
                kind_bytes["hll"] = kind_bytes.get("hll", 0) + len(regs)
            if moment is not None:
                kind_bytes["moment"] = (kind_bytes.get("moment", 0)
                                        + len(moment))
        blobs.append(sketch_encode(m, w, regs, moment))
    return bases[starts], blobs


# ---------------------------------------------------------------------------
# Mesh-sharded window fold (the execution plane's rollup-fold leg)
# ---------------------------------------------------------------------------

def window_summaries_sharded(series, res: int, mesh):
    """Fold MANY series' points into per-window records across a mesh.

    ``series``: [(ts int64 sorted+deduplicated, vals)] — the same
    per-series inputs :func:`window_summaries` takes one at a time.
    The fold shards over the mesh's series-hash axis via the execution
    plane (parallel/sharded.sharded_window_fold): each device folds
    its series block locally, the combine is an all_gather, so the
    answer is BYTE-IDENTICAL across mesh widths (1 vs N devices —
    proven in tests/test_mesh_plane.py and across real gloo processes
    by scripts/multihost_run.py --plane).

    Returns [(wbase int64 [W_i], rec float32 structured array with
    count/sum/min/max/first/last/first_dt/last_dt)] per series.

    float32, deliberately: this is the device fold for mesh batteries
    and read-side aggregation pipelines. The CHECKPOINT fold stays on
    the float64 host twin above — stored records carry the planner's
    bit-exactness contract against raw float64 scans, which a float32
    device sum cannot honor (the long-standing "no device round trips
    at spill" design note).
    """
    from opentsdb_tpu.parallel.sharded import (
        pack_shards,
        shard_placement,
        sharded_window_fold,
    )

    out_dtype = np.dtype([
        ("count", "<f4"), ("sum", "<f4"), ("min", "<f4"),
        ("max", "<f4"), ("first", "<f4"), ("last", "<f4"),
        ("first_dt", "<u4"), ("last_dt", "<u4")])
    if not series:
        return []
    nonempty = [i for i, (ts, _) in enumerate(series) if len(ts)]
    results = [(np.empty(0, np.int64), np.empty(0, out_dtype))
               for _ in series]
    if not nonempty:
        return results
    origin = min(int(series[i][0][0]) for i in nonempty)
    origin -= origin % res
    hi = max(int(series[i][0][-1]) for i in nonempty)
    num_windows = int((hi - origin) // res) + 1
    D = int(mesh.devices.size)
    packed = [((np.asarray(series[i][0], np.int64) - origin)
               .astype(np.int64),
               np.asarray(series[i][1], np.float32))
              for i in nonempty]
    ts, vals, sid, valid, sps = pack_shards(packed, D)
    grids = np.asarray(sharded_window_fold(
        ts, vals, sid, valid, mesh=mesh, series_per_shard=sps,
        num_windows=num_windows, res=res))
    place = shard_placement(len(packed), D)
    for gi, (d, local) in zip(nonempty, place):
        g = grids[d, :, local, :]                  # [8, W]
        mask = g[0] > 0
        w_idx = np.flatnonzero(mask)
        rec = np.empty(len(w_idx), out_dtype)
        rec["count"] = g[0][mask]
        rec["sum"] = g[1][mask]
        rec["min"] = g[2][mask]
        rec["max"] = g[3][mask]
        rec["first"] = g[4][mask]
        rec["last"] = g[5][mask]
        wbase = origin + w_idx.astype(np.int64) * res
        # Timestamp planes are int32 bitcast into the f32 grid (exact
        # past 2^24 s, unlike a float cast) — view the bits back.
        t_min = np.ascontiguousarray(g[6][mask]).view(np.int32)
        t_max = np.ascontiguousarray(g[7][mask]).view(np.int32)
        rec["first_dt"] = (t_min.astype(np.int64)
                           + origin - wbase).astype(np.uint32)
        rec["last_dt"] = (t_max.astype(np.int64)
                          + origin - wbase).astype(np.uint32)
        results[gi] = (wbase, rec)
    return results


# ---------------------------------------------------------------------------
# Device CHECKPOINT fold (opt-in, declared storage contract)
# ---------------------------------------------------------------------------
#
# window_summaries (above) is the canonical float64-HOST checkpoint
# fold with a bit-exactness contract against raw float64 scans. This
# section moves that fold on-device behind the execution plane
# (Config.rollup_device_fold): f64 accumulation where the backend
# supports it (jax x64 — CPU yes, TPU no), else f32 with the contract
# explicitly RELAXED. Either way the fold KIND is declared in the
# tier's state file ("fold": host-f64 | device-f64 | device-f32),
# because even the f64 device fold is tolerance-level vs the host
# pairwise sum: XLA's scatter-add reduction order is unspecified,
# while the host fold pins numpy's pairwise order. Callers that need
# the byte contract keep the default (host).

_DEVICE_F64: bool | None = None


def device_f64_supported() -> bool:
    """Probe (once) whether the default jax backend really computes in
    float64 under x64 mode — CPU does; TPU silently can't."""
    global _DEVICE_F64
    if _DEVICE_F64 is None:
        try:
            import jax
            import jax.numpy as jnp
            from jax.experimental import enable_x64

            with enable_x64():
                x = jax.device_put(np.array([1.0, 2.0**-40]))
                _DEVICE_F64 = bool(
                    np.asarray(x).dtype == np.float64
                    and float(jnp.sum(x)) != 1.0)
        except Exception:
            _DEVICE_F64 = False
    return _DEVICE_F64


def device_fold_kind() -> str:
    """The storage-contract label a device checkpoint fold would run
    under on this backend (the tier declares it in its state file)."""
    return "device-f64" if device_f64_supported() else "device-f32"


def _device_fold_fn():
    """The jitted fold body, built lazily (summary stays importable
    without jax) and registered on the execution plane."""
    import jax.numpy as jnp

    from opentsdb_tpu.parallel.compile import jit_plan
    from opentsdb_tpu.parallel.plan import ExecPlan

    @jit_plan(ExecPlan(name="rollup.checkpoint_fold", axis="series",
                       static_argnames=("num_windows", "res")))
    def fold(rel_ts, vals, valid, *, num_windows, res):
        n = rel_ts.shape[0]
        w = jnp.clip(rel_ts // res, 0, num_windows - 1)
        w = jnp.where(valid, w, num_windows)    # spill row for padding
        nW = num_windows + 1
        count = jnp.zeros(nW, jnp.int32).at[w].add(1)
        total = jnp.zeros(nW, vals.dtype).at[w].add(
            jnp.where(valid, vals, 0))
        mn = jnp.full(nW, jnp.inf, vals.dtype).at[w].min(
            jnp.where(valid, vals, jnp.inf))
        mx = jnp.full(nW, -jnp.inf, vals.dtype).at[w].max(
            jnp.where(valid, vals, -jnp.inf))
        idx = jnp.arange(n, dtype=jnp.int32)
        i_first = jnp.full(nW, n, jnp.int32).at[w].min(
            jnp.where(valid, idx, n))
        i_last = jnp.full(nW, -1, jnp.int32).at[w].max(
            jnp.where(valid, idx, -1))
        gf = jnp.clip(i_first, 0, n - 1)
        gl = jnp.clip(i_last, 0, n - 1)
        return (count[:num_windows], total[:num_windows],
                mn[:num_windows], mx[:num_windows],
                vals[gf][:num_windows], vals[gl][:num_windows],
                rel_ts[gf][:num_windows], rel_ts[gl][:num_windows])

    return fold


_DEVICE_FOLD = None


def window_summaries_device(ts: np.ndarray, vals: np.ndarray,
                            res: int) -> tuple[np.ndarray, np.ndarray]:
    """:func:`window_summaries` computed ON DEVICE behind the plane.
    Same (window_bases, REC_DTYPE records) return; sums accumulate in
    f64 when the backend supports it (:func:`device_fold_kind`), and
    the result is tolerance-level — NOT byte-identical — vs the host
    fold (XLA scatter order). Spans the int32 rebase can't carry (or a
    missing/odd jax) fall back to the host fold silently: the caller's
    declared kind stays honest because the contract it declares is
    "at most this relaxed"."""
    n = len(ts)
    if n == 0:
        return (np.empty(0, np.int64), np.empty(0, REC_DTYPE))
    origin = int(ts[0]) - int(ts[0]) % res
    span = int(ts[-1]) - origin
    num_windows = span // res + 1
    if span > 2**31 - 1 or num_windows > 1 << 22:
        return window_summaries(ts, vals, res)
    global _DEVICE_FOLD
    try:
        import jax

        if _DEVICE_FOLD is None:
            _DEVICE_FOLD = _device_fold_fn()
        f64 = device_f64_supported()
        pad_n = 1 << max(int(n - 1).bit_length(), 10)
        pad_w = 1 << max(int(num_windows - 1).bit_length(), 6)
        rel = np.zeros(pad_n, np.int32)
        rel[:n] = (np.asarray(ts, np.int64) - origin).astype(np.int32)
        v = np.zeros(pad_n, np.float64 if f64 else np.float32)
        v[:n] = vals
        valid = np.zeros(pad_n, bool)
        valid[:n] = True

        def run():
            return [np.asarray(g) for g in _DEVICE_FOLD(
                jax.device_put(rel), jax.device_put(v),
                jax.device_put(valid), num_windows=pad_w, res=res)]

        if f64:
            from jax.experimental import enable_x64

            with enable_x64():
                grids = run()
        else:
            grids = run()
    except Exception:
        return window_summaries(ts, vals, res)
    count, total, mn, mx, first, last, t_first, t_last = grids
    mask = count > 0
    w_idx = np.flatnonzero(mask)
    rec = np.empty(len(w_idx), REC_DTYPE)
    rec["count"] = count[mask].astype(np.uint32)
    rec["sum"] = total[mask].astype(np.float64)
    rec["min"] = mn[mask].astype(np.float64)
    rec["max"] = mx[mask].astype(np.float64)
    rec["first"] = first[mask].astype(np.float64)
    rec["last"] = last[mask].astype(np.float64)
    wbase = origin + w_idx.astype(np.int64) * res
    rec["first_dt"] = (t_first[mask].astype(np.int64)
                       + origin - wbase).astype(np.uint32)
    rec["last_dt"] = (t_last[mask].astype(np.int64)
                      + origin - wbase).astype(np.uint32)
    return wbase, rec
