"""Materialized multi-resolution rollup tier (summaries + planner).

- summary.py: record format, batched window reductions, sketch columns
- tier.py:    per-shard persistence, checkpoint fold, catch-up daemon
- planner.py: query-side resolution pick + raw-edge stitching
"""

from opentsdb_tpu.rollup.summary import EXACT_DSAGGS, REC_DTYPE  # noqa: F401
