"""Incremental delta accumulators for checkpoint rollup folds.

The checkpoint fold (tier.py ``_fold``) recomputes every summary record
whose coarse window holds a spilled row key by RE-READING the window's
raw rows — replace-from-raw keeps re-folds idempotent across WAL
replay, duplicate ingest, backfill, and deletes. That rescan is also
the dominant cost of checkpoints under sustained ingest, where almost
every spilled window is append-only: the points being rescanned are
exactly the points ``add_batch`` just wrote.

``DeltaFolds`` keeps those points in memory, per (series key, coarse
window), as the SAME columns the rescan would decode — timestamps and
f64 values with floats quantized through f32 (the stored width) and
ints widened i64→f64 — so a fold can feed them to ``_emit_series``
directly and produce bit-identical records without touching the raw
store.

Correctness does NOT rest on the tombstone set; a window is served
from its buffer only when four independent checks pass:

1. Feed cleanliness: every row-hour fed carried ``existed=False`` from
   ``put_many_columnar`` (the row had no cells we didn't feed) or was
   fed by us before. A pre-existing row (WAL replay, scalar puts,
   pre-buffer history) kills the window.
2. Coverage at serve time: the checkpoint spills the WHOLE memtable,
   so any unfolded raw data of the window has its row key in the same
   fold's spilled-key set — the fold serves a window from its buffer
   only if every spilled hour of the window was fed.
3. No prior records: data spilled AND folded in an earlier checkpoint
   (or a previous process) left a summary record in the coarse rollup
   row. A window whose record slot is already populated by anyone but
   this buffer falls back to the full rescan forever.
4. Invalidation hooks: scalar ``add_point`` writes, raw-table deletes
   (fsck, CLI, sabotage harness), and throttled partial batches kill
   their windows explicitly — they bypass the feed path, so checks
   1-2 cannot see them.

Anything killed, evicted (the ``Config.rollup_delta_points`` cap), or
simply never buffered takes the existing full re-read path; the two
paths emit through the same ``_MapBuffer`` under the same fold lock,
so mixing them within one fold is safe.
"""

from __future__ import annotations

import threading

import numpy as np

from opentsdb_tpu.core import codec
from opentsdb_tpu.core.const import TIMESTAMP_BYTES, UID_WIDTH
from opentsdb_tpu.rollup import summary
from opentsdb_tpu.rollup.summary import QUAL_MOMENTS, ROLLUP_FAMILY

# Tombstone-set bound: past this the set is cleared outright (sound —
# the serve-time checks carry correctness; tombstones only save the
# cost of re-buffering known-dead windows).
_DEAD_CAP = 1 << 20


class _Buf:
    """One (series, coarse window) accumulator: parallel ts/value
    chunk lists, merged lazily at serve time."""

    __slots__ = ("ts_chunks", "val_chunks", "fed", "gmin", "gmax",
                 "n", "folded")

    def __init__(self) -> None:
        self.ts_chunks: list[np.ndarray] = []
        self.val_chunks: list[np.ndarray] = []
        self.fed: set[int] = set()       # row-hour bases fed by us
        self.gmin = 0
        self.gmax = -1                   # empty: gmax < gmin
        self.n = 0
        # True once a fold emitted this window FROM THIS BUFFER: the
        # records now in the store are ours, so the no-prior-records
        # check is bypassed on later folds of the same (still
        # complete, still appended-to) window.
        self.folded = False

    def append(self, ts: np.ndarray, vals: np.ndarray) -> None:
        self.ts_chunks.append(ts)
        self.val_chunks.append(vals)
        lo, hi = int(ts[0]), int(ts[-1])
        if self.n == 0:
            self.gmin, self.gmax = lo, hi
        else:
            self.gmin = min(self.gmin, lo)
            self.gmax = max(self.gmax, hi)
        self.n += len(ts)

    def merged_ts(self) -> np.ndarray:
        if len(self.ts_chunks) > 1:
            self._compact()
        return self.ts_chunks[0]

    def _compact(self) -> None:
        ts = np.concatenate(self.ts_chunks)
        vals = np.concatenate(self.val_chunks)
        order = np.argsort(ts, kind="stable")
        self.ts_chunks = [ts[order]]
        self.val_chunks = [vals[order]]

    def columns(self) -> tuple[np.ndarray, np.ndarray]:
        """(ts, vals) sorted ascending — the ``_emit_series`` input
        shape. Chunks are individually sorted (``sort_dedup`` slices),
        so a single merged sort is exact."""
        if len(self.ts_chunks) > 1:
            self._compact()
        return self.ts_chunks[0], self.val_chunks[0]


class DeltaFolds:
    """In-memory per-(series, coarse window) point accumulators.

    Fed from ``TSDB.add_batch`` (the columnar fast path), consumed by
    ``RollupTier._fold``. All public methods are thread-safe;
    ``self.lock`` is strictly innermost — ``serve`` runs under the
    tier's fold lock, ``feed`` under no tier lock at all."""

    def __init__(self, coarse: int, cap_points: int) -> None:
        self.coarse = int(coarse)
        self.cap = max(int(cap_points), 1)
        self.lock = threading.Lock()
        self.bufs: dict[tuple[bytes, int], _Buf] = {}
        self.dead: set[tuple[bytes, int]] = set()
        self.total = 0
        self.enabled = True
        # Compaction rewrites rows delete-after-put with the SAME point
        # set; its deletes must not kill eligibility. Thread-local — the
        # compaction thread's preserve window must not mask a concurrent
        # real delete from another thread.
        self.preserve = threading.local()
        # Best-effort counters (stats surface; GIL discipline).
        self.served = 0
        self.killed = 0
        self.evicted = 0

    # -- ingest side -----------------------------------------------------

    def feed(self, skey: bytes, ts: np.ndarray, f: np.ndarray,
             i: np.ndarray, isf: np.ndarray, base: np.ndarray,
             row_starts: np.ndarray, existed) -> None:
        """Account one applied ``add_batch`` (post sort_dedup columns,
        per-row ``existed`` flags from ``put_many_columnar``)."""
        if not self.enabled:
            return
        skey = bytes(skey)
        nrows = len(row_starts)
        ends = np.concatenate((row_starts[1:], [len(ts)]))
        hb = base[row_starts]
        coarse = self.coarse
        with self.lock:
            r0 = 0
            while r0 < nrows:
                h0 = int(hb[r0])
                cb = h0 - h0 % coarse
                r1 = r0
                while (r1 + 1 < nrows
                       and int(hb[r1 + 1]) - int(hb[r1 + 1]) % coarse
                       == cb):
                    r1 += 1
                self._feed_cb(skey, cb, ts, f, i, isf, hb, row_starts,
                              ends, existed, r0, r1)
                r0 = r1 + 1
            if self.total > self.cap:
                self._evict()

    def _feed_cb(self, skey: bytes, cb: int, ts, f, i, isf, hb,
                 row_starts, ends, existed, r0: int, r1: int) -> None:
        key = (skey, cb)
        if key in self.dead:
            return
        b = self.bufs.get(key)
        fed = b.fed if b is not None else ()
        for r in range(r0, r1 + 1):
            # existed=True on an hour we never fed means the row holds
            # cells that bypassed this buffer — window incomplete.
            if existed[r] and int(hb[r]) not in fed:
                self._kill(key)
                return
        lo, hi = int(row_starts[r0]), int(ends[r1])
        tchunk = ts[lo:hi]
        if b is not None and b.n:
            # A timestamp collision across batches means the raw cell
            # was overwritten (same qualifier, last-writer-wins) or a
            # type/value conflict the full fold would fsck-error on —
            # either way the buffer's view diverges from storage.
            # sort_dedup already settled within-batch duplicates.
            if int(tchunk[0]) <= b.gmax and int(tchunk[-1]) >= b.gmin:
                if np.isin(tchunk, b.merged_ts()).any():
                    self._kill(key)
                    return
        # Values exactly as the raw rescan decodes them: floats are
        # stored 4-byte (encode_cells_multi) and widened f32→f64 by
        # decode_cells_flat; ints widen i64→f64.
        s = slice(lo, hi)
        vchunk = np.where(isf[s],
                          f[s].astype(np.float32).astype(np.float64),
                          i[s].astype(np.float64))
        if b is None:
            b = self.bufs[key] = _Buf()
        b.append(np.ascontiguousarray(tchunk), vchunk)
        for r in range(r0, r1 + 1):
            b.fed.add(int(hb[r]))
        self.total += hi - lo

    # -- invalidation hooks ----------------------------------------------

    def invalidate(self, skey: bytes, hour_base: int) -> None:
        """A write or delete bypassed the feed path (scalar add_point,
        fsck/CLI row deletes): its coarse window can no longer be
        served from the buffer."""
        if not self.enabled:
            return
        cb = int(hour_base) - int(hour_base) % self.coarse
        with self.lock:
            self._kill((bytes(skey), cb))

    def invalidate_key(self, row_key: bytes) -> None:
        """Row-key flavored ``invalidate`` for raw-table delete sites
        (the store delete hook). No-op inside a preserve window — a
        point-set-preserving rewrite (compact_row) is not a delete."""
        if not self.enabled or getattr(self.preserve, "on", False):
            return
        if len(row_key) < UID_WIDTH + TIMESTAMP_BYTES:
            return
        self.invalidate(codec.series_key(row_key),
                        codec.key_base_time(row_key))

    def kill_batch(self, skey: bytes, hour_bases: np.ndarray) -> None:
        """A batch partially applied (throttle): which rows landed is
        unknowable here, so every window it touched dies."""
        if not self.enabled:
            return
        skey = bytes(skey)
        coarse = self.coarse
        with self.lock:
            for cb in {int(h) - int(h) % coarse for h in hour_bases}:
                self._kill((skey, cb))

    def _kill(self, key: tuple[bytes, int]) -> None:
        b = self.bufs.pop(key, None)
        if b is not None:
            self.total -= b.n
            self.killed += 1
        self.dead.add(key)
        if len(self.dead) > _DEAD_CAP:
            # Sound to forget: tombstones are an optimization (module
            # docstring); serve-time checks reject stale re-buffers.
            self.dead.clear()

    def _evict(self) -> None:
        """Oldest coarse windows first, down to 3/4 of the cap — old
        windows are the least likely to see more appends, and their
        next fold (if any) just takes the full path."""
        target = self.cap - self.cap // 4
        for key in sorted(self.bufs, key=lambda k: k[1]):
            if self.total <= target:
                break
            b = self.bufs.pop(key)
            self.total -= b.n
            self.evicted += 1
            self.dead.add(key)
        if len(self.dead) > _DEAD_CAP:
            self.dead.clear()

    # -- fold side -------------------------------------------------------

    def serve(self, tier, cb: int, keys: list[bytes], buf, seen: set,
              ) -> bool:
        """Try to fold one (metric, coarse window) group of spilled row
        ``keys`` from buffers. On True the group's records were emitted
        into ``buf`` (every resolution, sketches included) and its keys
        added to ``seen``; on False nothing was emitted and the caller
        owns the full rescan. Runs under the tier's fold lock."""
        if not self.enabled:
            return False
        with self.lock:
            groups: dict[bytes, list[bytes]] = {}
            for k in keys:
                groups.setdefault(bytes(codec.series_key(k)),
                                  []).append(bytes(k))
            plan = []
            for skey, ks in groups.items():
                b = self.bufs.get((skey, cb))
                if b is None or b.n == 0:
                    return False
                # Whole-memtable spills: unfolded raw data of this
                # window not in the buffer would have spilled its row
                # key right here — an unfed spilled hour proves the
                # buffer incomplete.
                if not {codec.key_base_time(k) for k in ks} <= b.fed:
                    return False
                plan.append((skey, ks, b))
            # All-or-nothing per (metric, window): the fallback rescan
            # is per metric+window and re-emits every series in it, so
            # mixing paths inside one group would double work, not
            # break anything — rejecting whole groups keeps it simple.
            for skey, ks, b in plan:
                if not b.folded and self._has_prior_records(tier, skey,
                                                            cb):
                    self._kill((skey, cb))
                    return False
            for skey, ks, b in plan:
                ts, vals = b.columns()
                if len(ts) > 1 and (ts[1:] == ts[:-1]).any():
                    # Can't happen (feed kills on collision); degrade
                    # to the rescan rather than risk divergence.
                    self._kill((skey, cb))
                    return False
            for skey, ks, b in plan:
                ts, vals = b.columns()
                tier._emit_series(skey, ts, vals, buf)
                b.folded = True
                seen.update(ks)
            self.served += len(plan)
            return True

    def _has_prior_records(self, tier, skey: bytes, cb: int) -> bool:
        """Does the coarse rollup row already record this window?
        (Folded by an earlier checkpoint, a catch-up rebuild, or a
        previous process — the buffer cannot prove it covers that
        data, so the window is not delta-eligible.)"""
        r = tier.resolutions[-1]
        span = r * tier.pack
        sb = cb - cb % span
        key = (skey[:UID_WIDTH] + int(sb).to_bytes(4, "big")
               + skey[UID_WIDTH:])
        idx = (cb - sb) // r
        store = tier.stores[r][tier._shard_of(key)]
        for c in store.get(tier.table, key, ROLLUP_FAMILY):
            if (c.qualifier != QUAL_MOMENTS
                    or len(c.value) % summary.ENTRY_SIZE):
                continue
            if (summary.decode_moment_map(c.value)["idx"] == idx).any():
                return True
        return False

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "windows": len(self.bufs),
            "points": self.total,
            "served": self.served,
            "killed": self.killed,
            "evicted": self.evicted,
        }
