"""Rollup query planning: serve window-aligned downsamples from the
materialized tier, stitching raw points over partial and dirty windows.

``plan()`` is the executor's rollup step. It either returns
``(groups, spec2, res)`` — per-series spans that are ALREADY
downsampled to the query's buckets, plus a rewritten QuerySpec whose
downsample stage is the identity — or ``None`` to fall back to the raw
scan. The executor then runs its normal group/interpolation stage on
either backend, so rollup-served and raw-served queries share every
line of group-aggregation code (and their answers can be compared
bucket for bucket).

Eligibility (the compatibility matrix, README "Rollup tier"):

- downsample present, interval a multiple of some resolution;
- downsample aggregator one of sum/count/min/max/avg (reconstructed
  exactly from the moment columns);
- no rate (rates need consecutive raw points);
- group aggregator any moment or percentile (both operate on the
  per-series bucket values, which are exact);
- tier ready (not rebuilding / crashed / corrupt).

Correctness: windows whose raw rows are still memtable-resident (or
mid-fold) are *dirty* — their summaries may be stale — so their
buckets, like the partial windows at the range edges, are recomputed
from a targeted raw scan. A mostly-dirty range falls back entirely:
the rollup path would degenerate into a slower raw scan.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.core import codec
from opentsdb_tpu.obs import trace as _trace
from opentsdb_tpu.query.aggregators import Aggregators
from opentsdb_tpu.rollup import summary
from opentsdb_tpu.rollup.summary import EXACT_DSAGGS

# A range more dirty than this serves raw outright.
_MAX_DIRTY_FRACTION = 0.5


def _coalesce(ranges: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Merge overlapping/adjacent inclusive [lo, hi] ranges."""
    out: list[list[int]] = []
    for lo, hi in sorted(ranges):
        if out and lo <= out[-1][1] + 1:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return [(lo, hi) for lo, hi in out]


def window_split(start: int, end: int, res: int):
    """Split [start, end] into (full-window range, raw edge ranges).
    Returns (w_lo, w_hi, edges) with w_hi < w_lo when no window fits."""
    s0, e0 = max(start, 0), min(end, 0xFFFFFFFF)
    w_lo = (s0 + res - 1) // res * res
    w_hi = (e0 + 1 - res) // res * res
    edges = []
    if w_hi >= w_lo:
        if s0 < w_lo:
            edges.append((s0, w_lo - 1))
        if w_hi + res <= e0:
            edges.append((w_hi + res, e0))
    return w_lo, w_hi, edges


def plan(executor, spec, start: int, end: int,
         rollup_only: bool = False, meta_out: dict | None = None):
    """``rollup_only`` (load shedding's degraded step): serve from the
    tier records alone — no raw stitching, no mostly-dirty bailout.
    Dirty windows serve their STALE records (their summaries reflect
    the last fold; ``meta_out['stale_windows']`` counts them) and the
    partial edge windows are omitted (``meta_out['omitted_edges']``)
    — the caller tags such results degraded AND reports the coverage,
    never a silent partial. Queries the tier can't serve at all still
    return None (the executor turns that into 503)."""
    tsdb = executor.tsdb
    tier = getattr(tsdb, "rollups", None)
    if tier is None:
        return None
    if not spec.downsample:
        tier.note_fallback("no-downsample")
        return None
    if spec.rate:
        tier.note_fallback("rate")
        return None
    interval, dsagg = spec.downsample
    if dsagg not in EXACT_DSAGGS:
        tier.note_fallback(f"dsagg-{dsagg}")
        return None
    agg = Aggregators.get(spec.aggregator)
    if agg.kind not in ("moment", "percentile"):
        tier.note_fallback("aggregator")
        return None
    res = tier.pick_resolution(interval)
    if res is None:
        tier.note_fallback("interval")
        return None
    if not tier.ready:
        tier.note_miss()
        return None
    sel = _select_windows(executor, tier, spec.metric, spec.tags,
                          start, end, res, want_sketches=False,
                          rollup_only=rollup_only)
    if sel is None:
        return None
    records, raw_parts, dirty_set = sel
    group_by_keys = sorted(
        k for k, _ in executor._tag_filters(spec.tags)[1])

    from opentsdb_tpu.query.executor import _Span

    dirty_arr = (np.fromiter(dirty_set, np.int64, len(dirty_set))
                 if dirty_set else None)
    stale_served: set[int] = set()
    groups: dict[tuple, list] = {}
    for skey in sorted(set(records) | set(raw_parts)):
        bases_list, recs_list = [], []
        hit = records.get(skey)
        if hit is not None:
            bases, recs, _ = hit
            if dirty_arr is not None:
                if rollup_only:
                    # Degraded: dirty windows' records SERVE (stale —
                    # they reflect the last fold) instead of being
                    # silently dropped; the count is reported so the
                    # caller can declare the coverage.
                    stale = np.isin(bases, dirty_arr)
                    if stale.any():
                        stale_served.update(
                            int(b) for b in bases[stale])
                else:
                    keep = ~np.isin(bases, dirty_arr)
                    bases, recs = bases[keep], recs[keep]
            if len(bases):
                bases_list.append(bases)
                recs_list.append(recs)
        part = raw_parts.get(skey)
        if part is not None:
            ts, vals = part
            pb, pr = summary.window_summaries(ts, vals, res)
            if len(pb):
                bases_list.append(pb)
                recs_list.append(pr)
        if not bases_list:
            continue
        bases = np.concatenate(bases_list)
        recs = np.concatenate(recs_list)
        order = np.argsort(bases, kind="stable")
        bts, bvals = summary.combine_buckets(bases[order], recs[order],
                                             interval, dsagg)
        if not len(bts):
            continue
        tag_uids = codec.series_tag_uids(skey)
        named = {tsdb.tagk.get_name(k): tsdb.tagv.get_name(v)
                 for k, v in tag_uids.items()}
        gkey = tuple(tag_uids.get(k, b"") for k in group_by_keys)
        groups.setdefault(gkey, []).append(
            _Span(skey, named, bts, bvals))
    tier.note_hit(res)
    if meta_out is not None and rollup_only:
        meta_out["stale_windows"] = len(stale_served)
        meta_out["omitted_edges"] = len(
            window_split(start, end, res)[2])
        # Dirty windows NO fold has ever recorded (a fresh hour):
        # their buckets are absent — declared, never silent.
        meta_out["missing_windows"] = len(dirty_set - stale_served)
    # The spans are already per-bucket values at bucket-start
    # timestamps: re-downsampling with 'sum' is the identity (one
    # value per bucket), so the whole group stage — interpolation,
    # moments, percentiles, multigroup batching — runs unchanged.
    spec2 = spec._replace(downsample=(interval, "sum"))
    return groups, spec2, res


def _scan_raw_parts(executor, metric_uid: bytes, regexp: bytes | None,
                    ranges: list[tuple[int, int]],
                    exact, group_bys):
    """Targeted raw scans over the stitch ranges -> per-series sorted
    (ts, float64 values), filtered to the ranges.

    Routed through the executor's chunked fragment cache
    (_scan_selector) instead of bespoke scan_series calls: dirty
    windows bypass the cache by definition (they ARE the memtable-hot
    ranges), but the clean EDGE windows of repeat dashboard queries —
    re-stitched on every poll — now serve from the same warm decoded
    fragments full raw scans use, and golden parity vs a cold stitch
    holds because _scan_selector is bit-identical to an uncached scan
    by the fragment-cache contract."""
    parts: dict[bytes, list] = {}
    for lo, hi in ranges:
        with _trace.span("raw.stitch", lo=int(lo), hi=int(hi)):
            per_series = executor._scan_selector(
                metric_uid, exact, group_bys, regexp, lo, hi)
        for skey, cols in per_series.items():
            m = (cols.timestamps >= lo) & (cols.timestamps <= hi)
            if not m.any():
                continue
            parts.setdefault(skey, []).append(
                (cols.timestamps[m], cols.values[m]))
    return {
        skey: (np.concatenate([p[0] for p in ps]),
               np.concatenate([p[1] for p in ps]))
        for skey, ps in parts.items()}


def sketch_windows(executor, tier, metric: str, tags: dict,
                   start: int, end: int, presence_only: bool = False,
                   want_hll: bool = False):
    """Shared selection for the range-limited sketch endpoints: pick a
    sketch-bearing resolution, split the range, and return
    ``(res, records, raw_parts, dirty_set)`` — records carry sketch
    blobs, raw_parts the edge/dirty points to fold in. None when the
    tier cannot serve the range (caller falls back to an exact raw
    computation).

    ``presence_only`` (ranged /distinct): the caller needs record
    PRESENCE, not sketch columns — any resolution serves, so pick the
    finest one that fits (narrowest raw edges), skip the sketch-bearing
    gate (works with digest_k=0 / sub-sketch_min_res ranges, which
    otherwise force a full exact scan), and don't decode blobs."""
    if tier is None or not tier.ready:
        if tier is not None:
            tier.note_miss()
        return None
    span = max(end - start + 1, 1)
    if presence_only:
        if tier.resolutions[0] > span:
            tier.note_fallback("short-range")  # no sketch gate involved
            return None
        candidates = [tier.resolutions[0]]
    else:
        # Coarse to fine: a range WIDE enough for a resolution may
        # still contain no aligned full window of it (a 28h range
        # holds no whole day) — fall through to the next finer
        # sketch-bearing resolution instead of serving raw.
        # ``want_hll`` (distinct-values) skips moment-only rungs:
        # their cells carry no registers, and folding zero of them
        # would return a confident undercount.
        candidates = tier.sketch_candidates(span, want_hll=want_hll)
        if not candidates:
            tier.note_fallback("sketch-res")
            return None
    for res in candidates:
        sel = _select_windows(executor, tier, metric, tags, start,
                              end, res,
                              want_sketches=not presence_only)
        if sel is not None:
            records, raw_parts, dirty_set = sel
            tier.note_hit(res)
            return res, records, raw_parts, dirty_set
    return None


def _select_windows(executor, tier, metric: str, tags: dict,
                    start: int, end: int, res: int,
                    want_sketches: bool, rollup_only: bool = False):
    """THE range selection, shared by plan() and sketch_windows() so
    moment queries and sketch endpoints can never disagree on which
    windows serve from the tier: split [start, end] into full windows
    at ``res`` plus raw edges, derive the dirty-window set (any raw
    row still outside the folded tier, window granularity), fall back
    on short or mostly-dirty ranges, scan the tier's records, and
    raw-scan the coalesced edge+dirty stitch ranges. Returns
    ``(records, raw_parts, dirty_set)`` or None (caller serves raw)."""
    w_lo, w_hi, edges = window_split(start, end, res)
    if w_hi < w_lo:
        tier.note_fallback("short-range")
        return None
    hours = tier.dirty_hour_bases()
    dirty = np.unique(hours - hours % res) if len(hours) else hours
    dirty = dirty[(dirty >= w_lo) & (dirty <= w_hi)]
    n_windows = (w_hi - w_lo) // res + 1
    if (not rollup_only
            and len(dirty) > _MAX_DIRTY_FRACTION * n_windows):
        # A mostly-dirty range would degenerate into a slower raw
        # scan. Under rollup_only the comparison is moot — there IS no
        # raw path — so serve whatever clean windows exist.
        tier.note_fallback("mostly-dirty")
        return None
    # Raw path setup shared with the scan planner: same UID filters,
    # same key regexp (rollup keys have the raw key shape).
    tsdb = executor.tsdb
    metric_uid = tsdb.metrics.get_id(metric)
    exact, group_bys = executor._tag_filters(tags)
    regexp = executor._build_regexp(exact, group_bys)
    with _trace.span("rollup.read", res=res) as sp:
        records = tier.scan_records(res, metric_uid, w_lo, w_hi,
                                    key_regexp=regexp,
                                    want_sketches=want_sketches)
        if sp is not None:
            sp.tags["series"] = len(records)
            sp.tags["dirty_windows"] = int(len(dirty))
    dirty_set = frozenset(int(b) for b in dirty)
    if rollup_only:
        # Degraded: zero raw-scan work — no stitching. The caller
        # decides what dirty windows' records mean (plan() serves
        # them stale and reports the count; the sketch path widens
        # its error bound by their weight).
        return records, {}, dirty_set
    raw_ranges = _coalesce(
        edges + [(int(w), int(w) + res - 1) for w in dirty_set])
    raw_parts = _scan_raw_parts(executor, metric_uid, regexp,
                                raw_ranges, exact, group_bys)
    return records, raw_parts, dirty_set
