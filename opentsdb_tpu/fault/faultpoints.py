"""Failpoint registry: named fault-injection sites in the durability path.

Call sites in storage/kv (WAL append/fsync, the three checkpoint
phases), storage/sstable (record body, atomic rename), storage/sharded
(per-shard spill joins), rollup/tier (spill bracketing, fold commits,
catch-up completion) and replica refresh invoke ``fire(site)``. Unarmed
— the production state — ``fire`` is one empty-dict truthiness check
and a return: the registry starts empty and nothing repopulates it
unless a test, the ``TSDB_FAULTPOINTS`` environment variable, or the
``/fault`` admin endpoint arms a site, so the instrumentation costs
nothing measurable on the ingest hot path (one call per WAL *batch*,
not per cell).

Armed, a site runs a deterministic schedule: the first ``skip`` hits
pass through, then ``count`` hits trigger the action:

    crash    os._exit(EXIT_CODE) — process death, the flock drops, the
             page cache (and with it every flushed-but-not-fsynced
             byte) survives: exactly what SIGKILL does to a daemon.
    torn     truncate the site's file INSIDE its last record (a seeded
             number of bytes off the tail), then crash — the state a
             mid-write power cut leaves. Only sites that pass a
             (path, rec_bytes) context support it.
    raise    raise FaultInjected (exercises in-process error paths:
             spill-failure thaw, manifest rollback, fold abort).
    ioerror  raise OSError (the fsync-failed / disk-full shape that
             broad ``except OSError`` handlers see).
    delay    sleep ``delay`` seconds and continue (race widening).

Schedules are per-process and deterministic: call sites are serialized
(the sharded store spills serially while any site is armed), hits count
up monotonically, and the torn-byte offset derives from the arming's
``seed`` and the hit number — the same arming reproduces the same
on-disk state. The harness (fault/harness.py) arms child processes via
``TSDB_FAULTPOINTS``; live daemons arm through ``/fault``.

Spec grammar (env var and /fault share it)::

    site=mode[:skip=N][:count=N][:delay=SECS][:seed=N][;site2=...]
"""

from __future__ import annotations

import os
import threading
import time

EXIT_CODE = 137  # what SIGKILL would report; harness expects it
ENV_VAR = "TSDB_FAULTPOINTS"

MODES = ("crash", "torn", "raise", "ioerror", "delay")


class FaultInjected(Exception):
    """Raised by an armed ``raise``-mode failpoint."""


class _Arming:
    __slots__ = ("site", "mode", "skip", "count", "delay", "seed",
                 "hits", "fired")

    def __init__(self, site: str, mode: str, skip: int = 0,
                 count: int = 1, delay: float = 0.05,
                 seed: int = 0) -> None:
        if mode not in MODES:
            raise ValueError(f"unknown fault mode {mode!r} "
                             f"(one of {', '.join(MODES)})")
        if skip < 0 or count < 1:
            raise ValueError(f"bad fault schedule skip={skip} "
                             f"count={count}")
        self.site = site
        self.mode = mode
        self.skip = skip
        self.count = count
        self.delay = delay
        self.seed = seed
        self.hits = 0    # total visits while armed
        self.fired = 0   # visits that triggered the action

    def snapshot(self) -> dict:
        return {"mode": self.mode, "skip": self.skip,
                "count": self.count, "delay": self.delay,
                "seed": self.seed, "hits": self.hits,
                "fired": self.fired}


_LOCK = threading.RLock()
_ARMED: dict[str, _Arming] = {}
# Cumulative per-site fired counts, surviving disarm/clear — the
# /stats export (fault.fired) and test assertions read these.
FIRED: dict[str, int] = {}


def active() -> bool:
    """Any site armed? Call sites that must serialize concurrent work
    for schedule determinism (the sharded spill pool) check this."""
    return bool(_ARMED)


def armed(site: str) -> bool:
    return site in _ARMED


def fire(site: str, path: str | None = None, rec_bytes: int = 0) -> None:
    """Hit a failpoint. The unarmed fast path is a dict truthiness
    check; ``path``/``rec_bytes`` give torn mode the file to cut and
    the byte span of its last record."""
    if not _ARMED:
        return
    _fire_armed(site, path, rec_bytes)


def _fire_armed(site: str, path: str | None, rec_bytes: int) -> None:
    with _LOCK:
        a = _ARMED.get(site)
        if a is None:
            return
        a.hits += 1
        if a.hits <= a.skip:
            return
        if a.fired >= a.count:
            return
        a.fired += 1
        FIRED[site] = FIRED.get(site, 0) + 1
        mode, delay = a.mode, a.delay
        # Seeded, hit-dependent, deterministic torn offset.
        torn_k = (a.seed * 2654435761 + a.hits * 40503) & 0x7FFFFFFF
    if mode == "crash":
        os._exit(EXIT_CODE)
    if mode == "torn":
        _tear(path, rec_bytes, torn_k)
        os._exit(EXIT_CODE)
    if mode == "raise":
        raise FaultInjected(f"failpoint {site}")
    if mode == "ioerror":
        raise OSError(f"injected I/O error at failpoint {site}")
    if mode == "delay":
        # A traced request passing through an armed delay site records
        # a fault.delay child span under whatever span is current —
        # the deterministic proof that exactly one stage stretched
        # (obs/trace.py). Imported lazily: fault/ must stay importable
        # in the harness's jax-free child processes even if obs ever
        # grows heavier deps.
        from opentsdb_tpu.obs import trace as _obs_trace
        with _obs_trace.span("fault.delay", site=site):
            time.sleep(delay)


def _tear(path: str | None, rec_bytes: int, k: int) -> None:
    """Truncate ``path`` so the cut lands inside its last record (the
    last ``rec_bytes`` bytes): size - (1 + k % rec_bytes). A k that
    lands exactly at the record boundary removes the whole record — a
    clean crash-before-write state, also worth covering."""
    if not path:
        return
    try:
        size = os.path.getsize(path)
        span = min(max(rec_bytes, 1), size)
        cut = 1 + k % span
        os.truncate(path, max(size - cut, 0))
    except OSError:
        return  # non-file site context: torn degrades to plain crash


# -- arming ----------------------------------------------------------------

def arm(site: str, mode: str, skip: int = 0, count: int = 1,
        delay: float = 0.05, seed: int = 0) -> None:
    with _LOCK:
        _ARMED[site] = _Arming(site, mode, skip=skip, count=count,
                               delay=delay, seed=seed)


def disarm(site: str) -> bool:
    with _LOCK:
        return _ARMED.pop(site, None) is not None


def clear() -> None:
    with _LOCK:
        _ARMED.clear()


def status() -> dict:
    """JSON-ready registry snapshot (the /fault endpoint body)."""
    with _LOCK:
        return {"armed": {s: a.snapshot() for s, a in _ARMED.items()},
                "fired": dict(FIRED)}


def parse_spec(spec: str) -> list[_Arming]:
    """Parse the spec grammar (module docstring) WITHOUT arming —
    validation for /fault before any state changes."""
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        head, *opts = part.split(":")
        site, sep, mode = head.partition("=")
        if not sep or not site or not mode:
            raise ValueError(f"bad fault spec {part!r} "
                             f"(want site=mode[:k=v...])")
        kw: dict = {}
        for opt in opts:
            k, sep, v = opt.partition("=")
            if not sep:
                raise ValueError(f"bad fault option {opt!r} in {part!r}")
            if k in ("skip", "count", "seed"):
                kw[k] = int(v)
            elif k == "delay":
                kw[k] = float(v)
            else:
                raise ValueError(f"unknown fault option {k!r} in "
                                 f"{part!r}")
        out.append(_Arming(site.strip(), mode.strip(), **kw))
    return out


def install_spec(spec: str) -> int:
    """Parse + arm every site in ``spec``; returns the number armed."""
    armings = parse_spec(spec)
    with _LOCK:
        for a in armings:
            _ARMED[a.site] = a
    return len(armings)


def format_spec(site: str, mode: str, skip: int = 0, count: int = 1,
                delay: float = 0.05, seed: int = 0) -> str:
    """One-site spec string (the harness builds child env vars with
    this, so the two grammars cannot drift)."""
    out = f"{site}={mode}"
    if skip:
        out += f":skip={skip}"
    if count != 1:
        out += f":count={count}"
    if mode == "delay":
        out += f":delay={delay}"
    if seed:
        out += f":seed={seed}"
    return out


# Child processes inherit their schedule through the environment: the
# harness sets TSDB_FAULTPOINTS before spawn and this module arms at
# first import (kv.py imports it, so arming precedes any storage work).
_env = os.environ.get(ENV_VAR)
if _env:
    install_spec(_env)
