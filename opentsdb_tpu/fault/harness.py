"""Crash-consistency harness: scripted workload + child crash + verify.

One scenario =

1. spawn a CHILD process (``python -m opentsdb_tpu.fault.harness
   --child``) that runs a seeded, deterministic ingest / backfill /
   delete / checkpoint workload against a real store, with one
   failpoint armed through ``TSDB_FAULTPOINTS`` (fault/faultpoints.py);
2. the armed site kills the child (``os._exit`` — the flock drops, the
   page cache survives: SIGKILL semantics);
3. the PARENT reopens the store and verifies the crash-consistency
   invariants:
     - recovery succeeds and **fsck is clean** (tools/fsck.run_fsck —
       literally the operator tool);
     - **raw golden parity**: every stored point matches an in-memory
       oracle replayed over the acknowledged ops (the progress log
       names them; the one possibly-in-flight op is probed — each op
       is a single WAL record, so it is present or absent atomically);
     - **rollup query parity**: rollup-served answers are bit-identical
       to raw-scan answers for the same queries (the "stale degrades,
       never lies" contract after a crash anywhere in the spill
       bracket);
     - **replica refresh**: a read-only replica over the same files
       refreshes across the writer's post-crash checkpoints (the WAL
       rotation / fresh-inode machinery) and serves the same rows.

Scenarios are deterministic given (seed, site, mode, skip): the
sharded store spills serially while faults are armed, the workload is
pure-seeded, and torn-write offsets derive from the arming seed. On an
invariant failure the harness SHRINKS the schedule (geometrically
fewer ops, same seed) to a minimal failing repro.

``build_matrix()`` is the ≥40-scenario (site x mode x config) sweep
``scripts/crashmatrix.py`` runs; ``FAST_LABELS`` names the tier-1
subset. ``--bug`` deliberately re-introduces a historical durability
bug in the child (e.g. the PR-2-era torn spill bracket) so tests can
prove the matrix CATCHES it — the harness's own regression gate.

The child imports only numpy-backed modules (core/storage/rollup), no
jax — spawn cost stays ~0.5 s per scenario.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import random
import shutil
import subprocess
import sys

import numpy as np

from opentsdb_tpu.core import codec
from opentsdb_tpu.core.errors import NoSuchUniqueName
from opentsdb_tpu.core.tsdb import TSDB
from opentsdb_tpu.fault import faultpoints
from opentsdb_tpu.storage.kv import MemKVStore
from opentsdb_tpu.utils.config import Config

# Day-aligned workload epoch; forward hours allocate upward from here,
# backfill hours downward — ranges never collide, so re-ingest can
# never create the conflicting duplicates IllegalDataError flags.
T0 = 1_600_000_000 - 1_600_000_000 % 86400

_SERIES = [
    ("sys.cpu.user", {"host": "web1", "dc": "east"}),
    ("sys.cpu.user", {"host": "web2", "dc": "east"}),
    ("sys.cpu.user", {"host": "db1", "dc": "west"}),
    ("net.bytes", {"host": "web1"}),
]

# Hour bases reserved for the parent's post-crash replica phase — far
# above anything the generated schedule can allocate.
_EXTRA_HOUR = T0 + 5000 * 3600

CHILD_TIMEOUT = 120.0

BUGS = ("torn-bracket", "ack-before-fsync")


@dataclasses.dataclass
class Scenario:
    label: str
    site: str
    mode: str
    skip: int = 0
    count: int = 1
    shards: int = 1
    rollups: bool = True
    seed: int = 1234
    n_ops: int = 36
    delete_heavy: bool = False
    bug: str | None = None
    # "crash" (child process) | "replica"/"promote" (in-proc) |
    # "meshreshard" (child process WITH jax: sharded hot set, dies at
    # the reshard commit gate)
    kind: str = "crash"
    # Write-side sstable codec for the workload ("none" | "tsst4"):
    # the sst.write.block scenarios need compressed spills to reach
    # their faultpoint; verification reopens with the same codec so
    # post-crash checkpoints re-exercise the compressed writers.
    codec: str = "none"
    # Incremental-catch-up parity row: verification additionally
    # reopens a pristine copy of the crashed store with
    # rollup_incremental_catchup=False (the legacy full rebuild) and
    # demands bit-identical rollup answers from both recovery paths.
    catchup_compare: bool = False
    # Tenant accounting tier for the workload (-1 = the Config
    # default exact cutoff): 0 forces every tenant straight onto the
    # HLL sketch tier, so the tenant-snapshot crash rows cover the
    # estimate-within-error recovery contract, not just the exact one.
    tenant_cutoff: int = -1
    # WAL group-commit linger (Config.wal_group_ms) for the workload:
    # >0 routes every append through the coalescing flusher, so the
    # kv.wal.group.* faultpoints are reachable and acked ops must be
    # covered by a group fsync before the progress file sees them.
    wal_group_ms: float = 0.0


# ---------------------------------------------------------------------------
# Workload: deterministic op schedule + the in-memory oracle
# ---------------------------------------------------------------------------

def gen_ops(seed: int, n_ops: int,
            delete_heavy: bool = False) -> list[tuple]:
    """The scripted op sequence for one scenario. Pure function of its
    arguments — the child executes it, the parent replays it into the
    oracle. Ops: ("ingest", si, hour, n_hours, step, is_float, vbase),
    ("delete_row"|"delete_cells", si, hour), ("checkpoint",)."""
    rng = random.Random(seed)
    fwd = [0] * len(_SERIES)
    bwd = [1] * len(_SERIES)
    ops: list[tuple] = []
    live: list[tuple[int, int]] = []   # deletable (si, hour) pairs

    def ingest(si: int, backfill: bool) -> None:
        n_hours = rng.randint(1, 2)
        if backfill:
            hour = T0 - (bwd[si] + n_hours - 1) * 3600
            bwd[si] += n_hours
        else:
            hour = T0 + fwd[si] * 3600
            fwd[si] += n_hours
        step = rng.choice((300, 600, 900))
        is_float = 1 if rng.random() < 0.3 else 0
        ops.append(("ingest", si, hour, n_hours, step, is_float,
                    rng.randrange(1, 1000)))
        for h in range(n_hours):
            live.append((si, hour + h * 3600))

    del_band = 0.85 if delete_heavy else 0.72
    for i in range(n_ops):
        r = rng.random()
        if i < 4 or r < 0.45:
            ingest(rng.randrange(len(_SERIES)), backfill=False)
        elif r < 0.60:
            ingest(rng.randrange(len(_SERIES)), backfill=True)
        elif r < del_band and live:
            si, hour = live.pop(rng.randrange(len(live)))
            ops.append(("delete_row" if rng.random() < 0.5
                        else "delete_cells", si, hour))
        else:
            ops.append(("checkpoint",))
    # Deterministic tail: ≥2 checkpoints always happen (so every spill
    # site is reachable) and the run ends with live memtable state
    # (so WAL replay is exercised on every reopen).
    ops.append(("checkpoint",))
    ingest(0, backfill=False)
    ops.append(("checkpoint",))
    ingest(1, backfill=False)
    return ops


def points_for(op: tuple):
    """(ts int64, values f64, int_values i64, is_float bool) for one
    ingest op — derived purely from the op tuple, so the child's
    add_batch and the parent's oracle can never disagree. Float values
    are f32-exact (quarters), ints stay on the exact int path."""
    _, _si, hour, n_hours, step, is_float, vbase = op
    per = 3600 // step
    ts = np.concatenate([
        hour + h * 3600 + np.arange(per, dtype=np.int64) * step
        for h in range(n_hours)])
    idx = np.arange(len(ts))
    if is_float:
        f = (vbase % 97) + (idx % 40) * 0.25
        return (ts, f.astype(np.float64),
                np.zeros(len(ts), np.int64), np.ones(len(ts), bool))
    iv = (vbase + idx % 997).astype(np.int64)
    return ts, iv.astype(np.float64), iv, np.zeros(len(ts), bool)


class Oracle:
    """The ground truth: {series index: {ts: (is_float, value)}}."""

    def __init__(self) -> None:
        self.data: dict[int, dict[int, tuple[bool, float]]] = {
            si: {} for si in range(len(_SERIES))}

    def apply(self, op: tuple) -> None:
        kind = op[0]
        if kind == "ingest":
            ts, f64, iv, fl = points_for(op)
            d = self.data[op[1]]
            for t, fv, i, isf in zip(ts.tolist(), f64.tolist(),
                                     iv.tolist(), fl.tolist()):
                d[t] = (bool(isf), fv if isf else i)
        elif kind in ("delete_row", "delete_cells"):
            _, si, hour = op
            d = self.data[si]
            for t in [t for t in d if hour <= t < hour + 3600]:
                del d[t]

    def state_hash(self) -> str:
        h = hashlib.sha1()
        for si in sorted(self.data):
            for t in sorted(self.data[si]):
                isf, v = self.data[si][t]
                h.update(f"{si}:{t}:{int(isf)}:{v!r};".encode())
        return h.hexdigest()

    def bounds(self) -> tuple[int, int] | None:
        ts = [t for d in self.data.values() for t in d]
        if not ts:
            return None
        return min(ts), max(ts)


# ---------------------------------------------------------------------------
# Store plumbing shared by child and parent
# ---------------------------------------------------------------------------

def open_store(dirpath: str, shards: int, read_only: bool = False):
    if shards > 1:
        from opentsdb_tpu.storage.sharded import ShardedKVStore
        return ShardedKVStore(dirpath, shards=shards,
                              read_only=read_only)
    return MemKVStore(wal_path=os.path.join(dirpath, "wal"),
                      read_only=read_only)


def open_tsdb(dirpath: str, shards: int, rollups: bool,
              codec: str = "none", incremental: bool = True,
              tenant_cutoff: int = -1, mesh: bool = False,
              wal_group_ms: float = 0.0) -> TSDB:
    """Writer TSDB with the harness profile: cpu backend, sketches and
    device window off (the child must stay jax-free), compactions off
    and no background threads (schedule determinism), rollup catch-up
    SYNC so a post-crash reopen finishes its rebuild before verify
    queries run. Tenant accounting stays ON (its default): every
    crash scenario doubles as a TENANTS.json recovery check.

    ``mesh=True`` (the meshreshard scenarios only) opts INTO jax: the
    ingest path runs through a 2-shard resident hot set on CPU devices
    so the ``mesh.reshard.commit`` faultpoint is reachable."""
    cfg = Config(
        wal_path=dirpath, shards=shards,
        backend="tpu" if mesh else "cpu",
        auto_create_metrics=True, enable_compactions=False,
        enable_sketches=False, device_window=mesh,
        devwindow_shards=2 if mesh else 0,
        enable_rollups=rollups, rollup_catchup="sync",
        rollup_incremental_catchup=incremental,
        sstable_codec=codec, wal_group_ms=wal_group_ms,
        # Sub-day sketch columns so the 1h resolution carries digests
        # too (more fold surface for the crash sites to land in).
        rollup_sketch_min_res=3600)
    if tenant_cutoff >= 0:
        cfg.tenant_exact_cutoff = tenant_cutoff
    store = open_store(dirpath, shards)
    return TSDB(store, cfg, start_compaction_thread=False)


def _row_key(tsdb: TSDB, si: int, hour: int) -> bytes:
    metric, tags = _SERIES[si]
    return tsdb.row_key_for(metric, tags, hour, create_metric=False,
                            create_tags=False)


def apply_op(tsdb: TSDB, op: tuple) -> None:
    kind = op[0]
    if kind == "ingest":
        ts, f64, iv, fl = points_for(op)
        metric, tags = _SERIES[op[1]]
        tsdb.add_batch(metric, ts, f64, tags, is_float=fl,
                       int_values=iv)
    elif kind == "delete_row":
        tsdb.store.delete_row(tsdb.table, _row_key(tsdb, op[1], op[2]))
    elif kind == "delete_cells":
        key = _row_key(tsdb, op[1], op[2])
        cells = tsdb.store.get(tsdb.table, key, b"t")
        if cells:
            tsdb.store.delete(tsdb.table, key, b"t",
                              [c.qualifier for c in cells])
    elif kind == "checkpoint":
        tsdb.checkpoint()
    else:  # pragma: no cover - schedule bug
        raise ValueError(f"unknown op {op!r}")


def _op_applied(tsdb: TSDB, op: tuple) -> bool:
    """Did the (possibly crash-interrupted) op reach durable storage?
    Sound because every op is one series and lands as ONE WAL record
    (columnar batch / delete record) in one shard: after recovery it is
    either fully present or fully absent — probing one row decides."""
    kind = op[0]
    if kind == "checkpoint":
        return False  # no oracle-visible footprint
    try:
        key = _row_key(tsdb, op[1], op[2])
    except NoSuchUniqueName:
        # UID creation precedes the data put; missing UIDs mean the
        # op's data cannot be in storage either.
        return kind != "ingest"  # a delete's target simply vanished
    if kind == "ingest":
        return tsdb.store.has_row(tsdb.table, key)
    return tsdb.store.cell_count(tsdb.table, key) == 0


def _apply_bug(bug: str) -> None:
    """Deliberately re-introduce a historical durability bug in the
    CHILD so tests can prove the matrix catches it (and stays able
    to). ``torn-bracket`` is the PR-2-era class: the checkpoint's
    rollup spill bracket never opens (no pending marker, no in-flight
    windows), so a crash between the spill-key drain and the fold
    leaves summaries stale with nothing owing a rebuild."""
    if bug == "torn-bracket":
        from opentsdb_tpu.rollup.tier import RollupTier
        RollupTier.begin_spill = lambda self: None
        # The fold side defensively re-persists the bracket before
        # draining spill keys (the peek-persist that makes the
        # bracket self-healing); a faithful reintroduction of the
        # bug class must tear BOTH writers of the pending marker, or
        # the defense quietly repairs the sabotage and the gate goes
        # vacuously green.
        orig_write = RollupTier._write_state

        def torn_write(self, pending, inflight=None):
            if pending:
                return  # the bracket never opens
            orig_write(self, pending)

        RollupTier._write_state = torn_write
    elif bug == "ack-before-fsync":
        # The group-commit regression class: the WAL barrier returns
        # before the covering group fsync, so sync=True appends ack
        # (and the progress file records them) while their bytes sit
        # in the page cache only as far as write() — a crash at
        # kv.wal.group.write loses acknowledged ops and verify must
        # flag the missing rows.
        MemKVStore._ACK_BEFORE_FSYNC = True
    else:
        raise ValueError(f"unknown --bug {bug!r} (one of {BUGS})")


# ---------------------------------------------------------------------------
# Child entry point
# ---------------------------------------------------------------------------

def _child_main(args) -> int:
    ops = gen_ops(args.seed, args.n_ops, args.delete_heavy)
    if args.bug:
        _apply_bug(args.bug)
    tsdb = open_tsdb(args.dir, args.shards, args.rollups,
                     codec=args.codec,
                     tenant_cutoff=args.tenant_cutoff,
                     mesh=args.mesh_reshard,
                     wal_group_ms=args.wal_group_ms)
    with open(args.progress, "a") as pf:
        for i, op in enumerate(ops):
            apply_op(tsdb, op)
            # Flushed (page cache survives os._exit): every op the
            # parent sees here was ACKNOWLEDGED, so its WAL record was
            # flushed first and recovery must surface it.
            pf.write(f"{i}\n")
            pf.flush()
            if args.mesh_reshard and i == len(ops) // 2:
                # Live hot-set redistribution mid-schedule; the armed
                # mesh.reshard.commit site SIGKILLs at the swap gate.
                tsdb.devwindow.reshard(n_shards=4)
        pf.write("end\n")
        pf.flush()
    tsdb.shutdown()
    return 0


def _read_progress(path: str) -> tuple[int, bool]:
    """(ops completed, reached end-of-schedule)."""
    try:
        with open(path) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return 0, False
    done = 0
    finished = False
    for ln in lines:
        if ln == "end":
            finished = True
        else:
            done = max(done, int(ln) + 1)
    return done, finished


# ---------------------------------------------------------------------------
# Parent: verification
# ---------------------------------------------------------------------------

def _dump_store(store, tables=("tsdb", "tsdb-uid")) -> dict:
    out = {}
    for table in tables:
        for key, items in store.scan_raw(table, b"", b""):
            out[(table, key)] = tuple(items)
    return out


def _check_raw_parity(tsdb: TSDB, oracle: Oracle) -> list[str]:
    problems: list[str] = []
    try:
        _, per_series = tsdb.scan_series(b"", b"\xff" * 64)
    except Exception as e:
        return [f"raw scan failed: {e!r}"]
    expected: dict[bytes, dict[int, tuple[bool, float]]] = {}
    for si, pts in oracle.data.items():
        if not pts:
            continue
        try:
            key = _row_key(tsdb, si, 0)
        except NoSuchUniqueName:
            problems.append(f"series {si}: oracle has points but UIDs "
                            f"are missing")
            continue
        expected[codec.series_key(key)] = pts
    for skey, pts in expected.items():
        cols = per_series.pop(skey, None)
        if cols is None:
            problems.append(f"series {skey.hex()}: {len(pts)} oracle "
                            f"points missing from storage")
            continue
        ts_e = np.fromiter(sorted(pts), np.int64, len(pts))
        if not np.array_equal(cols.timestamps, ts_e):
            problems.append(
                f"series {skey.hex()}: timestamp mismatch "
                f"(engine {len(cols.timestamps)} vs oracle {len(ts_e)})")
            continue
        isf_e = np.array([pts[t][0] for t in ts_e.tolist()], bool)
        if not np.array_equal(cols.is_float.astype(bool), isf_e):
            problems.append(f"series {skey.hex()}: float-flag mismatch")
            continue
        vals_e = np.array([pts[t][1] for t in ts_e.tolist()],
                          np.float64)
        # Floats round-trip through the stored f32; ints are exact.
        fbad = isf_e & (cols.values !=
                        vals_e.astype(np.float32).astype(np.float64))
        ibad = ~isf_e & (cols.int_values != vals_e.astype(np.int64))
        if fbad.any() or ibad.any():
            problems.append(f"series {skey.hex()}: value mismatch at "
                            f"ts={int(ts_e[(fbad | ibad)][0])}")
    for skey, cols in per_series.items():
        if len(cols.timestamps):
            problems.append(f"series {skey.hex()}: {len(cols.timestamps)}"
                            f" stored points the oracle never wrote")
    return problems


def _query_specs():
    from opentsdb_tpu.query.executor import QuerySpec
    specs = [
        QuerySpec("sys.cpu.user", {"host": "*"}, aggregator="sum",
                  downsample=(3600, "sum")),
        QuerySpec("sys.cpu.user", {}, aggregator="max",
                  downsample=(86400, "max")),
        QuerySpec("sys.cpu.user", {"dc": "east"}, aggregator="sum",
                  downsample=(3600, "avg")),
        QuerySpec("net.bytes", {}, aggregator="sum",
                  downsample=(3600, "sum")),
        QuerySpec("sys.cpu.user", {}, aggregator="p95",
                  downsample=(3600, "sum")),
    ]
    return specs


def _check_query_parity(tsdb: TSDB, oracle: Oracle,
                        require_rollup: bool) -> list[str]:
    """Rollup-served vs raw-scan answers must be BIT-identical for the
    same spec (the golden-parity invariant after any crash)."""
    from opentsdb_tpu.query.executor import QueryExecutor
    bounds = oracle.bounds()
    if bounds is None:
        return []
    lo, hi = bounds
    hi = max(hi, lo + 1)
    # A range too narrow to hold one aligned 1h window legitimately
    # planner-falls-back everywhere (very early crashes).
    require_rollup = require_rollup and hi - lo >= 2 * 3600
    ex = QueryExecutor(tsdb, backend="cpu")
    problems: list[str] = []
    rollup_served = False
    for spec in _query_specs():
        try:
            served, plan, _ = ex.run_with_plan(spec, lo, hi)
            saved, tsdb.rollups = tsdb.rollups, None
            try:
                raw = ex.run(spec, lo, hi)
            finally:
                tsdb.rollups = saved
        except NoSuchUniqueName:
            # The crash can land before this metric's first ingest was
            # acknowledged — then its UID legitimately doesn't exist.
            # Only a metric the ORACLE holds data for must be
            # queryable.
            if any(pts for si, pts in oracle.data.items()
                   if _SERIES[si][0] == spec.metric):
                problems.append(f"query {spec.metric}: UID missing but "
                                f"the oracle holds its points")
            continue
        except Exception as e:
            problems.append(f"query {spec.aggregator}/{spec.downsample}"
                            f" failed: {e!r}")
            continue
        if plan not in ("raw", "resident"):
            rollup_served = True
        k_s = {tuple(sorted(r.tags.items())): r for r in served}
        k_r = {tuple(sorted(r.tags.items())): r for r in raw}
        if set(k_s) != set(k_r):
            problems.append(f"query {spec.aggregator} plan={plan}: "
                            f"group sets differ")
            continue
        for gk, rs in k_s.items():
            rr = k_r[gk]
            if not (np.array_equal(rs.timestamps, rr.timestamps)
                    and np.array_equal(rs.values, rr.values)):
                problems.append(
                    f"query {spec.aggregator}/{spec.downsample} "
                    f"plan={plan} group={dict(gk)}: rollup-served "
                    f"answer != raw answer")
    if require_rollup and not rollup_served:
        problems.append("rollup tier never served an eligible query "
                        "(planner fell back everywhere)")
    return problems


def _check_tenant_accounting(tsdb: TSDB, sc: Scenario) -> list[str]:
    """The TENANTS.json recovery oracle, run on the freshly reopened
    store BEFORE any verification ingest:

    - **coverage**: every series with rows in storage must be in the
      accountant's seen-set (a series the control plane doesn't know
      is a series no limit can ever govern);
    - **exact tier**: the tracked total must equal the per-tenant
      exact counts (the harness workload is single-tenant, so the
      default tenant's count IS the total) — and after a REBUILD
      (torn/foreign snapshot) it must equal the stored-series count
      exactly;
    - **sketch tier** (tenant_cutoff=0 rows): the HLL estimate must
      sit within 3x the declared relative error of the true tracked
      count (clamped to ±2 absolute for tiny populations, where
      linear counting is effectively exact but the relative bound
      degenerates)."""
    from opentsdb_tpu.storage.sstable import series_hash
    from opentsdb_tpu.tenant.accounting import hll_rel_error
    acct = tsdb.tenants
    if acct is None:
        return ["tenant accounting unexpectedly disabled after reopen"]
    problems: list[str] = []
    stored: set[int] = set()
    for key, _items in tsdb.store.scan_raw(tsdb.table, b"",
                                           b"\xff" * 64):
        stored.add(series_hash(codec.series_key(key)))
    missing = sum(1 for h in stored if not acct.seen(h))
    if missing:
        problems.append(f"tenant accounting is missing {missing} of "
                        f"{len(stored)} stored series")
    if acct.rebuilt and acct.total_tracked() != len(stored):
        problems.append(
            f"rebuilt tenant accounting tracks "
            f"{acct.total_tracked()} series, storage holds "
            f"{len(stored)} (rebuild must be exact)")
    info = acct.snapshot_info()
    true = acct.total_tracked()
    est = sum(ent["series"] for ent in info["tenants"].values())
    tiers = {ent["tier"] for ent in info["tenants"].values()}
    if tiers == {"exact"}:
        if est != true:
            problems.append(f"exact-tier tenant counts sum to {est}, "
                            f"seen-set holds {true}")
    elif true:
        bound = max(3 * hll_rel_error(acct.hll_p) * true, 2)
        if abs(est - true) > bound:
            problems.append(
                f"sketch-tier tenant estimate {est} outside "
                f"±{bound:.1f} of true {true}")
    return problems


def _check_replica(dirpath: str, sc: Scenario, tsdb: TSDB) -> list[str]:
    """Replica-over-live-writer parity, across a post-crash writer
    checkpoint cycle — the WAL rotation + <wal>.old append + fresh-
    inode recreate machinery (the PR-1 replica inode-reuse regression
    rides this check: a recycled inode would make the replica replay
    mid-record garbage)."""
    problems: list[str] = []
    replica = open_store(dirpath, sc.shards, read_only=True)
    try:
        replica.refresh()
        if _dump_store(replica) != _dump_store(tsdb.store):
            problems.append("replica diverged after initial refresh")
        # Writer keeps living: ingest + checkpoint (rotates the WAL; a
        # crash-leftover <wal>.old takes the append + fresh-inode
        # path), then a post-rotation suffix ingest.
        for i, (hour_off, vb) in enumerate(((0, 7), (1, 9))):
            apply_op(tsdb, ("ingest", i, _EXTRA_HOUR + hour_off * 3600,
                            1, 300, 0, vb))
            if i == 0:
                tsdb.checkpoint()
            replica.refresh()
            if _dump_store(replica) != _dump_store(tsdb.store):
                problems.append(
                    f"replica diverged after post-crash "
                    f"{'checkpoint' if i == 0 else 'suffix ingest'}")
    finally:
        replica.close()
    return problems


def _check_catchup_parity(dirpath: str, sc: Scenario, tsdb: TSDB,
                          oracle) -> list[str]:
    """Parity of the two crash-recovery paths: the control copy of the
    crashed store (made BEFORE the primary reopen) recovers with the
    legacy FULL rebuild, then both engines must give bit-identical
    rollup-served answers for the whole battery."""
    from opentsdb_tpu.query.executor import QueryExecutor
    ctl_dir = dirpath + "-fullctl"
    if not os.path.isdir(ctl_dir):
        return ["catchup_compare set but no control copy was made"]
    problems: list[str] = []
    try:
        ctl = open_tsdb(ctl_dir, sc.shards, sc.rollups,
                        codec=sc.codec, incremental=False)
    except Exception as e:
        return [f"full-rebuild control reopen failed: {e!r}"]
    try:
        ctl.checkpoint()   # same post-crash fold the primary ran
        bounds = oracle.bounds()
        if bounds is None:
            return problems
        lo, hi = bounds
        hi = max(hi, lo + 1)
        ex_i = QueryExecutor(tsdb, backend="cpu")
        ex_f = QueryExecutor(ctl, backend="cpu")
        for spec in _query_specs():
            try:
                ri, plan_i, _ = ex_i.run_with_plan(spec, lo, hi)
                rf, plan_f, _ = ex_f.run_with_plan(spec, lo, hi)
            except NoSuchUniqueName:
                continue
            except Exception as e:
                problems.append(f"catchup-compare query "
                                f"{spec.aggregator} failed: {e!r}")
                continue
            if plan_i != plan_f:
                problems.append(
                    f"catchup-compare {spec.aggregator}/"
                    f"{spec.downsample}: plans diverge "
                    f"(incr={plan_i} full={plan_f})")
                continue
            k_i = {tuple(sorted(r.tags.items())): r for r in ri}
            k_f = {tuple(sorted(r.tags.items())): r for r in rf}
            if set(k_i) != set(k_f):
                problems.append(f"catchup-compare {spec.aggregator}: "
                                f"group sets diverge")
                continue
            for gk, a in k_i.items():
                b = k_f[gk]
                if not (np.array_equal(a.timestamps, b.timestamps)
                        and np.array_equal(a.values, b.values)):
                    problems.append(
                        f"catchup-compare {spec.aggregator}/"
                        f"{spec.downsample} group={dict(gk)}: "
                        f"incremental != full-rebuild answer")
    finally:
        try:
            ctl.shutdown()
        except Exception as e:
            problems.append(f"control shutdown failed: {e!r}")
    return problems


def _check_resident_parity(dirpath: str, sc: Scenario) -> list[str]:
    """Post-crash REWARM of the sharded resident hot set: the SIGKILL
    landed at the ``mesh.reshard.commit`` gate, so the swap never
    happened and nothing half-redistributed can have reached durable
    state (the hot set is device memory; durability is the WAL's). A
    restart must (a) rebuild a coherent sharded window, (b) serve
    fresh appends from the RESIDENT plan with scan-path parity, and
    (c) complete the reshard the crash interrupted — with the same
    parity at the new width."""
    from opentsdb_tpu.query.executor import QueryExecutor, QuerySpec
    problems: list[str] = []
    tsdb = open_tsdb(dirpath, sc.shards, rollups=False, mesh=True)
    try:
        dw = tsdb.devwindow
        if dw is None or not hasattr(dw, "shard_of"):
            return ["mesh reopen did not build a sharded hot set"]
        hour = _EXTRA_HOUR + 100 * 3600
        for i in range(3):
            apply_op(tsdb, ("ingest", i, hour + i * 3600, 1, 300, 0,
                            11 + i))
        spec = QuerySpec("sys.cpu.user", {"host": "*"},
                         aggregator="sum", downsample=(600, "avg"))
        ex = QueryExecutor(tsdb, backend="tpu")
        lo, hi = hour, hour + 3 * 3600

        def compare(tag: str) -> None:
            h0 = dw.window_hits
            got = ex.run(spec, lo, hi)
            if dw.window_hits <= h0:
                problems.append(f"{tag}: query fell back off the "
                                f"resident plan")
            keep, tsdb.devwindow = tsdb.devwindow, None
            try:
                want = ex.run(spec, lo, hi)
            finally:
                tsdb.devwindow = keep
            k_g = {tuple(sorted(r.tags.items())): r for r in got}
            k_w = {tuple(sorted(r.tags.items())): r for r in want}
            if set(k_g) != set(k_w):
                problems.append(f"{tag}: resident group set != scan")
                return
            for gk, a in k_g.items():
                b = k_w[gk]
                if not (np.array_equal(a.timestamps, b.timestamps)
                        and np.allclose(a.values, b.values,
                                        rtol=1e-5, atol=1e-5)):
                    problems.append(f"{tag}: resident answer != scan "
                                    f"answer group={dict(gk)}")

        compare("post-crash rewarm")
        dw.reshard(n_shards=4)
        if dw.n_shards != 4 or dw.reshard_count != 1:
            problems.append("post-crash reshard did not complete")
        compare("post-crash reshard to width 4")
    except Exception as e:
        problems.append(f"resident parity check crashed: {e!r}")
    finally:
        tsdb.shutdown()
    return problems


def verify(dirpath: str, sc: Scenario, ops: list[tuple],
           ops_done: int) -> tuple[list[str], str]:
    """Reopen after the crash and check every invariant. Returns
    (problems, oracle state hash)."""
    from opentsdb_tpu.tools.fsck import run_fsck
    problems: list[str] = []
    if sc.catchup_compare:
        # Snapshot the crashed store BEFORE the primary reopen
        # mutates it: the full-rebuild control must recover from the
        # same bytes the incremental path saw.
        import shutil as _sh
        _sh.copytree(dirpath, dirpath + "-fullctl",
                     dirs_exist_ok=True)
    try:
        tsdb = open_tsdb(dirpath, sc.shards, sc.rollups,
                         codec=sc.codec,
                         tenant_cutoff=sc.tenant_cutoff,
                         wal_group_ms=sc.wal_group_ms)
    except Exception as e:
        return [f"reopen failed: {e!r}"], ""
    try:
        # Instrumentation canary: the reopen-and-verify fsck must land
        # a tsd.fsck.duration timer sample in the metrics registry.
        # Every crash scenario exercises this, so observability that
        # dies on recovery paths (half-open store, pending rollup
        # bracket) fails the whole matrix — not just a dashboard.
        from opentsdb_tpu.obs.registry import METRICS
        fsck_timer = METRICS.timer("fsck.duration")
        fsck_count0 = fsck_timer.count
        rep = run_fsck(tsdb, log=problems.append)
        if fsck_timer.count <= fsck_count0:
            problems.append(
                "fsck ran but recorded no tsd.fsck.duration timer "
                "sample (metrics registry broken on recovery path)")
        if rep.errors:
            problems.append(f"fsck: {rep.errors} errors")
        oracle = Oracle()
        for op in ops[:ops_done]:
            oracle.apply(op)
        if ops_done < len(ops) and _op_applied(tsdb, ops[ops_done]):
            # The op the crash interrupted: atomic per op (one WAL
            # record), so a single probe decides its fate.
            oracle.apply(ops[ops_done])
        problems += _check_raw_parity(tsdb, oracle)
        # Tenant accounting parity BEFORE the replica phase ingests
        # its extra rows (the oracle compares against storage as the
        # crash left it + WAL replay).
        problems += _check_tenant_accounting(tsdb, sc)
        problems += _check_replica(dirpath, sc, tsdb)
        if sc.rollups:
            # Fold the recovered (WAL-replayed) memtable so the tier
            # covers the whole history, then demand bit-identical
            # rollup-vs-raw answers. The replica phase above already
            # extended the oracle-visible data; queries compare
            # engine-vs-engine, so that extension is invisible here.
            tsdb.checkpoint()
            problems += _check_query_parity(tsdb, oracle,
                                            require_rollup=True)
        if sc.rollups and sc.catchup_compare:
            problems += _check_catchup_parity(dirpath, sc, tsdb,
                                              oracle)
        return problems, oracle.state_hash()
    except Exception as e:  # verification machinery itself broke
        import traceback
        return (problems + [f"verify crashed: {e!r}",
                            traceback.format_exc(limit=5)], "")
    finally:
        try:
            tsdb.shutdown()
        except Exception as e:
            problems.append(f"shutdown after verify failed: {e!r}")


# ---------------------------------------------------------------------------
# Parent: scenario driver
# ---------------------------------------------------------------------------

def _repo_root() -> str:
    import opentsdb_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(opentsdb_tpu.__file__)))


def _run_once(sc: Scenario, workdir: str) -> dict:
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    store_dir = os.path.join(workdir, "store")
    progress = os.path.join(workdir, "progress")
    spec = faultpoints.format_spec(sc.site, sc.mode, skip=sc.skip,
                                   count=sc.count, seed=sc.seed)
    env = dict(os.environ)
    env["TSDB_FAULTPOINTS"] = spec
    # Most children never import jax; meshreshard children DO (the
    # sharded hot set) and must stay on CPU devices.
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _repo_root() + os.pathsep + \
        env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "opentsdb_tpu.fault.harness",
           "--child", "--dir", store_dir, "--seed", str(sc.seed),
           "--n-ops", str(sc.n_ops), "--shards", str(sc.shards),
           "--progress", progress]
    if sc.rollups:
        cmd.append("--rollups")
    if sc.delete_heavy:
        cmd.append("--delete-heavy")
    if sc.bug:
        cmd += ["--bug", sc.bug]
    if sc.codec != "none":
        cmd += ["--codec", sc.codec]
    if sc.tenant_cutoff >= 0:
        cmd += ["--tenant-cutoff", str(sc.tenant_cutoff)]
    if sc.wal_group_ms > 0:
        cmd += ["--wal-group-ms", str(sc.wal_group_ms)]
    if sc.kind == "meshreshard":
        cmd.append("--mesh-reshard")
    result = {
        "label": sc.label, "site": sc.site, "mode": sc.mode,
        "skip": sc.skip, "shards": sc.shards, "rollups": sc.rollups,
        "seed": sc.seed, "n_ops": sc.n_ops, "bug": sc.bug,
        "codec": sc.codec,
        "problems": [], "ops_done": 0,
    }
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              timeout=CHILD_TIMEOUT)
    except subprocess.TimeoutExpired:
        result.update(status="child-error", child_exit=None,
                      problems=["child timed out"])
        return result
    ops_done, finished = _read_progress(progress)
    result["child_exit"] = proc.returncode
    result["ops_done"] = ops_done
    state_hash = ""
    if proc.returncode == 0 and finished:
        # The armed site never fired: a matrix scenario whose
        # workload can't reach its failpoint is lying about coverage.
        result["status"] = "not-hit"
    elif proc.returncode != faultpoints.EXIT_CODE:
        result.update(status="child-error", problems=[
            f"child exit {proc.returncode}",
            proc.stderr.decode(errors="replace")[-2000:]])
    else:
        ops = gen_ops(sc.seed, sc.n_ops, sc.delete_heavy)
        problems, state_hash = verify(store_dir, sc, ops, ops_done)
        if not problems and sc.kind == "meshreshard":
            # Runs after verify's writer closed: the rewarm reopens
            # the store read-write itself.
            problems += _check_resident_parity(store_dir, sc)
        result["problems"] = problems
        result["status"] = "ok" if not problems else "invariant-failed"
    result["fingerprint"] = hashlib.sha1(
        f"{result['status']}|{result['child_exit']}|{ops_done}|"
        f"{';'.join(result['problems'])}|{state_hash}".encode()
    ).hexdigest()
    result["repro"] = repro_command(sc)
    return result


def repro_command(sc: Scenario) -> str:
    """A self-contained crashmatrix.py invocation that reproduces this
    scenario from its explicit parameters — label-independent, so
    ad-hoc/bug-injected scenarios (whose labels are not in the matrix)
    reproduce too."""
    if sc.kind != "crash":
        # Non-default kinds carry behavior the flag surface doesn't
        # encode — reproduce by matrix label.
        return f"python scripts/crashmatrix.py --only {sc.label}"
    out = (f"python scripts/crashmatrix.py --site {sc.site} "
           f"--mode {sc.mode} --skip {sc.skip} --shards {sc.shards} "
           f"--seed {sc.seed} --n-ops {sc.n_ops}")
    if not sc.rollups:
        out += " --no-rollups"
    if sc.delete_heavy:
        out += " --delete-heavy"
    if sc.bug:
        out += f" --bug {sc.bug}"
    if sc.codec != "none":
        out += f" --codec {sc.codec}"
    if sc.tenant_cutoff >= 0:
        out += f" --tenant-cutoff {sc.tenant_cutoff}"
    if sc.wal_group_ms > 0:
        out += f" --wal-group-ms {sc.wal_group_ms}"
    return out


def _shrink(sc: Scenario, workdir: str) -> dict | None:
    """Minimal failing repro: geometrically fewer ops, same seed/site.
    Returns the smallest still-failing config, or None if only the
    full schedule fails."""
    best = None
    n = sc.n_ops
    tried = sorted({max(4, n // 2), max(4, n // 4), 8, 6, 4},
                   reverse=True)
    for cand in tried:
        if cand >= n:
            continue
        r = _run_once(dataclasses.replace(sc, n_ops=cand),
                      os.path.join(workdir, f"shrink-{cand}"))
        if r["status"] == "invariant-failed":
            best = {"n_ops": cand, "seed": sc.seed,
                    "problems": r["problems"][:3]}
            n = cand
    return best


def _run_replica_scenario(sc: Scenario, workdir: str) -> dict:
    """In-process fault scenarios for the replica refresh path (no
    child crash): an injected refresh/rebuild failure must leave the
    replica serving its coherent pre-refresh view, and a later clean
    refresh must fully converge."""
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    store_dir = os.path.join(workdir, "store")
    problems: list[str] = []
    tsdb = open_tsdb(store_dir, sc.shards, rollups=False)
    try:
        for op in gen_ops(sc.seed, 8):
            apply_op(tsdb, op)
        tsdb.checkpoint()
        replica = open_store(store_dir, sc.shards, read_only=True)
        try:
            before = _dump_store(replica)
            apply_op(tsdb, ("ingest", 2, _EXTRA_HOUR, 1, 300, 0, 3))
            tsdb.checkpoint()   # forces the rebuild path on refresh
            faultpoints.arm(sc.site, sc.mode, skip=sc.skip,
                            count=sc.count, seed=sc.seed)
            try:
                replica.refresh()
                problems.append(f"injected {sc.mode} at {sc.site} was "
                                f"swallowed by refresh()")
            except (faultpoints.FaultInjected, OSError):
                pass
            finally:
                faultpoints.disarm(sc.site)
            if _dump_store(replica) != before:
                problems.append("replica view changed across a FAILED "
                                "refresh (torn rebuild served)")
            replica.refresh()
            if _dump_store(replica) != _dump_store(tsdb.store):
                problems.append("replica did not converge on the clean "
                                "refresh after an injected failure")
        finally:
            replica.close()
    except Exception as e:
        problems.append(f"replica scenario crashed: {e!r}")
    finally:
        faultpoints.disarm(sc.site)
        tsdb.shutdown()
    status = "ok" if not problems else "invariant-failed"
    return {"label": sc.label, "site": sc.site, "mode": sc.mode,
            "skip": sc.skip, "shards": sc.shards, "rollups": False,
            "seed": sc.seed, "n_ops": 8, "bug": None,
            "child_exit": None, "ops_done": 8, "status": status,
            "problems": problems,
            "fingerprint": hashlib.sha1(
                f"{status}|{';'.join(problems)}".encode()).hexdigest(),
            "repro": f"python scripts/crashmatrix.py --only {sc.label}"}


def _run_promote_scenario(sc: Scenario, workdir: str) -> dict:
    """In-process fault scenarios for the replica-promotion path
    (cluster/): an injected failure mid-promotion must leave the
    candidate a coherent, still-read-only replica; a RETRY must take
    over fully; and the deposed writer must come out fenced — exactly
    the states the live serve-matrix promote-crash scenario checks at
    process granularity."""
    from opentsdb_tpu.cluster import epoch as cepoch
    from opentsdb_tpu.core.errors import FencedWriterError

    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    store_dir = os.path.join(workdir, "store")
    problems: list[str] = []
    epoch_path = (os.path.join(store_dir, "EPOCH.json")
                  if sc.shards > 1
                  else os.path.join(store_dir, "wal") + ".epoch.json")
    tsdb = open_tsdb(store_dir, sc.shards, rollups=False)
    try:
        cepoch.write_epoch(epoch_path, 1, "writer")
        # The writer runs UNGUARDED until the fence matters — the ops
        # below predate the promotion, so they must apply normally.
        for op in gen_ops(sc.seed, 10):
            apply_op(tsdb, op)
        tsdb.checkpoint()
        apply_op(tsdb, ("ingest", 1, _EXTRA_HOUR, 1, 300, 0, 5))
        tsdb.store.flush()
        replica = open_store(store_dir, sc.shards, read_only=True)
        try:
            before = _dump_store(replica)
            new_epoch = cepoch.bump_epoch(epoch_path, "replica",
                                          expect=1)
            faultpoints.arm(sc.site, sc.mode, skip=sc.skip,
                            count=sc.count, seed=sc.seed)
            try:
                replica.promote_writable(
                    new_epoch,
                    epoch_guard=cepoch.EpochGuard(epoch_path,
                                                  new_epoch, 0.0))
                problems.append(f"injected {sc.mode} at {sc.site} was "
                                f"swallowed by promote_writable()")
            except (faultpoints.FaultInjected, OSError):
                pass
            finally:
                faultpoints.disarm(sc.site)
            if not replica.read_only:
                problems.append("failed promotion left the store "
                                "writable (half-promoted)")
            if _dump_store(replica) != before:
                problems.append("replica view changed across a FAILED "
                                "promotion (torn takeover served)")
            # The retry must fully take over...
            replica.promote_writable(
                new_epoch,
                epoch_guard=cepoch.EpochGuard(epoch_path, new_epoch,
                                              0.0))
            if _dump_store(replica) != _dump_store(tsdb.store):
                problems.append("promoted store != writer store "
                                "(takeover lost records)")
            # ...and the deposed writer must be fenced: arm ITS guard
            # (production writers carry one from boot; the harness
            # writer ran unguarded so the pre-promotion ops above
            # stayed clean) and watch a mutation refuse.
            tsdb.store.epoch_guard = cepoch.EpochGuard(epoch_path, 1,
                                                       0.0)
            shards = getattr(tsdb.store, "shards", None)
            for s in (shards or [tsdb.store]):
                s.epoch_guard = tsdb.store.epoch_guard
            try:
                apply_op(tsdb, ("ingest", 0, _EXTRA_HOUR + 3600, 1,
                                300, 0, 7))
                problems.append("deposed writer's post-promotion "
                                "ingest was NOT fenced")
            except FencedWriterError:
                pass
        finally:
            replica.close()
    except Exception as e:
        problems.append(f"promote scenario crashed: {e!r}")
    finally:
        faultpoints.disarm(sc.site)
        tsdb.shutdown()
    status = "ok" if not problems else "invariant-failed"
    return {"label": sc.label, "site": sc.site, "mode": sc.mode,
            "skip": sc.skip, "shards": sc.shards, "rollups": False,
            "seed": sc.seed, "n_ops": 10, "bug": None,
            "child_exit": None, "ops_done": 10, "status": status,
            "problems": problems,
            "fingerprint": hashlib.sha1(
                f"{status}|{';'.join(problems)}".encode()).hexdigest(),
            "repro": f"python scripts/crashmatrix.py --only {sc.label}"}


def run_scenario(sc: Scenario, work_root: str,
                 shrink: bool = True) -> dict:
    workdir = os.path.join(work_root, sc.label)
    if sc.kind == "replica":
        return _run_replica_scenario(sc, workdir)
    if sc.kind == "promote":
        return _run_promote_scenario(sc, workdir)
    if sc.mode not in ("crash", "torn"):
        # Child scenarios are verified BY the crash: a raise/ioerror/
        # delay child either errors out mid-workload or finishes
        # cleanly, and _run_once would misreport both as
        # child-error/not-hit. Those modes belong to in-process
        # scenarios (kind="replica") and live-daemon arming — fail
        # loudly instead of lying about coverage.
        raise ValueError(
            f"{sc.label}: child crash scenarios support modes "
            f"crash/torn, not {sc.mode!r} (use kind='replica' or arm "
            f"a live process via /fault for in-process modes)")
    res = _run_once(sc, workdir)
    if res["status"] == "invariant-failed" and shrink:
        res["min_repro"] = _shrink(sc, workdir)
    return res


# ---------------------------------------------------------------------------
# The matrix
# ---------------------------------------------------------------------------

# Tier-1 subset: one scenario per durability machine, cheapest configs.
FAST_LABELS = (
    "wal-append-torn-s1",
    "wal-group-fsync-torn-s1",
    "ckpt-freeze-crash-s1",
    "ckpt-commit-crash-s1",
    "sst-body-torn-s1",
    "sst-footer-torn-s1",
    "sst-block-torn-s1",
    "rollup-foldstart-crash-s1",
    "rollup-flip-crash-s1",
    "rollup-folddel-crash-s1",
    "rollup-foldflush-incrcmp-s1",
    "tenant-snap-commit-torn-s1",
    "shard-join-crash-k2",
    "meshreshard-commit-crash",
)


def build_matrix() -> list[Scenario]:
    """The full (site x mode x config) sweep — ≥40 scenarios across
    WAL / checkpoint / sstable / rollup / sharded-spill / replica."""
    scens: list[Scenario] = []

    def add(label: str, site: str, mode: str, **kw) -> None:
        scens.append(Scenario(label=label, site=site, mode=mode, **kw))

    for shards in (1, 4):
        t = f"s{shards}"
        c = dict(shards=shards, rollups=True, seed=1000 + shards)
        add(f"wal-append-crash-{t}", "kv.wal.append", "crash",
            skip=2, **c)
        add(f"wal-append-torn-{t}", "kv.wal.append", "torn",
            skip=2, **c)
        add(f"wal-append-torn-late-{t}", "kv.wal.append", "torn",
            skip=11, **c)
        add(f"wal-fsync-crash-{t}", "kv.wal.fsync", "crash",
            skip=4, **c)
        # Group commit (Config.wal_group_ms): the coalescing flusher's
        # write and fsync sites. Crash AT the buffered write (the
        # whole group's bytes may be lost — but none of its ops were
        # acked, the barrier still held them) and crash/torn at the
        # group fsync (the torn cut lands inside the unfsynced tail of
        # the WAL, never into bytes a barrier already released).
        add(f"wal-group-write-crash-{t}", "kv.wal.group.write",
            "crash", skip=30, wal_group_ms=2.0,
            **{**c, "seed": 7000 + shards})
        add(f"wal-group-fsync-crash-{t}", "kv.wal.group.fsync",
            "crash", skip=25, wal_group_ms=2.0,
            **{**c, "seed": 7010 + shards})
        add(f"wal-group-fsync-torn-{t}", "kv.wal.group.fsync",
            "torn", skip=30, wal_group_ms=2.0,
            **{**c, "seed": 7020 + shards})
        add(f"wal-group-fsync-torn-late-{t}", "kv.wal.group.fsync",
            "torn", skip=45, wal_group_ms=2.0,
            **{**c, "seed": 7030 + shards})
        add(f"ckpt-freeze-crash-{t}", "kv.checkpoint.freeze", "crash",
            **c)
        add(f"ckpt-freeze-crash2-{t}", "kv.checkpoint.freeze", "crash",
            skip=2, **c)
        add(f"ckpt-commit-crash-{t}", "kv.checkpoint.commit", "crash",
            **c)
        add(f"ckpt-commit-crash2-{t}", "kv.checkpoint.commit", "crash",
            skip=2, **c)
        add(f"ckpt-manifest-crash-{t}", "kv.checkpoint.manifest",
            "crash", **c)
        add(f"sst-body-crash-{t}", "sst.write.body", "crash", **c)
        add(f"sst-body-torn-{t}", "sst.write.body", "torn", **c)
        # Torn targeting the FOOTER section specifically (index +
        # bloom + trailer, the bytes a half-durable file would parse
        # garbage from): the fault-injection follow-on from PR 4.
        add(f"sst-footer-torn-{t}", "sst.write.footer", "torn", **c)
        # Torn/crash INSIDE a TSST4 compressed block body
        # (sst.write.block fires per flushed block): the spill dies
        # mid-compression, leaving a .tmp whose last block is cut —
        # recovery must treat the whole file as a stray and replay
        # <wal>.old. Workload spills compressed (codec=tsst4); the
        # verify reopen + post-crash checkpoints re-exercise the v4
        # writers and fsck's block audits.
        add(f"sst-block-crash-{t}", "sst.write.block", "crash",
            codec="tsst4", **c)
        add(f"sst-block-torn-{t}", "sst.write.block", "torn",
            codec="tsst4", **c)
        add(f"sst-block-torn-late-{t}", "sst.write.block", "torn",
            skip=2, codec="tsst4", **c)
        add(f"sst-rename-crash-{t}", "sst.rename", "crash", **c)
        add(f"rollup-begin-crash-{t}", "rollup.begin_spill", "crash",
            **c)
        add(f"rollup-foldstart-crash-{t}", "rollup.fold.start",
            "crash", **c)
        add(f"rollup-foldflush-crash-{t}", "rollup.fold.flush",
            "crash", **c)
        add(f"rollup-foldcommit-crash-{t}", "rollup.fold.commit",
            "crash", **c)
        add(f"rollup-flip-crash-{t}", "rollup.bracket.flip", "crash",
            **c)
        # Delete-heavy fold crashes: the deleted-row rollup-clobber
        # class (zero records vs surviving coarse windows).
        add(f"rollup-folddel-crash-{t}", "rollup.fold.flush", "crash",
            delete_heavy=True, **{**c, "seed": 77 + shards})
        # Incremental-catch-up parity rows (ROADMAP "Rollup
        # incremental catch-up"): the crash lands between spill and
        # fold commit, the reopen refolds ONLY the persisted inflight
        # windows, and the verify additionally reopens a pristine
        # copy with the legacy FULL rebuild — both recovery paths
        # must give bit-identical rollup answers.
        add(f"rollup-foldflush-incrcmp-{t}", "rollup.fold.flush",
            "crash", catchup_compare=True,
            **{**c, "seed": 4100 + shards})
        add(f"rollup-folddel-incrcmp-{t}", "rollup.fold.flush",
            "crash", delete_heavy=True, catchup_compare=True,
            **{**c, "seed": 4200 + shards})
        # TENANTS.json bracket (tenant/accounting.py): a torn TMP
        # leaves the previous snapshot governing (and the crash
        # happened BEFORE the spill, so snapshot + replayed memtable
        # still cover everything); a torn COMMITTED file is the
        # corruption the storage-scan rebuild must absorb exactly.
        add(f"tenant-snap-write-torn-{t}", "tenant.snapshot.write",
            "torn", **{**c, "seed": 5000 + shards})
        add(f"tenant-snap-commit-torn-{t}", "tenant.snapshot.commit",
            "torn", **{**c, "seed": 5010 + shards})
        add(f"tenant-snap-commit-crash-{t}", "tenant.snapshot.commit",
            "crash", **{**c, "seed": 5020 + shards})
    # Partial cross-shard spills: crash after exactly k of 4 shards.
    for k in (1, 2, 3):
        add(f"shard-join-crash-k{k}", "sharded.spill.shard", "crash",
            skip=k - 1, shards=4, rollups=True, seed=2000 + k)
    # Rollup-less raw stores (the pre-rollup durability surface).
    add("wal-append-crash-norollup", "kv.wal.append", "crash", skip=3,
        shards=1, rollups=False, seed=3001)
    add("ckpt-commit-crash-norollup", "kv.checkpoint.commit", "crash",
        shards=1, rollups=False, seed=3002)
    # Compressed-block torn writes on rollup-less stores too (the
    # ISSUE-12 shards x rollups sweep for sst.write.block).
    add("sst-block-torn-norollup", "sst.write.block", "torn",
        shards=1, rollups=False, codec="tsst4", seed=3003)
    add("sst-block-torn-norollup-s4", "sst.write.block", "torn",
        shards=4, rollups=False, codec="tsst4", seed=3004)
    # Sketch-tier tenant accounting (tenant_cutoff=0 pushes every
    # tenant straight onto the HLL tier): a torn committed snapshot
    # must recover to an estimate within the declared error bound.
    add("tenant-snap-commit-torn-hll", "tenant.snapshot.commit",
        "torn", shards=1, rollups=True, seed=5101, tenant_cutoff=0)
    # Sharded resident hot set: SIGKILL at the reshard commit gate.
    # The swap never lands; a restart must rebuild coherent, serve
    # resident with scan parity, and finish the interrupted reshard.
    add("meshreshard-commit-crash", "mesh.reshard.commit", "crash",
        shards=1, rollups=False, kind="meshreshard", seed=6001,
        n_ops=12)
    # Replica refresh faults (in-process, no child crash).
    add("replica-refresh-ioerror", "replica.refresh", "ioerror",
        shards=1, kind="replica", seed=3101)
    add("replica-rebuild-raise", "replica.rebuild", "raise",
        shards=1, kind="replica", seed=3102)
    add("replica-rebuild-raise-s4", "replica.rebuild", "raise",
        shards=4, kind="replica", seed=3103)
    # Replica promotion faults (cluster/, in-process): a failed
    # takeover must leave a coherent replica, the retry must win, and
    # the deposed writer must be fenced. The live process-kill variant
    # is scripts/servematrix.py promote-crash.
    add("promote-take-raise", "cluster.promote.take", "raise",
        shards=1, kind="promote", seed=3201)
    add("promote-rotate-raise", "cluster.promote.rotate", "raise",
        shards=1, kind="promote", seed=3202)
    add("promote-rotate-raise-s4", "cluster.promote.rotate", "raise",
        shards=4, kind="promote", seed=3203)
    add("promote-rotate-ioerror", "cluster.promote.rotate", "ioerror",
        shards=1, kind="promote", seed=3204)
    return scens


def fast_matrix() -> list[Scenario]:
    by_label = {s.label: s for s in build_matrix()}
    return [by_label[lb] for lb in FAST_LABELS]


def run_matrix(scens, work_root: str, shrink: bool = True,
               log=None) -> list[dict]:
    results = []
    for sc in scens:
        r = run_scenario(sc, work_root, shrink=shrink)
        if log:
            log(f"{r['status']:17s} {sc.label} "
                f"(ops_done={r['ops_done']})")
        results.append(r)
    return results


# ---------------------------------------------------------------------------
# module entry (the child)
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(prog="fault.harness")
    p.add_argument("--child", action="store_true", required=True)
    p.add_argument("--dir", required=True)
    p.add_argument("--seed", type=int, required=True)
    p.add_argument("--n-ops", type=int, required=True)
    p.add_argument("--shards", type=int, default=1)
    p.add_argument("--rollups", action="store_true")
    p.add_argument("--delete-heavy", action="store_true")
    p.add_argument("--progress", required=True)
    p.add_argument("--bug", default=None, choices=BUGS)
    p.add_argument("--codec", default="none",
                   choices=("none", "tsst4"))
    p.add_argument("--tenant-cutoff", type=int, default=-1)
    p.add_argument("--wal-group-ms", type=float, default=0.0)
    p.add_argument("--mesh-reshard", action="store_true")
    args = p.parse_args(argv)
    return _child_main(args)


if __name__ == "__main__":
    sys.exit(main())
