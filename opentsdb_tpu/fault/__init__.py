"""Fault-injection subsystem: failpoint registry + crash harness.

- ``faultpoints``: named failpoints compiled into the durability
  machinery (WAL append/fsync, checkpoint phases, sstable writes,
  rollup spill bracketing, replica refresh); zero-overhead no-ops until
  armed, then crash / tear / raise / delay on a deterministic schedule.
- ``harness``: runs a seeded ingest/delete/checkpoint workload in a
  child process, kills it at the armed point, reopens in the parent and
  verifies the crash-consistency invariants (fsck clean, golden query
  parity raw and rollup-served, replica refresh) against an in-memory
  oracle, with automatic schedule shrinking to a minimal repro.

``scripts/crashmatrix.py`` sweeps the (site x mode) scenario matrix and
writes FAULT_MATRIX.json — the regression floor every durability change
must pass.
"""
