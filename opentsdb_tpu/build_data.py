"""Build/runtime provenance for the ``version`` RPCs and CLI.

Parity: the reference generates a BuildData.java at build time
(build-aux/gen_build_data.sh) carrying git revision, repo status, user,
host, and timestamp, surfaced by the telnet ``version`` command and
``/version`` endpoint (src/tsd/RpcHandler.java:396-421). A source-run
Python package has no build step, so the same facts are resolved at
runtime: revision/status from the live git checkout when the package
sits in one, "unknown" otherwise (e.g. installed into site-packages).
"""

from __future__ import annotations

import functools
import getpass
import os
import socket
import subprocess
import time

from opentsdb_tpu import __version__


# Resolved at import: "since when" must mean process start, not the
# first time someone asks for the version.
_PROCESS_START = int(time.time())


def _git(*args: str) -> str | None:
    root = os.path.dirname(os.path.dirname(__file__))
    # Only trust git when this package itself sits in a checkout: from
    # site-packages, git would walk up and report some unrelated
    # enclosing repository's revision as ours.
    if not os.path.isdir(os.path.join(root, ".git")):
        return None
    try:
        out = subprocess.run(
            ("git", "-C", root) + args,
            capture_output=True, text=True, timeout=5)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return out.stdout.strip() if out.returncode == 0 else None


@functools.lru_cache(maxsize=1)
def build_data() -> dict:
    """Resolved once per process; cheap to call anywhere."""
    revision = _git("rev-parse", "HEAD") or "unknown"
    dirty = _git("status", "--porcelain")
    status = ("unknown" if dirty is None
              else "MODIFIED" if dirty else "MINT")
    ts = _PROCESS_START
    try:
        user = getpass.getuser()
    except Exception:  # no passwd entry in minimal containers
        user = "unknown"
    return {
        "version": __version__,
        "short_revision": revision[:7],
        "full_revision": revision,
        "repo_status": status,
        "user": user,
        "host": socket.gethostname(),
        "timestamp": ts,
    }


def version_string() -> str:
    """One-line human form, shaped like the reference's BuildData.revisionString()."""
    d = build_data()
    when = time.strftime("%Y/%m/%d %H:%M:%S +0000",
                         time.gmtime(d["timestamp"]))
    return (f"opentsdb_tpu {d['version']} built from revision "
            f"{d['short_revision']} ({d['repo_status']})\n"
            f"Running on {d['host']} as {d['user']} since {when}\n")
