"""The TSD network server: one asyncio TCP listener, two protocols.

Parity: reference src/tsd/ — PipelineFactory's first-byte protocol sniff
(a capital ASCII letter means HTTP, :68-98), the telnet command set
(put/stats/version/help/exit/diediedie/dropcaches, RpcHandler :66-96), and
the HTTP endpoint set (/ /aggregators /diediedie /dropcaches /favicon.ico
/logs /q /s /stats /suggest /version, :71-103) plus a /distinct extension
for the HLL cardinality aggregator.

Design departure (fixing the reference's acknowledged flaw, GraphHandler
:180-181 "XXX ... will block Netty"): queries run in a bounded thread pool
off the event loop, so ingest keeps flowing while graphs render. The /q
disk cache keyed on the query-string hash follows GraphHandler
(:335-468): nocache honored, max-age from the end-time rules.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import functools
import hashlib
import json
import logging
import os
import time
import urllib.parse

from opentsdb_tpu import __version__
from opentsdb_tpu.build_data import build_data, version_string
from opentsdb_tpu.core import tags as tags_mod
from opentsdb_tpu.core.errors import (
    BadRequestError,
    FencedWriterError,
    NoSuchUniqueName,
    OverloadedError,
    PleaseThrottleError,
    ReadOnlyStoreError,
)
from opentsdb_tpu.graph.plot import Plot
from opentsdb_tpu.obs import trace as obs_trace
from opentsdb_tpu.obs.registry import METRICS, read_rss_bytes
from opentsdb_tpu.obs.ring import TraceRing, log_slow, make_record
from opentsdb_tpu.query.aggregators import Aggregators
from opentsdb_tpu.query.executor import QueryExecutor, QuerySpec
from opentsdb_tpu.query.grammar import parse_m
from opentsdb_tpu.server import logbuffer
from opentsdb_tpu.stats.collector import LatencyDigest, StatsCollector
from opentsdb_tpu.utils import timeparse
from typing import NamedTuple


class HttpRequest(NamedTuple):
    """What an HttpRpc handler sees (the reference's HttpQuery analog,
    src/tsd/HttpQuery.java, reduced to the parsed request surface)."""
    method: str
    path: str
    q: dict                    # last-value-wins query params
    params: dict               # full multi-value query params
    query_string: str
    body: bytes = b""          # request body (bounded at
    #                            MAX_BODY_BYTES; b"" for GETs)

LOG = logging.getLogger(__name__)

MAX_LINE = 1024       # per-line telnet framing limit (reference
                      # LineBasedFrameDecoder's 1024 B discard protection)
MAX_BUFFER = 1 << 22  # pipelined-burst buffer bound for the bulk path
                      # (4 MiB: bigger bursts = bigger native-decode
                      # batches and fewer pipeline turns per point)

# Protocol-level error counters (the wire.py error-path contract):
# every >= 400 HTTP response and every telnet line the server answered
# with an error bumps these — a collector watching them sees malformed
# clients, oversized bodies, and shed load without parsing log text.
_M_HTTP_ERRORS = METRICS.counter("http.errors")
_M_TELNET_ERRORS = METRICS.counter("telnet.errors")

# Test-only sabotage hook (scripts/servematrix.py --bug): names a
# deliberate serve-tier bug the staleness-oracle gate must catch.
# "stale-serve" suppresses the degraded/stale tagging while the
# replica keeps serving — the exact contract violation the matrix
# exists to flag.
_SERVE_BUG = os.environ.get("TSDB_SERVE_BUG", "")


def _retry_after(seconds: float) -> dict:
    """Retry-After is integral delta-seconds on the wire; never 0 (a
    0 invites an instant retry storm from well-behaved clients)."""
    import math
    return {"Retry-After": str(max(1, math.ceil(seconds)))}


def _parse_max_error(q) -> float | None:
    """The shared ``max_error=`` budget parse for /q and /sketch:
    a positive relative half-width, or None when absent."""
    if "max_error" not in q:
        return None
    try:
        max_error = float(q["max_error"])
    except ValueError:
        raise BadRequestError(
            f"invalid max_error: {q['max_error']}") from None
    if max_error <= 0:
        raise BadRequestError("max_error must be > 0")
    return max_error


def _put_prefix_len(buf: bytes) -> int:
    """Byte length of the longest prefix of complete ``put `` lines.

    Vectorized: the per-line find/startswith loop cost ~200 ns x ~28k
    lines per MiB (~210 ms per million points) on the socket ingest
    path. Four numpy gathers test every line head at once."""
    if len(buf) < 4096:
        pos = 0
        while True:
            nl = buf.find(b"\n", pos)
            if nl < 0:
                return pos
            if not buf.startswith(b"put ", pos):
                return pos
            pos = nl + 1
    import numpy as np

    if not buf.startswith(b"put "):
        return 0
    arr = np.frombuffer(buf, np.uint8)
    nls = np.flatnonzero(arr == 10)
    if len(nls) == 0:
        return 0
    # Line i (i >= 1) starts at nls[i-1] + 1; it must begin "put ".
    starts = nls[:-1] + 1
    # A line start too close to the end can't hold "put " — treat as
    # non-put so the prefix stops before it (the loop path does too,
    # via startswith failing).
    in_range = starts + 4 <= len(buf)
    okput = (in_range
             & (arr[np.minimum(starts, len(buf) - 1)] == 0x70)
             & (arr[np.minimum(starts + 1, len(buf) - 1)] == 0x75)
             & (arr[np.minimum(starts + 2, len(buf) - 1)] == 0x74)
             & (arr[np.minimum(starts + 3, len(buf) - 1)] == 0x20))
    bad = np.flatnonzero(~okput)
    if len(bad) == 0:
        return int(nls[-1]) + 1
    # Prefix = complete put lines before the first non-put line start.
    return int(nls[bad[0]]) + 1

_CONTENT_TYPES = {
    ".html": "text/html; charset=UTF-8",
    ".css": "text/css",
    ".js": "application/javascript",
    ".png": "image/png",
    ".gif": "image/gif",
    ".ico": "image/x-icon",
    ".txt": "text/plain",
}


class TSDServer:
    def __init__(self, tsdb, executor: QueryExecutor | None = None) -> None:
        self.tsdb = tsdb
        if executor is None:
            mesh = None
            shape = getattr(tsdb.config, "mesh_shape", "") or ""
            if shape:
                from opentsdb_tpu.parallel.plan import build_mesh

                mesh = build_mesh(shape)
            elif tsdb.config.mesh_devices > 1:
                from opentsdb_tpu.parallel import make_mesh

                mesh = make_mesh(tsdb.config.mesh_devices)
            if mesh is not None:
                from opentsdb_tpu.parallel.compile import \
                    set_mesh_devices
                set_mesh_devices(int(mesh.devices.size))
            executor = QueryExecutor(tsdb, mesh=mesh)
        self.executor = executor
        self.config = tsdb.config
        # Expert-parallel dashboard serving: the knob alone arms the
        # ATTEMPT — a knob-on daemon without a (multi-device) mesh
        # still DECLARES the decline (plan: "expert-decline",
        # mesh.expert.decline{reason=no-mesh}) instead of silently
        # serving serially, so a misconfigured fleet is visible.
        self.expert_enabled = bool(
            getattr(self.config, "expert_parallel", False))
        if self.config.cachedir:
            # The /q disk cache writes <hash>.txt.tmp files here; create
            # the directory up front so a fresh --cachedir works without
            # operator mkdir (the reference requires a pre-existing dir,
            # GraphHandler.java:335-346 — friendlier here).
            os.makedirs(self.config.cachedir, exist_ok=True)
        self._server: asyncio.AbstractServer | None = None
        self._shutdown = asyncio.Event()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(2, self.config.worker_threads))
        self.log_ring = logbuffer.install()
        # counters (reference ConnectionManager/RpcHandler/PutDataPointRpc)
        self.connections_established = 0
        self.exceptions_caught = 0
        self.telnet_rpcs = 0
        self.http_rpcs = 0
        self.rpcs_unknown = 0
        self.requests_put = 0
        self.hbase_errors_put = 0
        self.illegal_arguments_put = 0
        self.unknown_metrics_put = 0
        self.put_latency = LatencyDigest()
        self.http_latency = LatencyDigest()
        self.graph_latency = LatencyDigest()
        self.cache_hits = 0
        self.cache_misses = 0
        self.start_time = int(time.time())
        # Observability (opentsdb_tpu/obs/): the trace ring holds the
        # last N traced/slow queries for /api/traces; the self-monitor
        # ingests the /stats snapshot into the store itself as tsd.*
        # series every selfmon_interval_s (0 = off — constructed
        # anyway so tests can run_once() deterministically).
        self.trace_ring = TraceRing(
            getattr(self.config, "trace_ring", 256))
        # 1-in-N ambient trace sampling counter (Config.trace_sample_n).
        self._trace_sample_seq = 0
        # Per-plan serve counters (raw / resident / fused / rollup /
        # approx), the /queries view's feed: bounded label set, bumped
        # once per sub-query.
        self.plan_counts: dict[str, int] = {}
        from opentsdb_tpu.obs.selfmon import SelfMonitor
        self.selfmon = SelfMonitor(
            tsdb, self._collect_stats,
            getattr(self.config, "selfmon_interval_s", 0.0))
        # Serve tier (opentsdb_tpu/serve/): admission control runs on
        # every daemon (all knobs default off); the WAL tailer is
        # attached by the CLI for --role replica daemons and owns the
        # staleness contract surfaced at /healthz and in /q tags.
        from opentsdb_tpu.serve.admission import AdmissionController
        self.admission = AdmissionController(self.config)
        self.tailer = None
        # Serializes cluster role transitions (/promote, /demote):
        # they run in the worker pool, so two retried requests can
        # both pass the event-loop idempotency check — the second
        # bump would fence the writer the first one just made.
        import threading
        self._role_lock = threading.Lock()
        self._register_default_commands()

    def attach_tailer(self, tailer) -> None:
        """Wire a serve.tailer.WalTailer into /healthz, /stats, and
        the /q staleness tagging (replica-role daemons)."""
        self.tailer = tailer

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.config.bind, self.config.port)
        self.selfmon.start()
        LOG.info("Ready to serve on %s:%d", self.config.bind,
                 self.config.port)

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        await self._shutdown.wait()
        await self.stop()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self.selfmon.stop()
        if self.tailer is not None:
            self.tailer.stop()
        self._pool.shutdown(wait=False)
        self.tsdb.shutdown()
        LOG.info("Server shut down")

    def request_shutdown(self) -> None:
        self._shutdown.set()

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    # ------------------------------------------------------------------
    # Connection handling: protocol sniff
    # ------------------------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        self.connections_established += 1
        try:
            first = await reader.read(1)
            if not first:
                return
            if b"A" <= first <= b"Z":
                await self._handle_http(first, reader, writer)
            else:
                await self._handle_telnet(first, reader, writer)
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        except Exception:
            self.exceptions_caught += 1
            LOG.exception("Unexpected exception from client")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    # ------------------------------------------------------------------
    # Telnet protocol
    # ------------------------------------------------------------------

    async def _handle_telnet(self, first: bytes, reader, writer) -> None:
        buf = first
        # Connection-scoped tenant id (the telnet analog of ?tenant=):
        # a `tenant <id>` line attributes every LATER put on this
        # connection — admission buckets and the cardinality
        # accounting see the same id the router's HTTP face sees. The
        # router forwards the line ahead of forwarded puts, so
        # attribution survives the hop (it used to stop at the
        # router).
        conn = {"tenant": "default", "line": 0}
        # Per-connection two-stage ingest pipeline (SURVEY §2.9 PP row):
        # chunk N's decode runs in the pool while chunk N-1's ingest is
        # still applying — the server-loop form of wire.pipelined_ingest.
        # ``pending`` is the newest chunk's in-order ingest task,
        # ``older`` the one before it; awaiting ``older`` before
        # spawning a third bounds the pipeline (and its buffered bytes)
        # at two chunks in flight — socket backpressure does the rest.
        pending: asyncio.Task | None = None
        older: asyncio.Task | None = None
        try:
            while not self._shutdown.is_set():
                nl = buf.find(b"\n")
                if nl < 0:
                    if len(buf) > MAX_BUFFER:
                        raise ValueError(
                            "frame length exceeds buffer limit")
                    chunk = await reader.read(
                        max(MAX_BUFFER + 1 - len(buf), 1))
                    if not chunk:
                        break
                    buf += chunk
                    continue
                # Bulk fast path: a pipelined burst of puts decodes
                # natively into columnar arrays and lands through
                # add_batch — this is how the 1M dps/s target is met
                # (SURVEY.md §7). One scan finds the longest prefix of
                # complete put lines; anything after it falls to the
                # per-line command path below.
                if buf.startswith(b"put ") and buf.find(b"\n", nl + 1) >= 0:
                    prefix_len = _put_prefix_len(buf)
                    if prefix_len > nl + 1:
                        chunk, buf = buf[:prefix_len], buf[prefix_len:]
                        if older is not None:
                            await older
                        # The connection's line counter advances NOW
                        # (synchronously, before the next chunk is
                        # carved) so each in-flight bulk task knows the
                        # exact stream line its chunk starts at — error
                        # lines report the connection-wide line number,
                        # not the chunk-relative offset.
                        line_base = conn["line"]
                        conn["line"] += chunk.count(b"\n")
                        older, pending = pending, asyncio.create_task(
                            self._bulk_puts_pipelined(
                                chunk, pending, writer,
                                conn["tenant"], line_base))
                        continue
                # Ordering: bulk results (error lines, stats) land
                # before any later single-line command executes.
                if pending is not None:
                    await pending
                    pending = older = None
                line, buf = buf[:nl], buf[nl + 1:]
                conn["line"] += 1
                if len(line) > MAX_LINE:
                    raise ValueError(f"frame length exceeds {MAX_LINE}")
                words = tags_mod.split_string(
                    line.decode("utf-8", "replace").rstrip("\r"))
                if not words:
                    continue
                self.telnet_rpcs += 1
                if not await self._telnet_command(words, writer, conn):
                    return
        finally:
            # Retrieve both tasks (even on error paths) so no exception
            # is left unawaited; the first failure propagates.
            tasks = [t for t in (older, pending) if t is not None]
            if tasks:
                results = await asyncio.gather(*tasks,
                                               return_exceptions=True)
                for r in results:
                    if isinstance(r, BaseException):
                        raise r

    async def _bulk_puts_pipelined(self, chunk: bytes,
                                   prev: asyncio.Task | None,
                                   writer,
                                   tenant: str = "default",
                                   line_base: int = 0) -> None:
        """Stage A (decode) runs immediately in the pool — overlapping
        the previous chunk's stage B — then awaits ``prev`` so ingest
        and error reporting stay in arrival order. ``line_base`` is the
        connection-wide line number of this chunk's first line, so a
        mid-batch parse error reports its exact stream line."""
        from opentsdb_tpu.server import wire

        t0 = time.time()
        loop = asyncio.get_running_loop()
        batch = await loop.run_in_executor(
            self._pool, functools.partial(
                wire.decode_puts, chunk, line_base=line_base))
        if prev is not None:
            await prev
        # Ingest admission (serve/admission.py): shed the whole batch
        # with a throttle line + retry hint BEFORE it allocates store
        # work — collectors already understand "Please throttle".
        npts = len(batch.sid)
        wait = self.admission.admit_ingest(npts, tenant) if npts \
            else 0.0
        if wait > 0:
            self.telnet_rpcs += npts + len(batch.errors)
            self.requests_put += npts + len(batch.errors)
            self.hbase_errors_put += 1
            _M_TELNET_ERRORS.inc()
            writer.write(
                f"put: Please throttle writes: over ingest quota, "
                f"retry after {max(wait, 0.1):.1f}s\n".encode())
            await writer.drain()
            return
        try:
            n, series_errors = await loop.run_in_executor(
                self._pool,
                functools.partial(wire.ingest_batch, self.tsdb, batch,
                                  tenant=tenant))
        finally:
            if npts:
                self.admission.ingest_done(npts)
        self.telnet_rpcs += n + len(batch.errors)
        self.requests_put += n + len(batch.errors)
        elines = list(batch.error_lines)
        for k, err in enumerate(batch.errors):
            self.illegal_arguments_put += 1
            _M_TELNET_ERRORS.inc()
            # 1-based stream line numbers when the decoder attributed
            # them (the native path doesn't); same line prefix either
            # way so `grep "put: illegal argument"` keeps working.
            at = f" at line {elines[k] + 1}" if k < len(elines) else ""
            writer.write(
                f"put: illegal argument{at}: {err}\n".encode())
        for err in series_errors:
            _M_TELNET_ERRORS.inc()
            if "No such name" in err:
                self.unknown_metrics_put += 1
                writer.write(f"put: unknown metric: {err}\n".encode())
            elif "throttle" in err.lower():
                self.hbase_errors_put += 1
                writer.write(
                    f"put: Please throttle writes: {err}\n".encode())
            elif "[tenant-limit]" in err:
                # Declared cardinality refusal (tenant/limits.py),
                # tagged by wire.ingest_batch: NOT a throttle — the
                # series can never ingest until the limit moves, so
                # the line must not invite a retry loop. The rest of
                # the batch (existing series) already applied.
                self.hbase_errors_put += 1
                writer.write(
                    f"put: tenant series limit exceeded: {err}\n"
                    .encode())
            elif "read-only" in err:
                self.hbase_errors_put += 1
                writer.write(
                    f"put: read-only replica: {err}\n".encode())
            elif "[fenced]" in err:
                # FencedWriterError, tagged by wire.ingest_batch with
                # a stable marker (message wording may drift): this
                # daemon has been deposed — refuse loudly, the router
                # forwards to the current writer.
                self.hbase_errors_put += 1
                writer.write(f"put: fenced writer: {err}\n".encode())
            else:
                self.illegal_arguments_put += 1
                writer.write(f"put: illegal argument: {err}\n".encode())
        self.put_latency.add((time.time() - t0) * 1000)
        await writer.drain()

    # ------------------------------------------------------------------
    # Command registries (the reference's TelnetRpc/HttpRpc SPIs,
    # src/tsd/TelnetRpc.java:22 / HttpRpc.java:20 / RpcHandler.java
    # :66-103 — but as plain dicts a deployment can extend at runtime).
    # ------------------------------------------------------------------

    def register_telnet(self, command: str, handler) -> None:
        """Register ``handler(words, writer) -> bool | None`` for a
        telnet command; returning False closes the connection. A
        handler carrying a truthy ``_wants_conn`` attribute is called
        ``handler(words, writer, conn)`` with the per-connection state
        dict instead (the built-in ``put``/``tenant`` pair use it for
        connection-scoped tenant attribution)."""
        self.telnet_commands[command] = handler

    def register_http(self, route: str, handler) -> None:
        """Register ``async handler(req) -> (status, ctype, body,
        headers)`` for an exact path (no trailing slash)."""
        self.http_routes[route] = handler

    def _register_default_commands(self) -> None:
        self.telnet_commands = {
            "put": self._cmd_put,
            "tenant": self._cmd_tenant,
            "version": lambda words, writer: writer.write(
                self._version_text().encode()),
            "stats": lambda words, writer: writer.write(
                ("\n".join(self._collect_stats()) + "\n").encode()),
            "help": lambda words, writer: writer.write((
                "available commands: "
                + " ".join(sorted(self.telnet_commands))
                + "\n").encode()),
            "exit": lambda words, writer: False,
            "dropcaches": self._cmd_dropcaches,
            "diediedie": self._cmd_diediedie,
        }
        self.http_routes = {
            "/": self._http_home,
            "/aggregators": self._http_aggregators,
            "/version": self._http_version,
            "/stats": self._http_stats,
            "/logs": self._http_logs,
            "/suggest": lambda req: self._suggest(req.q),
            "/q": lambda req: self._query(req.q, req.query_string,
                                          req.params),
            "/distinct": lambda req: self._distinct(req.q),
            "/sketch": lambda req: self._sketch(req.q),
            "/forecast": lambda req: self._forecast(req.q, req.params),
            "/fault": self._http_fault,
            "/queries": self._http_queries_page,
            "/api/queries": self._http_queries,
            "/tenants": self._http_tenants_page,
            "/api/tenants": self._http_tenants,
            "/api/put": self._http_put,
            "/promote": self._http_promote,
            "/demote": self._http_demote,
            "/healthz": self._http_healthz,
            "/api/mesh/reshard": self._http_mesh_reshard,
            "/metrics": self._http_metrics,
            "/api/traces": self._http_traces,
            "/dropcaches": self._http_dropcaches,
            "/diediedie": self._http_diediedie,
            "/favicon.ico": self._http_favicon,
        }

    def _cmd_tenant(self, words, writer, conn):
        # Connection-scoped attribution: `tenant <id>` binds every
        # later put to <id>'s quota + cardinality budget.
        if len(words) != 2 or not words[1]:
            _M_TELNET_ERRORS.inc()
            writer.write(b"tenant: need exactly one id\n")
        else:
            conn["tenant"] = words[1]
            writer.write(f"tenant {words[1]}\n".encode())
    _cmd_tenant._wants_conn = True

    def _cmd_put(self, words, writer, conn):
        self._telnet_put(words, writer, conn["tenant"])
    _cmd_put._wants_conn = True

    def _cmd_dropcaches(self, words, writer):
        self.tsdb.drop_caches()
        writer.write(b"Caches dropped.\n")

    def _cmd_diediedie(self, words, writer):
        writer.write(b"Cleaning up and exiting now.\n")
        self.request_shutdown()
        return False

    async def _telnet_command(self, words: list[str], writer,
                              conn: dict | None = None) -> bool:
        """Dispatch one telnet command; False closes the connection.
        ``conn`` is the per-connection state dict (tenant id)."""
        conn = conn if conn is not None else {"tenant": "default"}
        handler = self.telnet_commands.get(words[0])
        if handler is None:
            self.rpcs_unknown += 1
            _M_TELNET_ERRORS.inc()
            writer.write(f"unknown command: {words[0]}\n".encode())
            await writer.drain()
            return True
        # Per-command latency timer (the HTTP _route twin). The bulk
        # put pipeline bypasses this dispatcher by design — it's
        # covered by rpc.latency/put and the wal.* instruments.
        with METRICS.timer("telnet.handler", {"cmd": words[0]}).time():
            if getattr(handler, "_wants_conn", False):
                out = handler(words, writer, conn)
            else:
                out = handler(words, writer)
            if asyncio.iscoroutine(out):
                out = await out
        # Per-command backpressure: a slow reader pipelining commands
        # must throttle the loop, not grow the transport buffer.
        await writer.drain()
        return out is not False

    def _telnet_put(self, words: list[str], writer,
                    tenant: str = "default") -> None:
        """Parity: reference PutDataPointRpc.importDataPoint (:93-123)."""
        from opentsdb_tpu.core.errors import TenantLimitError
        t0 = time.time()
        self.requests_put += 1
        try:
            wait = self.admission.admit_ingest(1, tenant)
            if wait > 0:
                # Shed: admit_ingest took NO slot, so nothing to
                # release (pairing ingest_done here would free
                # capacity someone else's batch is really using).
                raise PleaseThrottleError(
                    f"over ingest quota, retry after "
                    f"{max(wait, 0.1):.1f}s")
            self.admission.ingest_done(1)
            if len(words) < 5:
                raise ValueError("not enough arguments"
                                 f" (need least 5, got {len(words)})")
            metric = words[1]
            timestamp = tags_mod.parse_long(words[2])
            if timestamp <= 0:
                raise ValueError("invalid timestamp: " + str(timestamp))
            # Same strict value grammar as the bulk/native path, so
            # acceptance never depends on pipelining.
            is_float, ival, fval = tags_mod.parse_value(words[3])
            tag_map: dict[str, str] = {}
            for tag in words[4:]:
                tags_mod.parse(tag_map, tag)
            if is_float:
                self.tsdb.add_point(metric, timestamp, fval, tag_map,
                                    tenant=tenant)
            else:
                self.tsdb.add_point(metric, timestamp, ival, tag_map,
                                    tenant=tenant)
            self.put_latency.add((time.time() - t0) * 1000)
        except TenantLimitError as e:
            # Declared cardinality refusal (tenant/limits.py): a
            # DISTINCT line from the throttle — collectors must not
            # treat it as transient; the put can never succeed until
            # the limit is raised. Existing series keep ingesting.
            self.hbase_errors_put += 1
            _M_TELNET_ERRORS.inc()
            writer.write(
                f"put: tenant series limit exceeded: {e}\n".encode())
        except NoSuchUniqueName as e:
            self.unknown_metrics_put += 1
            _M_TELNET_ERRORS.inc()
            writer.write(f"put: unknown metric: {e}\n".encode())
        except (ValueError, ArithmeticError) as e:
            self.illegal_arguments_put += 1
            _M_TELNET_ERRORS.inc()
            writer.write(f"put: illegal argument: {e}\n".encode())
        except PleaseThrottleError as e:
            self.hbase_errors_put += 1
            _M_TELNET_ERRORS.inc()
            writer.write(f"put: Please throttle writes: {e}\n".encode())
        except ReadOnlyStoreError as e:
            # A replica daemon (--read-only) serves reads only; tell
            # the collector to write to the writer frontend instead.
            self.hbase_errors_put += 1
            _M_TELNET_ERRORS.inc()
            writer.write(f"put: read-only replica: {e}\n".encode())
        except FencedWriterError as e:
            # Deposed writer (cluster/epoch.py): a promotion bumped
            # the epoch past ours while this daemon was wedged. The
            # put is REFUSED — never acked, never applied to a
            # replayable file — and the collector should re-send to
            # the router, which forwards to the current writer.
            self.hbase_errors_put += 1
            _M_TELNET_ERRORS.inc()
            writer.write(f"put: fenced writer (superseded by epoch "
                         f"{e.current_epoch}): {e}\n".encode())

    # ------------------------------------------------------------------
    # HTTP protocol
    # ------------------------------------------------------------------

    # HTTP request bounds (the telnet path's MAX_BUFFER analog).
    MAX_HEADER_BYTES = 65536
    MAX_BODY_BYTES = 1 << 20

    async def _handle_http(self, first: bytes, reader, writer) -> None:
        """Persistent-connection HTTP loop.

        Parity: reference HttpQuery.java:471-530 keeps HTTP/1.1
        connections alive between requests; :432 renders errors on graph
        requests as PNG so browser <img> embeds show the failure. Bounds:
        headers capped at MAX_HEADER_BYTES, bodies at MAX_BODY_BYTES
        (413) — the read path never buffers unbounded client data.
        """
        data = first
        while not self._shutdown.is_set():
            while b"\r\n\r\n" not in data:
                chunk = await reader.read(4096)
                if not chunk:
                    return
                data = data + chunk
                if len(data) > self.MAX_HEADER_BYTES:
                    await self._http_respond(
                        writer, 431, "text/plain",
                        b"Request Header Fields Too Large\n", {}, False)
                    return
            head, _, data = data.partition(b"\r\n\r\n")
            lines = head.decode("latin-1").split("\r\n")
            try:
                method, target, version = lines[0].split(" ", 2)
            except ValueError:
                return
            headers = {}
            for ln in lines[1:]:
                k, _, v = ln.partition(":")
                headers[k.strip().lower()] = v.strip()
            # Drain (and bound) the request body so the next request on
            # the connection parses from a clean boundary.
            try:
                clen = int(headers.get("content-length", "0") or "0")
            except ValueError:
                return
            if clen > self.MAX_BODY_BYTES:
                await self._http_respond(
                    writer, 413, "text/plain",
                    b"Payload Too Large\n", {}, False)
                return
            while len(data) < clen:
                chunk = await reader.read(1 << 16)
                if not chunk:
                    return
                data += chunk
            req_body, data = data[:clen], data[clen:]
            keep = (version.strip().upper() == "HTTP/1.1"
                    and headers.get("connection", "").lower() != "close")

            t0 = time.time()
            try:
                status, ctype, body, extra = await self._route(
                    method, target, req_body)
            except BadRequestError as e:
                status, extra = e.status, {}
                ctype, body = self._error_body(target, str(e))
            except NoSuchUniqueName as e:
                status, extra = 400, {}
                ctype, body = self._error_body(target, str(e))
            except OverloadedError as e:
                # Admission shed: an explicit retry signal, not a
                # failure — 429 (tenant quota) / 503 (load) with an
                # honest Retry-After.
                status, extra = e.status, _retry_after(e.retry_after)
                ctype, body = "text/plain", f"{e}\n".encode()
            except Exception as e:
                self.exceptions_caught += 1
                LOG.exception("HTTP error on %s", target)
                status, extra = 500, {}
                ctype, body = self._error_body(
                    target, f"Internal Server Error: {e}")
            self.http_latency.add((time.time() - t0) * 1000)
            await self._http_respond(writer, status, ctype, body, extra,
                                     keep)
            if not keep:
                return

    def _error_body(self, target: str, message: str) -> tuple[str, bytes]:
        """Error payload; PNG-rendered for graph requests so <img>
        embeds show the failure (reference HttpQuery.java:432)."""
        parsed = urllib.parse.urlsplit(target)
        if parsed.path == "/q" and "png" in urllib.parse.parse_qs(
                parsed.query, keep_blank_values=True):
            try:
                from opentsdb_tpu.graph.plot import render_error_png
                return "image/png", render_error_png(message)
            except Exception:  # fall back to text on render failure
                pass
        return "text/plain", f"{message}\n".encode()

    async def _http_respond(self, writer, status: int, ctype: str,
                            body: bytes, extra: dict,
                            keep: bool) -> None:
        reason = {200: "OK", 304: "Not Modified", 400: "Bad Request",
                  404: "Not Found", 405: "Method Not Allowed",
                  413: "Payload Too Large",
                  429: "Too Many Requests",
                  431: "Request Header Fields Too Large",
                  500: "Internal Server Error",
                  503: "Service Unavailable"}.get(status, "OK")
        if status >= 400:
            _M_HTTP_ERRORS.inc()
        hdrs = [f"HTTP/1.1 {status} {reason}",
                f"Content-Type: {ctype}",
                f"Content-Length: {len(body)}",
                f"Connection: {'keep-alive' if keep else 'close'}"]
        for k, v in extra.items():
            hdrs.append(f"{k}: {v}")
        writer.write(("\r\n".join(hdrs) + "\r\n\r\n").encode() + body)
        await writer.drain()

    async def _route(self, method: str, target: str,
                     body: bytes = b""):
        self.http_rpcs += 1
        parsed = urllib.parse.urlsplit(target)
        path = parsed.path
        params = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
        q = {k: v[-1] for k, v in params.items()}

        if path.startswith("/s/") or path == "/s":
            return self._static_file(path[2:].lstrip("/"))
        route = path.rstrip("/") or "/"
        handler = self.http_routes.get(route)
        if handler is None:
            self.rpcs_unknown += 1
            return 404, "text/plain", b"Page Not Found\n", {}
        req = HttpRequest(method=method, path=path, q=q, params=params,
                          query_string=parsed.query, body=body)
        # Per-endpoint latency timer: tagged by the ROUTE (a bounded
        # label set), never the raw path — /metrics cardinality must
        # not scale with request strings.
        with METRICS.timer("http.handler", {"endpoint": route}).time():
            out = handler(req)
            if asyncio.iscoroutine(out):
                out = await out
        return out

    # -- built-in HTTP handlers ----------------------------------------

    def _http_home(self, req) -> tuple:
        # Serve the query UI (reference: HomePage bootstraps the GWT
        # client, RpcHandler.java:304-317) with a no-cache header so UI
        # updates take effect immediately (an operator staticroot copy
        # would otherwise carry the year-long /s header).
        status, ctype, body, hdrs = self._static_file("index.html")
        if status == 200:
            return (status, ctype, body,
                    dict(hdrs, **{"Cache-Control": "no-cache"}))
        return (200, "text/html; charset=UTF-8",
                self._homepage().encode(), {})

    def _http_aggregators(self, req) -> tuple:
        return (200, "application/json",
                json.dumps(Aggregators.available()).encode(), {})

    def _http_version(self, req) -> tuple:
        if "json" in req.q:
            info = dict(build_data(), start_time=self.start_time)
            return (200, "application/json",
                    json.dumps(info).encode(), {})
        return 200, "text/plain", self._version_text().encode(), {}

    def _http_stats(self, req) -> tuple:
        lines = self._collect_stats()
        if "json" in req.q:
            return (200, "application/json",
                    json.dumps(lines).encode(), {})
        return 200, "text/plain", ("\n".join(lines) + "\n").encode(), {}

    def _http_logs(self, req) -> tuple:
        logbuffer_lines = self.log_ring.formatted()
        if "level" in req.q:
            try:
                logbuffer.set_level(req.q["level"])
            except ValueError as e:
                raise BadRequestError(str(e)) from None
        if "json" in req.q:
            return (200, "application/json",
                    json.dumps(logbuffer_lines).encode(), {})
        return (200, "text/plain",
                ("\n".join(logbuffer_lines) + "\n").encode(), {})

    def _http_fault(self, req) -> tuple:
        """Fault-injection admin (fault/faultpoints.py): integration
        tests arm failpoints on a LIVE tsd process.

            GET /fault                     registry snapshot (JSON)
            GET /fault?arm=site=mode:k=v   arm (spec grammar; crash
                                           modes WILL kill the daemon
                                           at the next hit — the point)
            GET /fault?disarm=site         disarm one site
            GET /fault?clear=1             disarm everything
        """
        from opentsdb_tpu.fault import faultpoints as fp
        q = req.q
        if "arm" in q:
            try:
                fp.install_spec(q["arm"])
            except ValueError as e:
                raise BadRequestError(str(e)) from None
        if "disarm" in q:
            fp.disarm(q["disarm"])
        if "clear" in q:
            fp.clear()
        return (200, "application/json",
                json.dumps(fp.status()).encode(), {})

    def _http_healthz(self, req) -> tuple:
        """Liveness + the replica staleness contract. The router's
        probes key on both the status code and the body: 200/ok keeps
        (or readmits) a replica in rotation, 503/stale ejects it from
        preference while the body still carries the measured lag. In
        cluster mode the body also carries the writer epoch this
        daemon owns (or is fenced behind) — the router's promotion
        manager keys demote-on-return off exactly this."""
        if self.tailer is not None:
            body = self.tailer.health()
        else:
            body = {
                "role": getattr(self.config, "role", "writer"),
                "ok": True,
                "read_only": bool(getattr(self.tsdb.store, "read_only",
                                          False)),
            }
        store = self.tsdb.store
        epoch = getattr(store, "writer_epoch", None)
        if epoch is not None:
            body["writer_epoch"] = int(epoch)
        guard = getattr(store, "epoch_guard", None)
        if guard is not None and guard.fenced:
            # Deposed but alive: reads still serve (coherent, just no
            # longer advancing), every write refuses. The router sees
            # this and issues /demote.
            body["fenced"] = True
            body["fenced_by_epoch"] = guard.fenced_epoch
        body["uptime_s"] = int(time.time()) - self.start_time
        body["inflight_queries"] = self.admission.inflight_queries
        mesh = self._mesh_serving_info()
        if mesh is not None:
            # The router's fan-out weights series ownership by this
            # width (resident hot-set shards): a wide backend owns
            # proportionally more of the series space.
            body["mesh"] = mesh
        status = 200 if body.get("ok") else 503
        return (status, "application/json",
                json.dumps(body).encode(), {})

    def _mesh_serving_info(self) -> dict | None:
        """The serving-mesh block for /healthz and /api/queries: plane
        membership (when --mesh-plane joined one) and the sharded
        resident hot set's live shape. None when neither is on — the
        body stays byte-compatible for non-mesh fleets."""
        from opentsdb_tpu.parallel.fleet import plane_info
        plane = plane_info()
        dw = getattr(self.tsdb, "devwindow", None)
        sharded = dw is not None and hasattr(dw, "shard_of")
        if plane is None and not sharded:
            return None
        out: dict = {"width": dw.n_shards if sharded else 1}
        if plane is not None:
            out["plane"] = dict(plane)
        if sharded:
            out["resident"] = {
                "shards": dw.n_shards,
                "points": dw.resident_points(),
                "generation": dw.generation,
                "reshards": dw.reshard_count,
                "last_reshard_ms": round(dw.reshard_ms, 2),
            }
        return out

    async def _http_mesh_reshard(self, req) -> tuple:
        """Live hot-set resharding admin: ``/api/mesh/reshard?shards=N``
        redistributes the resident device columns over N shards
        (coherent swap — pre-swap queries finish on the complete old
        set; see storage/devshard.py). Runs in the worker pool: the
        drain/rebuild must not block the event loop's ingest."""
        dw = getattr(self.tsdb, "devwindow", None)
        if dw is None or not hasattr(dw, "shard_of"):
            raise BadRequestError(
                "resident hot set is not sharded (start the daemon "
                "with --devwindow-shards or --mesh-plane)")
        try:
            n = int(req.q.get("shards", "0"))
        except ValueError:
            raise BadRequestError(
                f"invalid shards: {req.q.get('shards')}") from None
        if n < 1:
            raise BadRequestError("shards must be >= 1")
        loop = asyncio.get_running_loop()
        try:
            stats = await loop.run_in_executor(
                self._pool, lambda: dw.reshard(n_shards=n))
        except RuntimeError as e:
            return (409, "application/json",
                    json.dumps({"error": str(e)}).encode(), {})
        return (200, "application/json",
                json.dumps(stats).encode(), {})

    # ------------------------------------------------------------------
    # Cluster failover (opentsdb_tpu/cluster/): promote / demote
    # ------------------------------------------------------------------

    async def _http_promote(self, req) -> tuple:
        """Replica → writer takeover. The router's promotion manager
        (cluster/promote.py) calls this when the writer's /healthz has
        been dead past the grace; operators can call it by hand.
        Bumps the persisted epoch (EPOCH.json CAS), reopens the WAL
        tail read-write under a fresh inode, swaps sketches + rollups
        into writer mode, and stops the tailer. Idempotent: asking an
        already-promoted daemon again returns its epoch without
        another bump (a retry after a lost response must not
        re-depose anyone)."""
        path = getattr(self.tsdb, "cluster_epoch_path", None)
        if not path:
            raise BadRequestError(
                "not a cluster member (start the daemon with "
                "--cluster)")
        store = self.tsdb.store
        if not getattr(store, "read_only", False):
            return (200, "application/json", json.dumps({
                "role": "writer", "already_writer": True,
                "epoch": int(getattr(store, "writer_epoch", 0) or 0),
            }).encode(), {})
        expect = None
        if req.q.get("expect"):
            try:
                expect = int(req.q["expect"])
            except ValueError:
                raise BadRequestError("expect must be an integer") \
                    from None
        loop = asyncio.get_running_loop()
        epoch = await loop.run_in_executor(
            self._pool, functools.partial(self._do_promote, path,
                                          expect))
        return (200, "application/json", json.dumps(
            {"role": "writer", "epoch": epoch}).encode(), {})

    def _do_promote(self, path: str, expect: int | None) -> int:
        from opentsdb_tpu.cluster import epoch as _ep
        from opentsdb_tpu.fault.faultpoints import fire as _fault
        # One role transition at a time: the event-loop idempotency
        # check races its own executor dispatch (two retried /promote
        # requests can both pass it), and a second bump after the
        # first promotion landed would instantly fence the freshly
        # promoted writer. Re-check under the lock.
        with self._role_lock:
            if not getattr(self.tsdb.store, "read_only", False):
                return int(getattr(self.tsdb.store, "writer_epoch", 0)
                           or 0)
            # Bump BEFORE touching the tailer: a failed bump (CAS
            # conflict, disk error) must leave the replica exactly as
            # it was — still tailing. The bump is durable; crash
            # after it leaves an epoch with no acting writer, and the
            # next promotion attempt bumps past it.
            owner = (getattr(self.config, "cluster_owner", None)
                     or f"{self.config.bind}:{self.config.port}")
            new = _ep.bump_epoch(path, owner=owner, expect=expect)
            _fault("cluster.promote.bumped", path)
            guard = _ep.EpochGuard(
                path, new,
                interval_s=getattr(self.config,
                                   "epoch_check_interval_s", 0.05))
            tailer, self.tailer = self.tailer, None
            if tailer is not None:
                # The tailer is the replica's only refresh driver; it
                # must stop BEFORE the store flips writable
                # (refresh_replica on a writable store raises —
                # correctly).
                tailer.stop()
            try:
                self.tsdb.promote(new, epoch_guard=guard)
            except BaseException:
                # The store restored itself to a coherent replica; go
                # back to tailing so this daemon keeps its place in
                # rotation while the router tries the next candidate.
                from opentsdb_tpu.serve.tailer import WalTailer
                self.tailer = WalTailer(self.tsdb)
                self.tailer.start()
                raise
            self.config.role = "writer"
            # A promoted replica inherits the spill cadence it was
            # configured with (0 = manual/shutdown checkpoints only,
            # the plain-writer default).
            self.tsdb.compactionq.checkpoint_interval = \
                getattr(self.config, "checkpoint_interval", 0.0) or 0.0
            LOG.warning("promoted to writer at epoch %d", new)
            return new

    async def _http_demote(self, req) -> tuple:
        """Writer → tailing replica (the deposed writer's way back
        into the fleet). The router calls this when a fenced or
        stale-epoch writer reappears; idempotent on replicas."""
        path = getattr(self.tsdb, "cluster_epoch_path", None)
        if not path:
            raise BadRequestError(
                "not a cluster member (start the daemon with "
                "--cluster)")
        if getattr(self.tsdb.store, "read_only", False):
            return (200, "application/json", json.dumps(
                {"role": "replica", "already_replica": True}).encode(),
                {})
        if os.environ.get("TSDB_CLUSTER_BUG") == "split-brain":
            # The servematrix cluster gate: an unfenced zombie ignores
            # the protocol entirely — it neither fences its writes nor
            # complies with demotion. The matrix must catch what such
            # a writer does to the cluster.
            return (500, "text/plain",
                    b"demote sabotaged by TSDB_CLUSTER_BUG\n", {})
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(self._pool, self._do_demote)
        return (200, "application/json", json.dumps(
            {"role": "replica"}).encode(), {})

    def _do_demote(self) -> None:
        with self._role_lock:
            if getattr(self.tsdb.store, "read_only", False):
                return  # a concurrent demote won the race; idempotent
            self.tsdb.demote()
            self.config.role = "replica"
            if not getattr(self.config, "max_staleness_ms", 0.0):
                # The staleness contract defaults ON for replicas (the
                # cmd_tsd replica-role default) — a demoted daemon
                # serves under the same promise as a born replica.
                self.config.max_staleness_ms = 5000.0
            # The tailer becomes the ONLY refresh driver: the
            # compaction timer must stop double-driving
            # refresh_replica (the make_tsdb role=replica exclusion,
            # applied at runtime).
            self.tsdb.compactionq.checkpoint_interval = 0.0
            from opentsdb_tpu.serve.tailer import WalTailer
            self.tailer = WalTailer(self.tsdb)
            self.tailer.start()
            LOG.warning("demoted to tailing replica")

    def _degraded_reason(self, load_degraded: bool) -> str | None:
        """The /q result tag: "stale" when the replica staleness
        contract is violated, "rollup-only" under load shedding's
        degraded step, both comma-joined when both hold. None = full
        service. The stale half is what the bounded-staleness oracle
        checks — and what TSDB_SERVE_BUG=stale-serve sabotages so the
        serve matrix's gate can prove the oracle catches a lying
        replica."""
        reasons = []
        if (self.tailer is not None and self.tailer.stale()
                and _SERVE_BUG != "stale-serve"):
            reasons.append("stale")
        if load_degraded:
            reasons.append("rollup-only")
        return ",".join(reasons) if reasons else None

    def _note_plan(self, plan: str, approx: bool = False) -> None:
        """Bump the bounded per-plan counters: planner-choice labels
        collapse to raw/resident/fused/rollup/approx (rollup
        resolution labels like "1h" fold into "rollup"; a degraded
        rollup answer that carries approx metadata counts BOTH)."""
        if plan.startswith("approx"):
            key = "approx"
        elif plan in ("raw", "resident", "fused", "expert"):
            key = plan
        else:
            key = "rollup"
        self.plan_counts[key] = self.plan_counts.get(key, 0) + 1
        if approx and key != "approx":
            self.plan_counts["approx"] = \
                self.plan_counts.get("approx", 0) + 1

    def _http_queries(self, req) -> tuple:
        """JSON feed behind the /queries browser view: per-plan serve
        counters, the sketch-serving contract counters, rollup tier
        state, fragment-cache hit rates — the query-planner sibling of
        the router's /api/topology."""
        from opentsdb_tpu.rollup.tier import res_label
        tier = getattr(self.tsdb, "rollups", None)
        rollup = None
        if tier is not None:
            rollup = {
                "ready": bool(tier.ready),
                "resolutions": [res_label(r) for r in tier.resolutions],
                "hits": {res_label(r): tier.hits.get(r, 0)
                         for r in tier.resolutions},
                "fallbacks": dict(tier.fallbacks),
                "sketch_alloc": {
                    res_label(r): {"digest_k": a[0], "moment_k": a[1],
                                   "hll_p": a[2]}
                    for r, a in sorted(tier.sketch_alloc.items())},
                "sketch_bytes": dict(tier.sketch_bytes),
                # Checkpoint fold sourcing: windows served from the
                # in-memory delta buffers vs full re-reads of spilled
                # rows (rollup/delta.py). A healthy append-mostly
                # daemon should see delta dominate.
                "folds": {"delta": tier.fold_delta,
                          "full": tier.fold_full},
            }
            if tier.delta is not None:
                rollup["delta"] = tier.delta.stats()
        sketch: dict = {}
        for name, kind, tkey, obj in METRICS._snapshot():
            if not name.startswith("sketch."):
                continue
            label = name[len("sketch."):]
            if tkey:
                label += "{" + ",".join(
                    f"{k}={v}" for k, v in tkey) + "}"
            if kind == "counter":
                sketch[label] = obj.value
            elif kind == "timer":
                sketch[label + ".count"] = obj.count
                sketch[label + ".p95"] = round(
                    obj.digest.percentile(95), 4)
        from opentsdb_tpu.parallel.compile import cache_info
        mesh_ex = getattr(self.executor, "mesh", None)
        expert_counts = {"serve": 0, "decline": 0}
        for name, kind, tkey, obj in METRICS._snapshot():
            if name == "mesh.expert.serve":
                expert_counts["serve"] += obj.value
            elif name == "mesh.expert.decline":
                expert_counts["decline"] += obj.value
        # The fused-on-compressed-blocks coverage line: what fraction
        # of fused-eligible batteries actually served fused, why the
        # rest declined, and how warm the device block cache is.
        fused = {"attempt": 0, "served": 0, "declines": {},
                 "devcache": {"hit": 0, "miss": 0, "evict": 0}}
        for name, kind, tkey, obj in METRICS._snapshot():
            if name == "compress.fused.attempt":
                fused["attempt"] += obj.value
            elif name == "compress.fused.served":
                fused["served"] += obj.value
            elif name == "compress.fused.decline":
                reason = dict(tkey).get("reason", "?")
                fused["declines"][reason] = \
                    fused["declines"].get(reason, 0) + obj.value
            elif name.startswith("compress.devcache."):
                fused["devcache"][name.rsplit(".", 1)[1]] = obj.value
        fused["coverage"] = (fused["served"] / fused["attempt"]
                             if fused["attempt"] else 0.0)
        # The ingest fast path (wire decode + WAL group commit):
        # batches-per-fsync is the coalescing win, wait_ms p95 the
        # latency each acked batch paid for its covering fsync.
        ingest = {"group": {"batches": 0, "points": 0, "fsyncs": 0,
                            "waits": 0, "wait_ms_p95": 0.0},
                  "parse": {"count": 0, "p95_ms": 0.0}}
        for name, kind, tkey, obj in METRICS._snapshot():
            if name == "wal.group.batches":
                ingest["group"]["batches"] += obj.value
            elif name == "wal.group.points":
                ingest["group"]["points"] += obj.value
            elif name == "wal.group.fsyncs":
                ingest["group"]["fsyncs"] += obj.value
            elif name == "wal.group.wait_ms" and kind == "timer":
                ingest["group"]["waits"] += obj.count
                ingest["group"]["wait_ms_p95"] = round(
                    obj.digest.percentile(95), 4)
            elif name == "ingest.parse" and kind == "timer":
                ingest["parse"]["count"] += obj.count
                ingest["parse"]["p95_ms"] = round(
                    obj.digest.percentile(95), 4)
        g = ingest["group"]
        g["batches_per_fsync"] = (g["batches"] / g["fsyncs"]
                                  if g["fsyncs"] else 0.0)
        body = {
            "uptime_s": int(time.time()) - self.start_time,
            "plans": dict(self.plan_counts),
            "fused": fused,
            "ingest": ingest,
            "sketch": sketch,
            "rollup": rollup,
            # The mesh execution plane's compile-cache line: devices
            # in the configured mesh, plan-cache size/hit/miss (a
            # steady dashboard should stop missing after warmup), and
            # the expert serve/decline counters.
            "mesh": {
                "devices": (int(mesh_ex.devices.size)
                            if mesh_ex is not None else 1),
                "expert_enabled": bool(self.expert_enabled),
                "compile_cache": cache_info(),
                "expert": expert_counts,
                # Serving-mesh shape (None outside --mesh-plane /
                # --devwindow-shards): plane membership + the sharded
                # resident hot set's live width/points/reshard stats.
                "serving": self._mesh_serving_info(),
            },
            "qcache": {"hit": self.executor.qcache_hits,
                       "miss": self.executor.qcache_misses,
                       "bypass": self.executor.qcache_bypasses},
            "admission": {
                "inflight": self.admission.inflight_queries,
                "degraded": self.admission.query_degraded,
                "shed_load": self.admission.query_shed_load,
            },
        }
        return (200, "application/json", json.dumps(body).encode(), {})

    def _http_queries_page(self, req) -> tuple:
        return (200, "text/html; charset=UTF-8",
                _QUERIES_HTML.encode(), {"Cache-Control": "no-cache"})

    # ------------------------------------------------------------------
    # Tenant cardinality control plane (opentsdb_tpu/tenant/)
    # ------------------------------------------------------------------

    def _http_tenants(self, req) -> tuple:
        """JSON feed behind the /tenants view: per-tenant series
        cardinality (exact or HLL tier, error declared), the limit
        governing each tenant, refusal counters, and the heavy-hitter
        summaries (top series by points, top metric prefixes by new
        series). Replicas and accounting-off daemons answer with
        enabled: false instead of 404 — the fleet shape is uniform."""
        acct = getattr(self.tsdb, "tenants", None)
        if acct is None:
            body = {"enabled": False,
                    "role": getattr(self.config, "role", "writer")}
            return (200, "application/json",
                    json.dumps(body).encode(), {})
        body = acct.snapshot_info(
            getattr(self.tsdb, "tenant_limits", None))
        body["enabled"] = True
        admission = self.admission
        body["admission"] = {
            "tenants": max(len(admission._ingest_buckets),
                           len(admission._query_buckets)),
            "evicted": admission.tenants_evicted,
            "collapsed": admission.tenants_collapsed,
        }
        return (200, "application/json", json.dumps(body).encode(), {})

    def _http_tenants_page(self, req) -> tuple:
        return (200, "text/html; charset=UTF-8",
                _TENANTS_HTML.encode(), {"Cache-Control": "no-cache"})

    async def _http_put(self, req) -> tuple:
        """HTTP ingest: a POST body of telnet-format ``put`` lines
        (no leading "put " required per line — both spellings
        accepted) or a JSON datapoint object/array (the reference
        ``/api/put`` shape), attributed to ``?tenant=``. Both bodies
        decode into the same columnar batch. The HTTP face of the
        tenant-limit contract: when every line was refused by the
        cardinality limiter the answer is 429 naming the limit;
        partial refusals report per-series errors in a 200 body so
        the caller can split permanent refusals from parse noise."""
        from opentsdb_tpu.server import wire
        if req.method != "POST":
            raise BadRequestError("POST a body of put lines", 405)
        if not req.body.strip():
            raise BadRequestError("empty body")
        tenant = req.q.get("tenant", "default")
        raw = req.body
        loop = asyncio.get_running_loop()
        # JSON bodies are unambiguous: no telnet put line can start
        # with '{' or '[' (the metric charset forbids both).
        if raw.lstrip()[:1] in (b"{", b"["):
            try:
                obj = json.loads(raw)
            except ValueError as e:
                raise BadRequestError(f"invalid json: {e}")
            try:
                batch = await loop.run_in_executor(
                    self._pool, wire.decode_json_puts, obj)
            except ValueError as e:
                raise BadRequestError(str(e))
        else:
            if not raw.endswith(b"\n"):
                raw += b"\n"
            # Accept bare "metric ts value tags" lines by prefixing
            # the telnet verb; lines already carrying it pass through.
            lines = []
            for ln in raw.split(b"\n"):
                if ln and not ln.startswith(b"put "):
                    ln = b"put " + ln
                lines.append(ln)
            raw = b"\n".join(lines)
            batch = await loop.run_in_executor(
                self._pool, wire.decode_puts, raw)
        npts = len(batch.sid)
        wait = self.admission.admit_ingest(npts, tenant) if npts \
            else 0.0
        if wait > 0:
            raise OverloadedError(
                f"over ingest quota for tenant {tenant!r}", wait,
                status=429)
        try:
            n, series_errors = await loop.run_in_executor(
                self._pool,
                functools.partial(wire.ingest_batch, self.tsdb, batch,
                                  tenant=tenant))
        finally:
            if npts:
                self.admission.ingest_done(npts)
        self.requests_put += n
        errors = list(batch.errors) + series_errors
        refused = [e for e in series_errors if "[tenant-limit]" in e]
        body = {"points": n, "errors": errors,
                "tenant": tenant,
                "refused_series": len(refused)}
        if refused and n == 0:
            # Everything the caller sent was a refused NEW series:
            # the declared 429 face, naming the limit — and no
            # Retry-After, because a retry cannot succeed until the
            # limit moves (this is not a throttle).
            limits = getattr(self.tsdb, "tenant_limits", None)
            body["error"] = refused[0]
            body["limit"] = (limits.limit_for(tenant)
                             if limits is not None else None)
            return (429, "application/json",
                    json.dumps(body).encode(), {})
        return 200, "application/json", json.dumps(body).encode(), {}

    def _http_metrics(self, req) -> tuple:
        """Prometheus text exposition: the metrics registry (typed —
        counters, gauges, timer summaries) merged with the classic
        /stats lines (untyped gauges, deduplicated against the
        registry's families) so one scrape covers both worlds."""
        body = METRICS.prometheus_text(extra_lines=self._collect_stats())
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                body.encode(), {})

    def _http_traces(self, req) -> tuple:
        """The trace ring: the last Config.trace_ring traced queries
        (explicit ?trace=1 requests + every slow query), newest last.
        ``?slow=1`` filters to slow-flagged records."""
        records = self.trace_ring.snapshot()
        if "slow" in req.q and req.q["slow"] not in ("", "0"):
            records = [r for r in records if r.get("slow")]
        return 200, "application/json", json.dumps(records).encode(), {}

    def _http_dropcaches(self, req) -> tuple:
        self.tsdb.drop_caches()
        return 200, "text/plain", b"Caches dropped.\n", {}

    def _http_diediedie(self, req) -> tuple:
        self.request_shutdown()
        return (200, "text/html; charset=UTF-8",
                b"Cleaning up and exiting now.\n", {})

    def _http_favicon(self, req) -> tuple:
        return 404, "text/plain", b"", {}

    def _suggest(self, q) -> tuple:
        kind = q.get("type", "metrics")
        prefix = q.get("q", "")
        try:
            limit = int(q.get("max", "25"))
        except ValueError:
            raise BadRequestError("invalid 'max' parameter") from None
        if kind == "metrics":
            names = self.tsdb.metrics.suggest(prefix, limit)
        elif kind == "tagk":
            names = self.tsdb.tagk.suggest(prefix, limit)
        elif kind == "tagv":
            names = self.tsdb.tagv.suggest(prefix, limit)
        else:
            raise BadRequestError(f"Invalid 'type' parameter: {kind}")
        return 200, "application/json", json.dumps(names).encode(), {}

    # -- /q ------------------------------------------------------------

    async def _query(self, q, query_string: str, params) -> tuple:
        if "start" not in q:
            raise BadRequestError("Missing parameter: start")
        tz = q.get("tz")
        now = int(time.time())
        start = timeparse.parse_date(q["start"], tz=tz, now=now)
        end_param = q.get("end")
        end = timeparse.parse_date(end_param, tz=tz, now=now) \
            if end_param else now
        ms = params.get("m", [])
        if not ms:
            raise BadRequestError("Missing parameter: m")

        # Admission (serve/admission.py): a dry per-tenant bucket is
        # 429, the ladder's top is 503 — both via OverloadedError so
        # the Retry-After reaches the wire. DEGRADE takes a slot like
        # OK (the work still runs, just cheaper), released in the
        # finally below. Only VALID requests consume slots: the
        # parameter checks above stay outside.
        from opentsdb_tpu.serve import admission as _adm
        verdict, retry = self.admission.admit_query(
            q.get("tenant", "default"))
        if verdict == _adm.SHED_QUOTA:
            raise OverloadedError(
                f"query quota exceeded for tenant "
                f"{q.get('tenant', 'default')!r}", retry, status=429)
        if verdict == _adm.SHED_LOAD:
            raise OverloadedError(
                "shedding load: too many queries in flight", retry,
                status=503)
        # ?degrade=rollup-only: an overloaded ROUTER asking for the
        # cheap path on this hop — honor it exactly like the local
        # ladder's degraded step (trace stripped, rollup-only, tagged).
        degrade = (verdict == _adm.DEGRADE
                   or q.get("degrade") == "rollup-only")
        try:
            return await self._query_admitted(q, query_string, params,
                                              ms, start, end, degrade)
        finally:
            self.admission.query_done()

    async def _query_admitted(self, q, query_string: str, params, ms,
                              start: int, end: int,
                              degrade: bool) -> tuple:
        # Tracing: requested explicitly (?trace=1) or implied for
        # every query when a slow-query threshold is configured (the
        # span tree is what makes the slow-query record debuggable).
        # The per-hook cost is one global-int check when off and a
        # perf_counter pair per STAGE when on — never per point.
        # The degraded ladder step sheds trace work FIRST: span
        # bookkeeping is pure overhead when the goal is staying up.
        want_trace = (q.get("trace", "0") not in ("", "0")
                      and not degrade)
        slow_ms = float(getattr(self.config, "slow_query_ms", 0) or 0)
        # Ambient 1-in-N trace sampling (Config.trace_sample_n): every
        # Nth query is traced into the ring even when nobody asked and
        # nothing is slow, so the traces BETWEEN incidents exist when
        # a slow-query record needs a baseline to compare against.
        # Sampled traces keep normal caching (a disk-cache hit simply
        # isn't traced — the baseline is of executed queries).
        sample_n = int(getattr(self.config, "trace_sample_n", 0) or 0)
        sampled = False
        if sample_n > 0 and not degrade and not want_trace:
            self._trace_sample_seq += 1
            sampled = self._trace_sample_seq % sample_n == 0
        do_trace = want_trace or sampled or (slow_ms > 0
                                             and not degrade)
        # The result tag for anything less than full service ("stale",
        # "rollup-only", or both): evaluated once per request, echoed
        # per-result in JSON and as X-Tsd-Degraded so the router can
        # propagate it without parsing bodies. Degraded answers bypass
        # the disk cache both ways — caching one would serve it after
        # recovery, and a cached full answer carries no tag.
        degraded = self._degraded_reason(degrade)
        # Approximate serving opt-in (sketch/serving.py): ``approx=1``
        # allows sketch-served percentile downsamples at any reported
        # bound; ``max_error=X`` (relative half-width) implies the
        # opt-in AND caps it — a sketch answer whose bound exceeds X
        # falls back to the exact path. The ladder's degraded step
        # implies approx for percentile queries (bounded-error
        # degradation) under Config.degrade_max_error.
        from opentsdb_tpu.sketch.serving import ApproxSpec
        max_error = _parse_max_error(q)
        approx_on = (q.get("approx", "0") not in ("", "0")
                     or max_error is not None)
        if degrade and max_error is None:
            cfg_budget = float(getattr(self.config,
                                       "degrade_max_error", 0) or 0)
            max_error = cfg_budget if cfg_budget > 0 else None
        aspec = ApproxSpec(approx_on, max_error)
        # An explicitly traced request bypasses the /q disk cache both
        # ways: a cached body carries no trace, and a trace of a disk
        # read would claim the query cost nothing. Approx opt-in does
        # NOT bypass: the cache key is the md5 of the full query string
        # (approx=1/max_error included), so an exact caller can never
        # land on an approx slot, and X-Tsd-Approx survives hits via
        # the .meta sidecar like the drag-zoom headers.
        cache_path = (None if want_trace or degraded
                      else self._cache_path(query_string, q))
        now = int(time.time())
        if cache_path and self._cache_fresh(cache_path, q, end, now):
            with open(cache_path, "rb") as f:
                body = f.read()
            # A PNG under 21 bytes (minimum possible PNG) cannot be
            # valid, and a 0-byte .json cannot either (an empty JSON
            # result serializes as b"[]") — regenerate instead of
            # serving garbage (reference GraphHandler.isDiskCacheHit
            # :367-374; our tmp+rename writes make this
            # near-impossible, but an operator touching files in the
            # cachedir shouldn't wedge a graph). Zero-byte .txt bodies
            # are NOT rejected: an empty ascii result is the
            # negative-cache hit — a query known to plot 0 points is
            # re-served from disk without re-running the executor
            # (reference :399-419).
            corrupt = ((cache_path.endswith(".png") and len(body) < 21)
                       or (cache_path.endswith(".json")
                           and len(body) == 0))
            if not corrupt:
                self.cache_hits += 1
                ctype = ("image/png" if cache_path.endswith(".png")
                         else "text/plain" if cache_path.endswith(".txt")
                         else "application/json")
                extra = {}
                try:  # drag-zoom headers survive cache hits via a sidecar
                    with open(cache_path + ".meta") as f:
                        extra = json.load(f)
                except (OSError, ValueError):
                    pass
                return 200, ctype, body, extra
        self.cache_misses += 1

        loop = asyncio.get_running_loop()
        results = []
        # Per-metric render options: o= params pair up positionally with
        # m= params (reference GraphHandler.doGraph :155-187).
        os_ = params.get("o", [])
        result_opts: list[str] = []
        result_plans: list[str] = []
        result_cached: list[bool] = []
        result_traces: list[dict | None] = []
        result_approx: list[dict | None] = []
        # Expert-parallel batch serving (parallel/expert.py, behind
        # Config.expert_parallel + a mesh): a mixed multi-sub-query
        # dashboard packs into expert buckets and runs in ONE mesh
        # dispatch. Attempted only on the full-service path (tracing,
        # the degrade ladder, and approx contracts keep their serial
        # semantics); a decline is DECLARED — per-result
        # plan: "expert-decline" + the mesh.expert.decline counter —
        # and the batch serves serially, answers unchanged.
        expert_label = None
        expert_specs: list | None = None
        if (self.expert_enabled and len(ms) >= 2 and not do_trace
                and not degrade and not aspec.enabled):
            specs = []
            for m in ms:
                parsed = parse_m(m)
                specs.append(QuerySpec(
                    metric=parsed.metric, tags=parsed.tags,
                    aggregator=parsed.aggregator, rate=parsed.rate,
                    downsample=parsed.downsample,
                    counter=parsed.counter,
                    counter_max=parsed.counter_max,
                    reset_value=parsed.reset_value))
            per_spec, reason = await loop.run_in_executor(
                self._pool,
                functools.partial(self.executor.run_expert_batch,
                                  specs, start, end))
            if per_spec is not None:
                # Counters bump PER SUB-QUERY, the serial loop's unit —
                # the /queries plans table must not mix units across a
                # mesh rollout.
                METRICS.counter("mesh.expert.serve").inc(len(ms))
                for _ in ms:
                    self._note_plan("expert")
                expert_label = "expert"
                for mi, rs in enumerate(per_spec):
                    results.extend(rs)
                    result_opts.extend(
                        [os_[mi] if mi < len(os_) else ""] * len(rs))
                    result_plans.extend(["expert"] * len(rs))
                    result_cached.extend([False] * len(rs))
                    result_traces.extend([None] * len(rs))
                    result_approx.extend([None] * len(rs))
                ms = ()
            else:
                METRICS.counter("mesh.expert.decline",
                                {"reason": reason}).inc(len(ms))
                self.plan_counts["expert-decline"] = \
                    self.plan_counts.get("expert-decline", 0) + len(ms)
                expert_label = "expert-decline"
                # The serial fallback reuses the parsed specs — a
                # declined batch must not pay the parse twice.
                expert_specs = specs
        for mi, m in enumerate(ms):
            if expert_specs is not None:
                spec = expert_specs[mi]
            else:
                parsed = parse_m(m)
                spec = QuerySpec(
                    metric=parsed.metric, tags=parsed.tags,
                    aggregator=parsed.aggregator, rate=parsed.rate,
                    downsample=parsed.downsample,
                    counter=parsed.counter,
                    counter_max=parsed.counter_max,
                    reset_value=parsed.reset_value)
            # Planner choice for this sub-query ("raw", "resident", or
            # a rollup resolution label) — surfaced in JSON metadata.
            # Returned with the results: reading it back off the shared
            # executor after the pool hop could pick up a CONCURRENT
            # request's label.
            # trace_parent: the router's fan-out id — hop traces on
            # this replica carry the SAME trace_id as the router's
            # assembled tree, so /api/traces correlates across
            # processes.
            trace = (obs_trace.Trace(
                m, trace_id=q.get("trace_parent") or None)
                if do_trace else None)
            rs, plan, cached, ainfo = await loop.run_in_executor(
                self._pool,
                functools.partial(self.executor.run_approx,
                                  spec, start, end, trace,
                                  rollup_only=degrade, approx=aspec))
            ajson = (ainfo.as_json() if hasattr(ainfo, "as_json")
                     else ainfo)
            self._note_plan(plan, approx=ajson is not None)
            tdict = None
            if trace is not None:
                rec = make_record(
                    m, trace, plan, cached, slow_ms,
                    getattr(self.tsdb.store, "shard_count", 1) or 1,
                    bool(getattr(self.tsdb.store, "read_only", False)))
                tdict = rec["trace"]
                # The ring holds what an operator would want to SEE at
                # /api/traces: every explicit trace, every slow query,
                # and the 1-in-N ambient samples (flagged, so ?slow=1
                # still filters to incidents). Threshold-only tracing
                # of fast queries stays out — it would flush the ring
                # with noise between incidents.
                if sampled:
                    rec["sampled"] = True
                if want_trace or sampled or rec["slow"]:
                    self.trace_ring.add(rec)
                if rec["slow"]:
                    log_slow(rec)
            results.extend(rs)
            result_opts.extend([os_[mi] if mi < len(os_) else ""] * len(rs))
            result_plans.extend([plan] * len(rs))
            result_cached.extend([cached] * len(rs))
            result_traces.extend([tdict] * len(rs))
            result_approx.extend([ajson] * len(rs))

        extra: dict = {}
        if degraded:
            extra["X-Tsd-Degraded"] = degraded
        approx_served = [a for a in result_approx if a]
        if approx_served:
            # Declared approximation, header form (the router
            # propagates it like X-Tsd-Degraded): the kinds involved
            # plus the worst reported relative bound (when numeric).
            kinds = sorted({a.get("kind", "?") for a in approx_served})
            rels = [a.get("rel_error") for a in approx_served
                    if isinstance(a.get("rel_error"), (int, float))]
            tagv = ",".join(kinds)
            if rels:
                tagv += f";rel_error={max(rels):.6g}"
            extra["X-Tsd-Approx"] = tagv
        if "ascii" in q:
            body = self._ascii_output(results).encode()
            ctype = "text/plain"
        elif "json" in q:
            body = json.dumps(
                self._json_output(
                    results, result_plans, result_cached,
                    result_traces if want_trace else None,
                    degraded=degraded,
                    approx=result_approx,
                    expert=expert_label)).encode()
            ctype = "application/json"
        else:
            t0 = time.time()
            body, extra = await loop.run_in_executor(
                self._pool, self._render_png, results, start, end, q,
                result_opts)
            self.graph_latency.add((time.time() - t0) * 1000)
            ctype = "image/png"
        if cache_path:
            tmp = cache_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(body)
            os.replace(tmp, cache_path)
            if extra:
                with open(cache_path + ".meta.tmp", "w") as f:
                    json.dump(extra, f)
                os.replace(cache_path + ".meta.tmp", cache_path + ".meta")
        return 200, ctype, body, extra

    def _cache_path(self, query_string: str, q) -> str | None:
        if self.config.cachedir is None or "nocache" in q:
            return None
        suffix = (".txt" if "ascii" in q
                  else ".json" if "json" in q else ".png")
        h = hashlib.md5(query_string.encode()).hexdigest()
        return os.path.join(self.config.cachedir, h + suffix)

    def _cache_fresh(self, path: str, q, end: int, now: int) -> bool:
        """Staleness rules following reference computeMaxAge (:223-244):
        queries ending >1d in the past cache long; recent/relative
        queries cache briefly."""
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return False
        if end < now - 86400:
            max_age = 86400
        elif timeparse.is_relative_date(q.get("end")):
            max_age = 60
        else:
            max_age = 300
        if (self.tailer is not None
                and getattr(self.config, "max_staleness_ms", 0) > 0):
            # Staleness-contract replicas: a disk-cache hit adds its
            # age to the answer's staleness, so cap it at the contract
            # bound — the cache can never make a fresh replica serve
            # an answer older than it promises.
            max_age = min(max_age,
                          self.config.max_staleness_ms / 1000.0)
        return (now - mtime) < max_age

    @staticmethod
    def _fmt_value(v: float) -> str:
        return str(int(v)) if float(v).is_integer() else repr(float(v))

    def _ascii_output(self, results) -> str:
        """One "metric timestamp value tags" line per point (reference
        GraphHandler.respondAsciiQuery :770-818) — re-importable."""
        out = []
        for r in results:
            tag_str = " ".join(
                f"{k}={v}" for k, v in sorted(r.tags.items()))
            for ts, v in zip(r.timestamps, r.values):
                line = f"{r.metric} {int(ts)} {self._fmt_value(v)}"
                out.append(line + (" " + tag_str if tag_str else ""))
        return "\n".join(out) + ("\n" if out else "")

    def _json_output(self, results, plans=None, cached=None,
                     traces=None, degraded=None, approx=None,
                     expert=None):
        out = [{
            "metric": r.metric,
            "tags": r.tags,
            "aggregateTags": r.aggregated_tags,
            "rollup": (plans[i] if plans and i < len(plans) else "raw"),
            # Fragment-cache provenance: True iff this sub-query's
            # whole range served from warm decoded fragments.
            "cached": bool(cached[i]) if cached and i < len(cached)
            else False,
            "dps": {str(int(t)): float(v)
                    for t, v in zip(r.timestamps, r.values)},
        } for i, r in enumerate(results)]
        if expert:
            # Expert-path provenance, DECLARED either way: "expert"
            # when the batch served through the mesh's expert buckets,
            # "expert-decline" when it was eligible for the attempt
            # but fell off the path (ragged shapes, rate, no-lerp
            # aggs) and served serially — the TSINT fused-decline
            # discipline: falling back is fine, silently is not.
            for ent in out:
                ent["plan"] = expert
        if degraded:
            # Anything less than full service is DECLARED per result:
            # "stale" (replica lag beyond the contract) and/or
            # "rollup-only" (load shedding omitted raw stitching).
            for ent in out:
                ent["degraded"] = degraded
        if approx:
            # The error contract: a sketch-served answer carries its
            # kind + reported bound per result ("approx": {"kind":
            # "tdigest"|"moment"|"rollup-stale", "error": ...}).
            for i, ent in enumerate(out):
                if i < len(approx) and approx[i]:
                    ent["approx"] = approx[i]
        if traces is not None:
            # ?trace=1 only: the per-sub-query span tree, inline.
            for i, ent in enumerate(out):
                if i < len(traces) and traces[i] is not None:
                    ent["trace"] = traces[i]
        return out

    def _render_png(self, results, start, end, q,
                    result_opts=None) -> tuple[bytes, dict]:
        plot = Plot(start, end)
        if "wxh" in q:
            w, _, h = q["wxh"].partition("x")
            try:
                plot.set_dimensions(int(w), int(h))
            except ValueError:
                raise BadRequestError(
                    f"invalid wxh parameter: {q['wxh']}") from None
        plot.set_params({k: v for k, v in q.items() if k in (
            "title", "ylabel", "yrange", "ylog", "key", "nokey",
            "bgcolor", "fgcolor", "y2label", "y2range", "y2log",
            "smooth")})
        for i, r in enumerate(results):
            label = r.metric
            if r.tags:
                label += "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(r.tags.items())) + "}"
            plot.add(label, r.timestamps, r.values,
                     result_opts[i] if result_opts else "")
        body = plot.render()
        # Pixel->time mapping headers for the web UI's drag-zoom: the
        # axes bbox in PNG pixels plus the plotted time range. (The GWT
        # client hardcodes gnuplot's margins for this; we report the
        # real bbox instead.)
        hdrs = {"X-Time-Range": f"{int(start)},{int(end)}"}
        if plot.plot_area is not None:
            hdrs["X-Plot-Area"] = ",".join(map(str, plot.plot_area))
        return body, hdrs

    async def _distinct(self, q) -> tuple:
        """Cardinality extension: distinct values of one tag key.

        Without ``start`` (or with ``stream`` set), answered from the
        streaming per-(metric, tagk) HLL registers updated at ingest —
        all-time, no storage rescan, staleness bounded by the sketch
        flush threshold. With a time range and no tag filter, the
        rollup tier serves an exact count from record presence
        (O(windows); executor.sketch_distinct falls back to the exact
        scan when the tier can't cover the range); with a tag filter
        the scan-based path runs.
        """
        for req in ("metric", "tagk"):
            if req not in q:
                raise BadRequestError(f"Missing parameter: {req}")
        loop = asyncio.get_running_loop()
        if "stream" in q or "start" not in q:
            if "end" in q and "stream" not in q:
                # Mirror /sketch: end= alone must not silently answer
                # the all-time streaming estimate for a ranged intent.
                raise BadRequestError(
                    "distinct range needs start= (end= alone would "
                    "silently answer all-time)")
            n = await loop.run_in_executor(
                self._pool, self.executor.sketch_distinct, q["metric"],
                q["tagk"])
            if n is None:
                raise BadRequestError(
                    f"no streaming sketch state for metric {q['metric']}"
                    f" / tagk {q['tagk']} (pass start= for a scan)")
            # The streaming estimate is an HLL — declare it under the
            # error contract like every other approximate answer.
            from opentsdb_tpu.sketch.bounds import hll_error
            err = hll_error(getattr(self.config, "sketch_hll_p", 12), n)
            body = json.dumps({
                "metric": q["metric"], "tagk": q["tagk"], "distinct": n,
                "source": "stream",
                "approx": {"kind": "hll", "error": err}}).encode()
            return (200, "application/json", body,
                    {"X-Tsd-Approx": f"hll;error={err:.6g}"})
        now = int(time.time())
        start = timeparse.parse_date(q["start"], now=now)
        end = timeparse.parse_date(q["end"], now=now) if "end" in q else now
        tag_map: dict[str, str] = {}
        if "tags" in q and q["tags"]:
            for t in q["tags"].split(","):
                tags_mod.parse(tag_map, t)
        if not tag_map:
            # What actually answered ("rollup" or the exact-scan
            # fallback), returned alongside the count so concurrent
            # /distinct requests can't mislabel each other.
            n, source = await loop.run_in_executor(
                self._pool, self.executor.sketch_distinct_with_source,
                q["metric"], q["tagk"], start, end)
        else:
            n = await loop.run_in_executor(
                self._pool, self.executor.distinct_tagv, q["metric"],
                tag_map, q["tagk"], start, end)
            source = "scan"
        body = json.dumps({"metric": q["metric"], "tagk": q["tagk"],
                           "distinct": n, "source": source}).encode()
        return 200, "application/json", body, {}

    async def _sketch(self, q) -> tuple:
        """Streaming-quantile extension: all-time percentiles of the
        matching series' merged t-digests, answered from device-resident
        sketch state with no storage rescan (the Histogram.java
        streaming-stats replacement). Params: ``m=metric{tag=v,...}``
        (no aggregator prefix) and ``q=p50,p99`` (or 0.5,0.99).
        """
        if "m" not in q:
            raise BadRequestError("Missing parameter: m")
        expr = q["m"]
        tag_map: dict[str, str] = {}
        try:
            metric = tags_mod.parse_with_metric(expr, tag_map)
        except ValueError as e:
            raise BadRequestError(str(e)) from None
        qs = []
        for part in q.get("q", "p50,p95,p99").split(","):
            part = part.strip()
            try:
                if part.startswith("p") and part[1:].isdigit():
                    d = part[1:]
                    # p5 -> 0.05, p99 -> 0.99 (whole percent); three or
                    # more digits use the aggregator-registry spelling
                    # where digits follow the decimal point: p999 ->
                    # 0.999 (so "p100" is 0.100, not the maximum — ask
                    # for q=1.0 explicitly).
                    qs.append(int(d) / 100 if len(d) <= 2
                              else int(d) / 10 ** len(d))
                else:
                    qs.append(float(part))
            except ValueError:
                raise BadRequestError(
                    f"bad quantile: {part}") from None
            if not 0.0 <= qs[-1] <= 1.0:
                raise BadRequestError(f"quantile out of range: {part}")
        # Optional time range: served from the rollup tier's per-window
        # digest columns (exact raw fallback) instead of the all-time
        # streaming digests.
        start = end = None
        if "start" in q:
            now = int(time.time())
            start = timeparse.parse_date(q["start"], now=now)
            end = (timeparse.parse_date(q["end"], now=now)
                   if "end" in q else now)
        elif "end" in q:
            raise BadRequestError(
                "sketch range needs start= (end= alone would silently "
                "answer all-time)")
        max_error = _parse_max_error(q)
        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(
            self._pool, self.executor.sketch_quantiles, metric, tag_map,
            qs, start, end, max_error)
        hdrs = {}
        ap = out.get("approx") if isinstance(out, dict) else None
        if ap:
            hdrs["X-Tsd-Approx"] = (
                f"{ap.get('kind', '?')}"
                f";rel_error={ap.get('rel_error', 0):.6g}")
        return 200, "application/json", json.dumps(out).encode(), hdrs

    async def _forecast(self, q, params) -> tuple:
        """Model extension: Holt-Winters / EWMA forecasts + anomaly
        bands over a query's result series (no reference analog — the
        predictive layer on top of the /q pipeline). Params: start, end,
        m= (must include a downsample to define the model's bucket
        grid), horizon (future buckets, default 10), season (buckets,
        default 0), alpha/beta/gamma, nsigma (default 3).
        """
        import numpy as np

        if "start" not in q:
            raise BadRequestError("Missing parameter: start")
        now = int(time.time())
        tz = q.get("tz")
        start = timeparse.parse_date(q["start"], tz=tz, now=now)
        end = timeparse.parse_date(q["end"], tz=tz, now=now) \
            if q.get("end") else now
        ms = params.get("m", [])
        if not ms:
            raise BadRequestError("Missing parameter: m")

        def num(name, default, lo, hi, as_int=False):
            try:
                v = float(q.get(name, default))
                if as_int:
                    v = int(v)
            except (ValueError, OverflowError):
                raise BadRequestError(
                    f"invalid '{name}' parameter") from None
            if not (lo <= v <= hi):
                raise BadRequestError(
                    f"'{name}' out of range [{lo}, {hi}]")
            return v

        # season/horizon bound both memory (they size device arrays) and
        # XLA recompiles (they're static shapes).
        horizon = num("horizon", 10, 1, 10000, as_int=True)
        season = num("season", 0, 0, 10000, as_int=True)
        alpha = num("alpha", 0.3, 0.0, 1.0)
        beta = num("beta", 0.1, 0.0, 1.0)
        gamma = num("gamma", 0.1, 0.0, 1.0)
        nsigma = num("nsigma", 3.0, 0.1, 1000.0)
        model = q.get("model", "hw")
        if model not in ("hw", "ewma"):
            raise BadRequestError(f"unknown model: {model}")

        loop = asyncio.get_running_loop()
        results = []
        interval = None
        for m in ms:
            parsed = parse_m(m)
            if not parsed.downsample:
                raise BadRequestError(
                    "forecast queries need a downsample interval "
                    "(e.g. m=sum:5m-avg:metric) to define the model grid")
            if interval is None:
                interval = parsed.downsample[0]
            elif interval != parsed.downsample[0]:
                raise BadRequestError(
                    "all m= specs must share one downsample interval")
            spec = QuerySpec(
                metric=parsed.metric, tags=parsed.tags,
                aggregator=parsed.aggregator, rate=parsed.rate,
                downsample=parsed.downsample, counter=parsed.counter,
                counter_max=parsed.counter_max,
                reset_value=parsed.reset_value)
            rs = await loop.run_in_executor(
                self._pool, self.executor.run, spec, start, end)
            results.extend(rs)

        def compute():
            from opentsdb_tpu.models import (anomaly_bands, ewma,
                                             hw_forecast)
            from opentsdb_tpu.query.executor import _pad_size

            grid0 = start - start % interval
            T = max((end - grid0) // interval + 1, 1)
            S = max(len(results), 1)
            # Pad the model shapes to powers of two: masked tail buckets
            # and empty padded series carry the scan state through
            # unchanged, so results are identical — but every distinct
            # query span stops triggering an XLA recompile of the
            # smoothing scan (the same _pad_size discipline as /q).
            Tp, Sp = _pad_size(T), _pad_size(S)
            vals = np.zeros((Sp, Tp), np.float32)
            mask = np.zeros((Sp, Tp), bool)
            for i, r in enumerate(results):
                idx = ((np.asarray(r.timestamps) - grid0) //
                       interval).astype(int)
                ok = (idx >= 0) & (idx < T)
                vals[i, idx[ok]] = np.asarray(r.values)[ok]
                mask[i, idx[ok]] = True
            if model == "ewma":
                fitted = np.asarray(ewma(vals, mask, alpha))[:S, :T]
                level = fitted[:, -1]
                fc = np.repeat(level[:, None], horizon, axis=1)
                bands = None
            else:
                bands = {k: np.asarray(v) for k, v in anomaly_bands(
                    vals, mask, alpha, beta, gamma, season,
                    nsigma).items()}
                fc = np.asarray(hw_forecast(
                    bands["level"], bands["trend"], bands["seasonal"],
                    horizon=_pad_size(horizon), season_length=season,
                    t_fitted=T))[:S, :horizon]
                grid_keys = ("fitted", "upper", "lower", "sigma",
                             "anomaly")
                bands = {k: (v[:S, :T] if k in grid_keys else v[:S])
                         for k, v in bands.items()}
                fitted = bands["fitted"]
            vals, mask = vals[:S, :T], mask[:S, :T]
            future_ts = grid0 + (T + np.arange(horizon)) * interval
            grid_ts = grid0 + np.arange(T) * interval

            if "png" in q:
                from opentsdb_tpu.graph.plot import render_forecast_png

                rseries = []
                for i, r in enumerate(results):
                    label = r.metric + (
                        "{" + ",".join(f"{k}={v}" for k, v in
                                       sorted(r.tags.items())) + "}"
                        if r.tags else "")
                    mk = mask[i]
                    anom = (bands["anomaly"][i] if bands is not None
                            else np.zeros(T, bool))
                    rseries.append({
                        "label": label,
                        "obs_ts": grid_ts[mk], "obs": vals[i][mk],
                        "fit_ts": grid_ts[mk], "fit": fitted[i][mk],
                        "upper": (bands["upper"][i][mk]
                                  if bands is not None else None),
                        "lower": (bands["lower"][i][mk]
                                  if bands is not None else None),
                        "fc_ts": future_ts, "fc": fc[i],
                        "anom_ts": grid_ts[anom], "anom": vals[i][anom],
                    })
                width, height = 1024, 768
                if "wxh" in q:
                    ws, _, hs = q["wxh"].partition("x")
                    try:
                        width, height = int(ws), int(hs)
                    except ValueError:
                        raise BadRequestError(
                            f"invalid wxh parameter: {q['wxh']}") \
                            from None
                    if not (8 <= width <= 4096 and 8 <= height <= 4096):
                        raise BadRequestError(
                            f"invalid dimensions {q['wxh']}")
                return render_forecast_png(
                    rseries, start, int(future_ts[-1]),
                    width=width, height=height, title=q.get("title"),
                    params={k: v for k, v in q.items()
                            if k in ("yrange", "ylog", "nokey")}), \
                    "image/png"

            out = []
            for i, r in enumerate(results):
                entry = {
                    "metric": r.metric, "tags": r.tags,
                    "model": model,
                    "fitted": {str(int(t)): float(v) for t, v, mk in
                               zip(grid_ts, fitted[i], mask[i]) if mk},
                    "forecast": {str(int(t)): float(v) for t, v in
                                 zip(future_ts, fc[i])},
                }
                if bands is not None:
                    entry["anomalies"] = [
                        int(t) for t, a in zip(grid_ts, bands["anomaly"][i])
                        if a]
                    entry["upper"] = {
                        str(int(t)): float(v) for t, v, mk in
                        zip(grid_ts, bands["upper"][i], mask[i]) if mk}
                    entry["lower"] = {
                        str(int(t)): float(v) for t, v, mk in
                        zip(grid_ts, bands["lower"][i], mask[i]) if mk}
                out.append(entry)
            return json.dumps(out).encode(), "application/json"

        body, ctype = await loop.run_in_executor(self._pool, compute)
        return 200, ctype, body, {}

    # -- static files / home page --------------------------------------

    # Packaged web UI (the GWT-client replacement): used when no
    # --staticroot is configured, or as a fallback below a custom root.
    _PACKAGED_STATIC = os.path.join(os.path.dirname(__file__), "static")

    def _static_file(self, rel: str) -> tuple:
        if ".." in rel:
            raise BadRequestError("Malformed path", 404)
        rel = rel or "index.html"
        path = None
        for root in (self.config.staticroot, self._PACKAGED_STATIC):
            if root is None:
                continue
            cand = os.path.join(root, rel)
            if os.path.isfile(cand):
                path = cand
                break
        if path is None:
            return 404, "text/plain", b"File Not Found\n", {}
        with open(path, "rb") as f:
            body = f.read()
        ext = os.path.splitext(path)[1]
        ctype = _CONTENT_TYPES.get(ext, "application/octet-stream")
        if path.startswith(self._PACKAGED_STATIC):
            # Packaged UI files aren't content-hashed: an upgrade must
            # reach browsers. Only operator staticroot assets (hashed GWT
            # style) earn the year-long header (reference :30-54).
            hdrs = {"Cache-Control": "no-cache"}
        else:
            hdrs = {"Cache-Control": "max-age=31536000"}
        return 200, ctype, body, hdrs

    def _homepage(self) -> str:
        return f"""<html><head><title>TSD (opentsdb_tpu)</title></head>
<body><h1>opentsdb_tpu {__version__}</h1>
<p>A TPU-native time-series database.</p>
<ul>
<li><a href="/aggregators">/aggregators</a></li>
<li>/q?start=1h-ago&amp;m=sum:metric&#123;tag=value&#125;&amp;ascii</li>
<li>/suggest?type=metrics&amp;q=prefix</li>
<li><a href="/stats">/stats</a></li>
<li><a href="/tenants">/tenants</a></li>
<li><a href="/metrics">/metrics</a></li>
<li><a href="/api/traces">/api/traces</a></li>
<li><a href="/version">/version</a></li>
<li><a href="/logs">/logs</a></li>
</ul></body></html>"""

    # -- stats ----------------------------------------------------------

    def _version_text(self) -> str:
        return version_string()

    def _collect_stats(self) -> list[str]:
        c = StatsCollector("tsd")
        c.record("connectionmgr.connections", self.connections_established)
        c.record("connectionmgr.exceptions", self.exceptions_caught)
        c.record("rpc.received", self.telnet_rpcs, "type=telnet")
        c.record("rpc.received", self.http_rpcs, "type=http")
        c.record("rpc.errors", self.rpcs_unknown, "type=unknown")
        c.record("rpc.errors", self.hbase_errors_put, "type=hbase_errors")
        c.record("rpc.errors", self.illegal_arguments_put,
                 "type=illegal_arguments")
        c.record("rpc.errors", self.unknown_metrics_put,
                 "type=unknown_metrics")
        c.record("rpc.requests", self.requests_put, "type=put")
        c.record("http.latency", self.http_latency, "type=all")
        c.record("http.latency", self.graph_latency, "type=graph")
        c.record("rpc.latency", self.put_latency, "type=put")
        c.record("scan.latency", self.executor.scan_latency, "type=query")
        c.record("http.graph.requests", self.cache_hits, "cache=hit")
        c.record("http.graph.requests", self.cache_misses, "cache=miss")
        c.record("qcache.hit", self.executor.qcache_hits)
        c.record("qcache.miss", self.executor.qcache_misses)
        c.record("qcache.bypass", self.executor.qcache_bypasses)
        for plan, n in sorted(self.plan_counts.items()):
            c.record("query.plan", n, f"plan={plan}")
        from opentsdb_tpu.fault import faultpoints as _fp
        fstat = _fp.status()
        c.record("fault.sites_armed", len(fstat["armed"]))
        c.record("fault.fired", sum(fstat["fired"].values()))
        for site, n in sorted(fstat["fired"].items()):
            c.record("fault.fired_site", n, f"site={site}")
        c.record("uptime", int(time.time()) - self.start_time)
        c.record("uptime_s", int(time.time()) - self.start_time)
        rss = read_rss_bytes()
        if rss:
            c.record("process.rss_bytes", rss)
        c.record("traces.recorded", self.trace_ring.recorded)
        c.record("traces.slow", self.trace_ring.slow)
        # Serve tier: the staleness contract (replica role) and the
        # admission/shedding counters — self-monitoring ingests these
        # as tsd.replica.* / tsd.admission.* series, which is what
        # `tsdb check -m tsd.replica.lag_ms ...` alerts on.
        if self.tailer is not None:
            self.tailer.collect_stats(c)
        self.admission.collect_stats(c)
        c.record("selfmon.cycles", self.selfmon.cycles)
        c.record("selfmon.points", self.selfmon.points)
        c.record("selfmon.errors", self.selfmon.errors)
        self.tsdb.collect_stats(c)
        # Engine instruments (obs/registry.py): WAL append/fsync,
        # checkpoint phases, per-shard spills, rollup folds, fsck,
        # per-handler latency — timers expand to p50/p95/p99 +
        # .count/.sum_ms lines.
        METRICS.collect(c)
        return c.lines


# ---------------------------------------------------------------------------
# /queries: the query-planner dashboard — per-plan serve counters
# (raw / resident / fused / rollup / approx), the sketch-serving
# error-contract counters, the rollup tier's per-resolution sketch
# allocation, fragment-cache rates. The /topology pattern one layer
# down: one self-contained page over the /api/queries JSON feed,
# served from memory, auto-refreshing.
# ---------------------------------------------------------------------------

_TENANTS_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>tsd tenants</title>
<style>
 body{font:13px/1.45 system-ui,sans-serif;margin:1.2em;background:#fafafa;
      color:#222}
 h1{font-size:1.2em;margin:0 0 .2em}
 h2{font-size:1em;margin:1.2em 0 .3em}
 table{border-collapse:collapse;background:#fff;min-width:36em}
 th,td{border:1px solid #ddd;padding:.25em .6em;text-align:left;
       font-variant-numeric:tabular-nums}
 th{background:#f0f0f0;font-weight:600}
 .ok{color:#0a7d32}.bad{color:#c0392b}.warn{color:#b8860b}
 #meta{color:#666;font-size:.9em;margin-bottom:.8em}
 .pill{display:inline-block;padding:0 .5em;border-radius:.8em;
       background:#eee;margin-right:.4em}
 small{color:#888}
</style></head><body>
<h1>Tenant cardinality</h1>
<div id="meta">loading /api/tenants&hellip;</div>
<div id="tenants"></div><div id="hh"></div><div id="adm"></div>
<script>
function esc(v){return String(v).replace(/&/g,"&amp;")
  .replace(/</g,"&lt;").replace(/>/g,"&gt;");}
function fmt(v){return v===null||v===undefined?"&mdash;":esc(v);}
function table(title, heads, rows){
  var h="<h2>"+title+"</h2><table><tr>"+heads.map(
    function(x){return "<th>"+x+"</th>";}).join("")+"</tr>";
  h+=rows.map(function(r){return "<tr>"+r.map(
    function(c){return "<td>"+c+"</td>";}).join("")+"</tr>";}).join("");
  return h+"</table>";
}
function pills(title, obj){
  return "<h2>"+title+"</h2>"+Object.keys(obj).sort().map(function(k){
    return "<span class='pill'>"+esc(k)+": "+esc(obj[k])+"</span>";
  }).join("")||"&mdash;";
}
function render(t){
  if(!t.enabled){
    document.getElementById("meta").innerHTML=
      "tenant accounting is off on this daemon (role "+
      fmt(t.role)+")";
    return;
  }
  document.getElementById("meta").innerHTML=
    "tracked series "+t.tracked_series+" &middot; mode "+fmt(t.mode)+
    " &middot; global limit "+(t.global_limit||"&infin;")+
    " &middot; snapshots "+t.snapshots_written+
    " &middot; refreshed "+new Date().toLocaleTimeString();
  var names=Object.keys(t.tenants||{});
  var rows=names.map(function(n){
    var e=t.tenants[n];
    var over=e.limit&&e.series>=e.limit;
    var ser=e.series+(e.tier==="hll"
      ?" <small>&plusmn;"+Math.round(e.error*100)+"% (hll)</small>":"");
    return [esc(n), over?"<span class='bad'>"+ser+"</span>":ser,
      e.limit?esc(e.limit):"&infin;", e.points,
      e.refused?"<span class='bad'>"+e.refused+"</span>":0,
      e.would_refuse||0];});
  document.getElementById("tenants").innerHTML=
    table("Tenants",["tenant","series","limit","points","refused",
                     "would refuse"],rows);
  var hh="";
  names.forEach(function(n){
    var e=t.tenants[n];
    if((e.top_series||[]).length)
      hh+=table("Heavy hitters &mdash; "+esc(n),
        ["series","points","err","","prefix","new series","err"],
        e.top_series.map(function(s,i){
          var p=(e.top_prefixes||[])[i]||{};
          return [esc(s.series),s.points,s.err,"",
            fmt(p.prefix),fmt(p.new_series),fmt(p.err)];}));
  });
  document.getElementById("hh").innerHTML=hh;
  document.getElementById("adm").innerHTML=
    pills("Admission buckets", t.admission||{});
}
function tick(){
  fetch("/api/tenants").then(function(r){return r.json();})
    .then(render)
    .catch(function(e){document.getElementById("meta").innerHTML=
      "<span class='bad'>fetch failed: "+esc(e)+"</span>";});
}
tick(); setInterval(tick, 2000);
</script></body></html>
"""

_QUERIES_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>tsd queries</title>
<style>
 body{font:13px/1.45 system-ui,sans-serif;margin:1.2em;background:#fafafa;
      color:#222}
 h1{font-size:1.2em;margin:0 0 .2em}
 h2{font-size:1em;margin:1.2em 0 .3em}
 table{border-collapse:collapse;background:#fff;min-width:30em}
 th,td{border:1px solid #ddd;padding:.25em .6em;text-align:left;
       font-variant-numeric:tabular-nums}
 th{background:#f0f0f0;font-weight:600}
 .ok{color:#0a7d32}.bad{color:#c0392b}.warn{color:#b8860b}
 #meta{color:#666;font-size:.9em;margin-bottom:.8em}
 .pill{display:inline-block;padding:0 .5em;border-radius:.8em;
       background:#eee;margin-right:.4em}
</style></head><body>
<h1>Query planner</h1>
<div id="meta">loading /api/queries&hellip;</div>
<div id="plans"></div><div id="sketch"></div>
<div id="rollup"></div><div id="caches"></div>
<script>
function esc(v){return String(v).replace(/&/g,"&amp;")
  .replace(/</g,"&lt;").replace(/>/g,"&gt;");}
function fmt(v){return v===null||v===undefined?"&mdash;":esc(v);}
function table(title, heads, rows){
  var h="<h2>"+title+"</h2><table><tr>"+heads.map(
    function(x){return "<th>"+x+"</th>";}).join("")+"</tr>";
  h+=rows.map(function(r){return "<tr>"+r.map(
    function(c){return "<td>"+c+"</td>";}).join("")+"</tr>";}).join("");
  return h+"</table>";
}
function pills(title, obj){
  return "<h2>"+title+"</h2>"+Object.keys(obj).sort().map(function(k){
    return "<span class='pill'>"+esc(k)+": "+esc(obj[k])+"</span>";
  }).join("")||"&mdash;";
}
function render(t){
  document.getElementById("meta").innerHTML=
    "up "+t.uptime_s+"s &middot; refreshed "+
    new Date().toLocaleTimeString();
  var order=["raw","resident","fused","rollup","approx","expert",
             "expert-decline"];
  var p=t.plans||{};
  document.getElementById("plans").innerHTML=
    table("Plans served",["plan","results"],order.filter(function(k){
      return p[k];}).map(function(k){
        var cls=k==="approx"?" class='warn'":"";
        return ["<span"+cls+">"+esc(k)+"</span>", p[k]];}));
  var f=t.fused;
  if(f&&f.attempt){
    var dec=Object.keys(f.declines||{}).sort().map(function(k){
      return esc(k)+"="+esc(f.declines[k]);}).join(" ")||"none";
    var dc=f.devcache||{};
    document.getElementById("plans").innerHTML+=
      "<p>fused coverage: <b>"+(100*f.coverage).toFixed(1)+"%</b> ("+
      f.served+"/"+f.attempt+" batteries) &middot; declines: "+dec+
      " &middot; devcache hit/miss/evict: "+(dc.hit||0)+"/"+
      (dc.miss||0)+"/"+(dc.evict||0)+"</p>";
  }
  document.getElementById("sketch").innerHTML=
    pills("Sketch serving (error contract)", t.sketch||{});
  var r=t.rollup;
  if(r){
    var rows=Object.keys(r.sketch_alloc||{}).map(function(res){
      var a=r.sketch_alloc[res];
      return [esc(res),(r.hits||{})[res]||0,a.digest_k,a.moment_k,
              a.hll_p];});
    document.getElementById("rollup").innerHTML=
      table("Rollup tier "+(r.ready?"<span class='ok'>ready</span>"
        :"<span class='bad'>not ready</span>"),
        ["res","hits","digest_k","moment_k","hll_p"],rows)
      +pills("Fallbacks", r.fallbacks||{})
      +pills("Sketch bytes written", r.sketch_bytes||{});
  } else { document.getElementById("rollup").innerHTML=""; }
  var mesh=t.mesh||{};
  var cc=mesh.compile_cache||{};
  document.getElementById("caches").innerHTML=
    pills("Mesh execution ("+(mesh.devices||1)+" device"+
          ((mesh.devices||1)>1?"s":"")+
          (mesh.expert_enabled?", expert on":"")+")",
          {"compile cache":(cc.size||0)+" plans",
           "hit":cc.hit||0,"miss":cc.miss||0,
           "expert served":(mesh.expert||{}).serve||0,
           "expert declined":(mesh.expert||{}).decline||0})+
    pills("Fragment cache", t.qcache||{})+
    pills("Admission", t.admission||{});
}
function tick(){
  fetch("/api/queries").then(function(r){return r.json();})
    .then(render)
    .catch(function(e){document.getElementById("meta").innerHTML=
      "<span class='bad'>fetch failed: "+esc(e)+"</span>";});
}
tick(); setInterval(tick, 2000);
</script></body></html>
"""
