"""Network front-end: asyncio TCP server speaking telnet-RPC and HTTP."""

from opentsdb_tpu.server.tsd import TSDServer

__all__ = ["TSDServer"]
