"""In-RAM ring of recent log events, surfaced at /logs.

Parity: reference src/logback.xml's CyclicBufferAppender (1024 events) +
LogsRpc (:62-103) including runtime log-level changes via ?level=.
"""

from __future__ import annotations

import collections
import logging

RING_SIZE = 1024


class RingBufferHandler(logging.Handler):
    def __init__(self, capacity: int = RING_SIZE) -> None:
        super().__init__()
        self.events: collections.deque[logging.LogRecord] = \
            collections.deque(maxlen=capacity)

    def emit(self, record: logging.LogRecord) -> None:
        self.events.append(record)

    def formatted(self, reverse: bool = True) -> list[str]:
        out = []
        # Snapshot: other threads append concurrently, and iterating a
        # mutating deque raises RuntimeError.
        for rec in list(self.events):
            out.append("%d\t%s\t%s\t%s\t%s" % (
                int(rec.created), rec.levelname, rec.threadName,
                rec.name, rec.getMessage()))
        if reverse:
            out.reverse()
        return out


_handler: RingBufferHandler | None = None


def install() -> RingBufferHandler:
    global _handler
    if _handler is None:
        _handler = RingBufferHandler()
        logging.getLogger().addHandler(_handler)
    return _handler


def set_level(level: str) -> None:
    value = getattr(logging, level.upper(), None)
    if not isinstance(value, int):
        raise ValueError(f"Unrecognized log level: {level}")
    logging.getLogger().setLevel(value)
