"""Wire decoding: native (C++) batch parser with pure-Python fallback.

``decode_puts(buf)`` turns a byte buffer of telnet ``put`` lines into
columnar arrays plus a canonical series table — the array form the whole
ingest pipeline (TSDB.add_batch / the TPU kernels) consumes. The native
path (native/wire_decoder.cpp via ctypes) parses ~10-30x faster than
line-by-line Python; build it with ``make -C native``. The fallback is
semantically identical (differential-tested).
"""

from __future__ import annotations

import ctypes
import logging
import os
import re
from typing import NamedTuple

import numpy as np

from opentsdb_tpu.core import tags as tags_mod
from opentsdb_tpu.obs.registry import METRICS as _metrics

LOG = logging.getLogger(__name__)

_M_PARSE = _metrics.timer("ingest.parse")

_LIB_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "native",
                 "libtsdwire.so"),
    "libtsdwire.so",
)


class DecodedBatch(NamedTuple):
    timestamps: np.ndarray   # int64 [N]
    fvalues: np.ndarray      # float64 [N]
    ivalues: np.ndarray      # int64 [N] (exact ints where ~is_float)
    is_float: np.ndarray     # bool [N]
    sid: np.ndarray          # int32 [N] index into series
    series: list[tuple[str, dict[str, str]]]  # sid -> (metric, tags)
    errors: list[str]
    consumed: int            # bytes of complete lines consumed
    # Stream line number (0-based, offset by the caller's line_base) of
    # each entry in ``errors``. Empty when the decoder cannot attribute
    # lines (the native path), in which case callers fall back to
    # index-free error reporting.
    error_lines: tuple | list = ()


def _load_native():
    for path in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(os.path.abspath(path)
                              if os.path.sep in path else path)
        except OSError:
            continue
        lib.tsd_parse.restype = ctypes.c_void_p
        lib.tsd_parse.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        for fn in ("tsd_npoints", "tsd_nseries", "tsd_nerrors",
                   "tsd_consumed"):
            getattr(lib, fn).restype = ctypes.c_size_t
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.tsd_copy_points.restype = None
        lib.tsd_copy_points.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32)]
        lib.tsd_series_name.restype = ctypes.c_char_p
        lib.tsd_series_name.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.tsd_error.restype = ctypes.c_char_p
        lib.tsd_error.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.tsd_free.restype = None
        lib.tsd_free.argtypes = [ctypes.c_void_p]
        LOG.info("native wire decoder loaded from %s", path)
        return lib
    return None


_NATIVE = _load_native()


def native_available() -> bool:
    return _NATIVE is not None


def _parse_series_name(name: str) -> tuple[str, dict[str, str]]:
    parts = name.split(" ")
    tag_map: dict[str, str] = {}
    for t in parts[1:]:
        k, _, v = t.partition("=")
        tag_map[k] = v
    return parts[0], tag_map


def decode_puts(buf: bytes, use_native: bool | None = None,
                line_base: int = 0) -> DecodedBatch:
    """Decode a buffer of ``put`` lines into a columnar batch.

    ``line_base`` offsets the per-error line numbers so chunked callers
    (the telnet bulk path feeds one TCP read at a time) report exact
    stream line indices rather than batch-relative offsets.
    """
    with _M_PARSE.time():
        if use_native is None:
            use_native = _NATIVE is not None
        if use_native and _NATIVE is not None:
            return _decode_native(buf)
        return _decode_python(buf, line_base)


def _decode_native(buf: bytes) -> DecodedBatch:
    arena = _NATIVE.tsd_parse(buf, len(buf))
    try:
        n = _NATIVE.tsd_npoints(arena)
        ts = np.empty(n, np.int64)
        fv = np.empty(n, np.float64)
        iv = np.empty(n, np.int64)
        isf = np.empty(n, np.uint8)
        sid = np.empty(n, np.int32)
        if n:
            _NATIVE.tsd_copy_points(
                arena,
                ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                fv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                iv.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                isf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                sid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        series = [
            _parse_series_name(
                _NATIVE.tsd_series_name(arena, i).decode())
            for i in range(_NATIVE.tsd_nseries(arena))]
        errors = [_NATIVE.tsd_error(arena, i).decode()
                  for i in range(_NATIVE.tsd_nerrors(arena))]
        consumed = _NATIVE.tsd_consumed(arena)
    finally:
        _NATIVE.tsd_free(arena)
    return DecodedBatch(ts, fv, iv, isf.astype(bool), sid, series,
                        errors, consumed)


def _parse_scalar_line(raw: bytes, series: list, series_ids: dict):
    """Parse ONE raw telnet line with the reference per-line grammar.

    Returns ``(ts, fv, iv, isf, sid)`` (registering new series into
    ``series``/``series_ids``), ``None`` for a blank line, or raises
    ``ValueError``. This is the single source of truth for line
    semantics: the vectorized decoder routes every irregular line here,
    and ``_decode_scalar`` (the differential-test oracle) is a plain
    loop over it — so the two decoders cannot drift on the hard cases.
    """
    line = raw.decode("utf-8", "replace").rstrip("\r")
    words = tags_mod.split_string(line)
    if not words:
        return None
    if words[0] != "put":
        raise ValueError(f"unknown command: {words[0]}")
    if len(words) < 5:
        raise ValueError(f"not enough arguments: {line}")
    metric = words[1]
    tags_mod.validate_string("metric name", metric)
    try:
        ts = tags_mod.parse_long(words[2])
    except ValueError:
        raise ValueError(
            f"invalid timestamp: {words[2]}") from None
    if ts <= 0 or ts > 0xFFFFFFFF:
        raise ValueError(f"invalid timestamp: {words[2]}")
    tag_map: dict[str, str] = {}
    for t in words[4:]:
        tags_mod.parse(tag_map, t)
        k, _, v = t.partition("=")
        tags_mod.validate_string("tag name", k)
        tags_mod.validate_string("tag value", v)
    if not tag_map:
        raise ValueError("need at least one tag")
    isf, iv, fv = tags_mod.parse_value(words[3])
    canon = metric + "".join(
        f" {k}={v}" for k, v in sorted(tag_map.items()))
    sid = series_ids.get(canon)
    if sid is None:
        sid = len(series)
        series_ids[canon] = sid
        series.append((metric, tag_map))
    return ts, fv, iv, isf, sid


def _decode_scalar(buf: bytes, line_base: int = 0) -> DecodedBatch:
    """Line-by-line reference decoder (differential-test oracle)."""
    ts_l: list[int] = []
    fv_l: list[float] = []
    iv_l: list[int] = []
    isf_l: list[bool] = []
    sid_l: list[int] = []
    series: list[tuple[str, dict[str, str]]] = []
    series_ids: dict[str, int] = {}
    errors: list[str] = []
    error_lines: list[int] = []
    consumed = buf.rfind(b"\n") + 1
    for i, raw in enumerate(buf[:consumed].split(b"\n")[:-1]):
        try:
            pt = _parse_scalar_line(raw, series, series_ids)
        except ValueError as e:
            errors.append(str(e))
            error_lines.append(line_base + i)
            continue
        if pt is None:
            continue
        ts, fv, iv, isf, sid = pt
        ts_l.append(ts)
        fv_l.append(fv)
        iv_l.append(iv)
        isf_l.append(isf)
        sid_l.append(sid)
    return DecodedBatch(
        np.asarray(ts_l, np.int64), np.asarray(fv_l, np.float64),
        np.asarray(iv_l, np.int64), np.asarray(isf_l, bool),
        np.asarray(sid_l, np.int32), series, errors, consumed,
        error_lines)


# Strict wire float grammar as bytes (mirror of tags._FLOAT_RE): the
# vectorized path pre-validates with this, then batch-converts via
# numpy's strtod — acceptance and rounding match the scalar parser.
_FLOAT_RE_B = re.compile(rb"[+-]?(\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?")


def _decode_python(buf: bytes, line_base: int = 0) -> DecodedBatch:
    """Vectorized telnet ``put`` decoder.

    One C-level pass frames and shape-checks lines; timestamps and
    values across the whole batch parse as numpy column operations
    (bytes matrices -> digit masks -> one ``astype`` cast each); metric
    validation, tag parsing, and series-id resolution run once per
    DISTINCT byte string and amortize to dict probes for repeats. Lines
    that don't fit the regular single-space shape (multi-space runs,
    ``\\r``, NULs, non-put commands) drop to ``_parse_scalar_line``,
    so error text and acceptance are identical to the scalar oracle on
    every input. Output point/series/error ordering follows line order
    exactly as the scalar decoder produces it.
    """
    consumed = buf.rfind(b"\n") + 1
    data = buf[:consumed]
    series: list[tuple[str, dict[str, str]]] = []
    series_ids: dict[str, int] = {}
    err_pairs: list[tuple[int, str]] = []   # (line_no, message)
    empty = (np.empty(0, np.int64), np.empty(0, np.float64),
             np.empty(0, np.int64), np.empty(0, bool),
             np.empty(0, np.int32))
    if not data:
        return DecodedBatch(*empty, series, [], consumed, [])

    # -- pass 1: vectorized framing and shape classification -----------
    # A line is "fast" when it is ``put metric ts value tags...`` with
    # single spaces only and no CR/NUL: field boundaries are then the
    # first three spaces after the command, all found as global
    # position-array operations — no per-line tokenizing.
    arr = np.frombuffer(data, np.uint8)
    ends = np.flatnonzero(arr == 10)
    nl = ends.size
    starts = np.empty(nl, np.int64)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    lens = ends - starts
    nonblank = lens > 0
    pre = np.zeros(nl, bool)
    cand = np.flatnonzero(lens >= 4)
    if cand.size:
        head = arr[starts[cand][:, None] + np.arange(4)]
        pre[cand] = (head == np.frombuffer(b"put ", np.uint8)).all(axis=1)
    badp = np.flatnonzero((arr == 13) | (arr == 0))
    dsp = np.flatnonzero((arr[:-1] == 32) & (arr[1:] == 32))

    def _contains(pos: np.ndarray) -> np.ndarray:
        return (np.searchsorted(pos, ends) > np.searchsorted(pos, starts))

    trail_sp = np.zeros(nl, bool)
    trail_sp[nonblank] = arr[ends[nonblank] - 1] == 32
    spp = np.flatnonzero(arr == 32)
    spp_pad = np.concatenate([spp, np.full(3, arr.size, spp.dtype)])
    j = np.searchsorted(spp, starts + 4)
    p1 = spp_pad[j]
    p2 = spp_pad[j + 1]
    p3 = spp_pad[j + 2]
    # Field-width caps bound the gather matrices; an over-wide ts or
    # value field is sent to the oracle (a >18-digit ts field may still
    # be valid through leading zeros and needs parse_long's exact
    # handling — as may a >48-byte value, a legal float needing
    # parse_value's).
    fast = (pre & ~_contains(badp) & ~_contains(dsp) & ~trail_sp
            & (p3 < ends)
            & (p2 - p1 <= 19) & (p3 - p2 <= 49)
            & (arr[np.minimum(p1 + 1, arr.size - 1)] != 43))
    fr = np.flatnonzero(fast)                 # fast rows (line indices)
    sr = np.flatnonzero(nonblank & ~fast)     # oracle rows
    nf = fr.size

    # -- pass 2: columnar timestamp + value parse ----------------------
    if nf:
        fs, fe = starts[fr], ends[fr]
        fp1, fp2, fp3 = p1[fr], p2[fr], p3[fr]

        def _field(lo: np.ndarray, hi: np.ndarray):
            """Gather variable-width fields into a null-padded bytes
            matrix (rows can then view as one fixed-width S column)."""
            flen = hi - lo
            w = int(flen.max())
            gi = lo[:, None] + np.arange(w)
            return (np.where(np.arange(w) < flen[:, None],
                             arr[np.minimum(gi, arr.size - 1)], 0),
                    flen)

        m, tslen = _field(fp1 + 1, fp2)
        dig = (m >= 48) & (m <= 57)
        pad = m == 0
        # all-digit body, padding only as a suffix. Pass 1 capped the
        # field at 18 digits, so the int64 cast below is always exact
        # (leading zeros may hide a small valid ts inside a wide
        # field); the range check right after decides validity.
        ts_ok = ((dig | pad).all(axis=1) & dig[:, 0]
                 & ~(pad[:, :-1] & dig[:, 1:]).any(axis=1))
        ts_vals = np.zeros(nf, np.int64)
        sel = np.flatnonzero(ts_ok)
        if sel.size:
            tsa = np.ascontiguousarray(m).view(f"S{m.shape[1]}").ravel()
            ts_vals[sel] = tsa[sel].astype(np.int64)
        ts_ok &= (ts_vals > 0) & (ts_vals <= 0xFFFFFFFF)

        vm, vlen = _field(fp2 + 1, fp3)
        va = np.ascontiguousarray(vm).view(f"S{vm.shape[1]}").ravel()
        vdig = (vm >= 48) & (vm <= 57)
        vpad = vm == 0
        sign = (vm[:, 0] == 43) | (vm[:, 0] == 45)
        ndig = vdig.sum(axis=1)
        # int syntax = optional sign then >= 1 digit (parse_long's
        # grammar); cap at 18 digits so the int64 cast can't overflow —
        # longer ints take parse_value for its exact overflow message.
        int_syntax = ((vdig[:, 0] | sign)
                      & (vdig | vpad)[:, 1:].all(axis=1)
                      & ~(vpad[:, :-1] & vdig[:, 1:]).any(axis=1)
                      & (ndig >= 1))
        int_like = int_syntax & (ndig <= 18)
        isf_arr = np.zeros(nf, bool)
        iv_arr = np.zeros(nf, np.int64)
        fv_arr = np.zeros(nf, np.float64)
        val_ok = np.ones(nf, bool)
        val_err: dict[int, str] = {}
        sel = np.flatnonzero(int_like)
        if sel.size:
            ivs = va[sel].astype(np.int64)
            iv_arr[sel] = ivs
            fv_arr[sel] = ivs.astype(np.float64)
        # unsigned digits.digits — the common float shape — converts
        # as one batch cast; anything fancier (signs, exponents, "5.")
        # revalidates against the strict grammar regex per value.
        isdot = vm == 46
        last = vm[np.arange(nf), vlen - 1]
        simple_f = (~int_syntax & (isdot.sum(axis=1) == 1)
                    & (vdig | isdot | vpad).all(axis=1)
                    & ~(vpad[:, :-1] & ~vpad[:, 1:]).any(axis=1)
                    & vdig[:, 0] & (last >= 48) & (last <= 57))
        sel = np.flatnonzero(simple_f)
        if sel.size:
            isf_arr[sel] = True
            fv_arr[sel] = va[sel].astype(np.float64)
        hard = np.flatnonzero(~int_like & ~simple_f)
        if hard.size:
            fp2_l, fp3_l = fp2.tolist(), fp3.tolist()
            int_syn_l = int_syntax.tolist()
            flt = np.array([
                not int_syn_l[k] and _FLOAT_RE_B.fullmatch(
                    data[fp2_l[k] + 1:fp3_l[k]]) is not None
                for k in hard.tolist()], bool)
            good = hard[flt]
            if good.size:
                isf_arr[good] = True
                fv_arr[good] = va[good].astype(np.float64)
            for k in hard[~flt].tolist():
                try:
                    isf, iv, fv = tags_mod.parse_value(
                        data[fp2_l[k] + 1:fp3_l[k]].decode(
                            "utf-8", "replace"))
                    isf_arr[k] = isf
                    iv_arr[k] = iv
                    fv_arr[k] = fv
                except ValueError as e:
                    val_ok[k] = False
                    val_err[k] = str(e)
        ts_ok_l = ts_ok.tolist()
        val_ok_l = val_ok.tolist()

    # -- pass 3: per-line resolution in stream order -------------------
    # Per fast line: two slices + dict probes. Metric validation, tag
    # parse/validate, and canonicalization run once per distinct byte
    # string; a (metric, tags) pair maps straight to its sid afterward.
    # Fast and oracle rows interleave in line order so series-id
    # assignment (first fully-valid appearance wins) matches the
    # oracle's numbering exactly.
    metric_cache: dict[bytes, object] = {}   # -> str | ValueError
    tags_cache: dict[bytes, object] = {}     # -> dict | ValueError
    pair_sid: dict[tuple, int] = {}
    keep_fi: list[int] = []   # fast indices emitted, in line order
    keep_sid: list[int] = []
    slow_pts: list = []       # (line_no, ts, fv, iv, isf, sid)
    if nf:
        fs_l, fe_l = fs.tolist(), fe.tolist()
        fp1_l, fp3_l = fp1.tolist(), fp3.tolist()
        fr_l = fr.tolist()
    if sr.size:
        sl = starts[sr].tolist()
        se = ends[sr].tolist()
        sr_l = sr.tolist()
        walk = sorted(
            [(ln, fi, -1) for fi, ln in enumerate(fr_l)]
            + [(ln, -1, si) for si, ln in enumerate(sr_l)]) if nf else [
            (ln, -1, si) for si, ln in enumerate(sr_l)]
    else:
        walk = [(ln, fi, -1) for fi, ln in enumerate(fr_l)] if nf else []
    for i, fi, si in walk:
        if fi < 0:
            try:
                pt = _parse_scalar_line(data[sl[si]:se[si]],
                                        series, series_ids)
            except ValueError as e:
                err_pairs.append((i, str(e)))
                continue
            if pt is not None:
                slow_pts.append((i, *pt))
            continue
        mkey = data[fs_l[fi] + 4:fp1_l[fi]]
        tkey = data[fp3_l[fi] + 1:fe_l[fi]]
        sid = pair_sid.get((mkey, tkey), -1)
        if sid < 0:
            # Error precedence matches the oracle: metric, timestamp,
            # tags, value — only then does the series register (an
            # all-error series never claims a sid).
            mres = metric_cache.get(mkey)
            if mres is None:
                metric = mkey.decode("utf-8", "replace")
                try:
                    tags_mod.validate_string("metric name", metric)
                    mres = metric
                except ValueError as e:
                    mres = e
                metric_cache[mkey] = mres
            if type(mres) is not str:
                err_pairs.append((i, str(mres)))
                continue
            if not ts_ok_l[fi]:
                err_pairs.append((i, "invalid timestamp: " + data[
                    fp1_l[fi] + 1:fp1_l[fi] + 1 + int(tslen[fi])].decode(
                        "utf-8", "replace")))
                continue
            tres = tags_cache.get(tkey)
            if tres is None:
                tag_map: dict[str, str] = {}
                try:
                    for t in tkey.decode("utf-8", "replace").split(" "):
                        tags_mod.parse(tag_map, t)
                        k, _, v = t.partition("=")
                        tags_mod.validate_string("tag name", k)
                        tags_mod.validate_string("tag value", v)
                    tres = tag_map
                except ValueError as e:
                    tres = e
                tags_cache[tkey] = tres
            if type(tres) is not dict:
                err_pairs.append((i, str(tres)))
                continue
            if not val_ok_l[fi]:
                err_pairs.append((i, val_err[fi]))
                continue
            canon = mres + "".join(
                f" {k}={v}" for k, v in sorted(tres.items()))
            sid = series_ids.get(canon)
            if sid is None:
                sid = len(series)
                series_ids[canon] = sid
                series.append((mres, dict(tres)))
            pair_sid[(mkey, tkey)] = sid
        else:
            if not ts_ok_l[fi]:
                err_pairs.append((i, "invalid timestamp: " + data[
                    fp1_l[fi] + 1:fp1_l[fi] + 1 + int(tslen[fi])].decode(
                        "utf-8", "replace")))
                continue
            if not val_ok_l[fi]:
                err_pairs.append((i, val_err[fi]))
                continue
        keep_fi.append(fi)
        keep_sid.append(sid)

    errors = [msg for _, msg in err_pairs]
    error_lines = [line_base + ln for ln, _ in err_pairs]
    # -- assembly: columnar gather, slow lines merged by line order ----
    if not keep_fi and not slow_pts:
        return DecodedBatch(*empty, series, errors, consumed, error_lines)
    if keep_fi:
        kfi = np.asarray(keep_fi, np.int64)
        f_cols = (ts_vals[kfi], fv_arr[kfi], iv_arr[kfi], isf_arr[kfi],
                  np.asarray(keep_sid, np.int32))
    if not slow_pts:
        cols = f_cols
    else:
        s_lines = np.asarray([p[0] for p in slow_pts], np.int64)
        s_cols = (np.asarray([p[1] for p in slow_pts], np.int64),
                  np.asarray([p[2] for p in slow_pts], np.float64),
                  np.asarray([p[3] for p in slow_pts], np.int64),
                  np.asarray([p[4] for p in slow_pts], bool),
                  np.asarray([p[5] for p in slow_pts], np.int32))
        if not keep_fi:
            cols = s_cols
        else:
            f_lines = fr[kfi]
            order = np.argsort(np.concatenate([f_lines, s_lines]),
                               kind="stable")
            cols = tuple(np.concatenate([f, s])[order]
                         for f, s in zip(f_cols, s_cols))
    return DecodedBatch(*cols, series, errors, consumed, error_lines)


def decode_json_puts(obj) -> DecodedBatch:
    """Decode an ``/api/put`` JSON body (one object or an array of
    ``{"metric", "timestamp", "value", "tags"}``) into the same
    columnar batch the telnet decoder produces.

    Per-point Python work is two dict probes and a list append; series
    validation/canonicalization runs once per distinct (metric, tags)
    and timestamps/values convert as whole-column numpy casts when the
    batch is homogeneous (all-int or all-float values — the shape
    collectors send), falling back per point only for mixed or string
    typed entries. ``error_lines`` carries the failing point's array
    index.
    """
    with _M_PARSE.time():
        return _decode_json_puts(obj)


def _decode_json_puts(obj) -> DecodedBatch:
    if isinstance(obj, dict):
        obj = [obj]
    if not isinstance(obj, list):
        raise ValueError(
            "expected a JSON datapoint object or array of them")
    n = len(obj)
    series: list[tuple[str, dict[str, str]]] = []
    series_ids: dict[str, int] = {}
    pair_cache: dict = {}        # (metric, tags items) -> sid | error
    errors: list[str] = []
    error_lines: list[int] = []
    sid = np.full(n, -1, np.int32)
    ts_raw: list = [None] * n
    val_raw: list = [None] * n
    for i, d in enumerate(obj):
        if not isinstance(d, dict):
            errors.append(f"datapoint {i} is not an object")
            error_lines.append(i)
            continue
        metric = d.get("metric")
        tags = d.get("tags")
        try:
            key = (metric, tuple(sorted(tags.items()))
                   if isinstance(tags, dict) else None)
        except TypeError:
            errors.append(f"unsortable tags in datapoint {i}")
            error_lines.append(i)
            continue
        s = pair_cache.get(key)
        if s is None:
            try:
                if not isinstance(metric, str):
                    raise ValueError("missing or non-string metric")
                if not isinstance(tags, dict):
                    raise ValueError("missing tags object")
                tag_map = {str(k): str(v) for k, v in tags.items()}
                tags_mod.check_metric_and_tags(metric, tag_map)
                canon = metric + "".join(
                    f" {k}={v}" for k, v in sorted(tag_map.items()))
                s = series_ids.get(canon)
                if s is None:
                    s = len(series)
                    series_ids[canon] = s
                    series.append((metric, tag_map))
            except ValueError as e:
                s = e
            pair_cache[key] = s
        if type(s) is not int:
            errors.append(str(s))
            error_lines.append(i)
            continue
        sid[i] = s
        ts_raw[i] = d.get("timestamp")
        val_raw[i] = d.get("value")

    ok = sid >= 0
    rows = np.flatnonzero(ok)
    ts_vals = np.zeros(n, np.int64)
    fv = np.zeros(n, np.float64)
    iv = np.zeros(n, np.int64)
    isf = np.zeros(n, bool)

    def _scalar_ts(x):
        if isinstance(x, bool):
            raise ValueError
        if isinstance(x, str):
            x = tags_mod.parse_long(x)
        if isinstance(x, float):
            if x != int(x):
                raise ValueError
            x = int(x)
        if not isinstance(x, int):
            raise ValueError
        return x

    if rows.size:
        col = [ts_raw[k] for k in rows.tolist()]
        arr = None
        if set(map(type, col)) == {int}:
            try:
                arr = np.asarray(col, np.int64)
            except OverflowError:
                arr = None
        if arr is not None:
            ts_vals[rows] = arr
        else:
            for k, x in zip(rows.tolist(), col):
                try:
                    ts_vals[k] = _scalar_ts(x)
                except (ValueError, TypeError, OverflowError):
                    ok[k] = False
                    errors.append(f"invalid timestamp: {x}")
                    error_lines.append(k)
        bad = rows[(ts_vals[rows] <= 0)
                   | (ts_vals[rows] > 0xFFFFFFFF)]
        for k in bad.tolist():
            if ok[k]:
                ok[k] = False
                errors.append(f"invalid timestamp: {ts_raw[k]}")
                error_lines.append(k)

    rows = np.flatnonzero(ok)
    if rows.size:
        col = [val_raw[k] for k in rows.tolist()]
        # type-set probe (one C-speed map) keeps int/float typing
        # exact: np.asarray on a mixed list would silently promote
        # every int to float64 and change how points are encoded.
        tset = set(map(type, col))
        arr = None
        if tset == {int}:
            try:
                arr = np.asarray(col, np.int64)
            except OverflowError:
                arr = None
            if arr is not None:
                iv[rows] = arr
                fv[rows] = arr.astype(np.float64)
        elif tset == {float}:
            arr = np.asarray(col, np.float64)
            fv[rows] = arr
            isf[rows] = True
        if arr is None:
            for k, x in zip(rows.tolist(), col):
                try:
                    if isinstance(x, bool):
                        raise ValueError(f"invalid value: {x}")
                    if isinstance(x, str):
                        f, i2, f2 = tags_mod.parse_value(x)
                        isf[k], iv[k], fv[k] = f, i2, f2
                    elif isinstance(x, int):
                        iv[k] = x
                        fv[k] = float(x)
                    elif isinstance(x, float):
                        fv[k] = x
                        isf[k] = True
                    else:
                        raise ValueError(f"invalid value: {x}")
                except (ValueError, TypeError, OverflowError):
                    ok[k] = False
                    errors.append(f"invalid value: {x}")
                    error_lines.append(k)

    rows = np.flatnonzero(ok)
    # sort point-index-attributed errors back into point order (the
    # ts/value passes appended out of order relative to series errors)
    pairs = sorted(zip(error_lines, errors))
    return DecodedBatch(
        ts_vals[rows], fv[rows], iv[rows], isf[rows], sid[rows],
        series, [m for _, m in pairs], 0, [ln for ln, _ in pairs])


def pipelined_ingest(tsdb, chunks, durable: bool = True,
                     use_native: bool | None = None,
                     max_queue: int = 2,
                     tenant: str = "default") -> tuple[int, list[str]]:
    """Two-stage host pipeline over a stream of byte chunks: a worker
    thread decodes chunk N+1 while the caller's thread ingests batch N —
    the pipeline-parallelism analog for this workload (SURVEY.md §2.9 PP
    row; the reference's nearest analog is async callback pipelining of
    scan->compact->aggregate, src/core/TsdbQuery.java:240-285). The
    native decoder drops the GIL inside ``tsd_parse``, so the stages
    genuinely overlap. Partial trailing lines carry into the next chunk
    (the stream analog of LineBasedFrameDecoder framing).

    Returns (points_written, error strings).
    """
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=max_queue)
    fail: list[BaseException] = []
    cancelled = threading.Event()

    def producer():
        try:
            carry = b""
            nbase = 0  # stream line number of the next batch's line 0
            for chunk in chunks:
                if cancelled.is_set():
                    return
                buf = carry + chunk
                batch = decode_puts(buf, use_native, line_base=nbase)
                carry = buf[batch.consumed:]
                nbase += buf.count(b"\n", 0, batch.consumed)
                q.put(batch)
            if carry.strip():
                q.put(decode_puts(carry + b"\n", use_native,
                                  line_base=nbase))
        except BaseException as e:  # surface in the consumer thread
            fail.append(e)
        finally:
            q.put(None)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    total = 0
    errors: list[str] = []
    batch = None
    try:
        while (batch := q.get()) is not None:
            errors += batch.errors  # parse errors, like the one-shot path
            n, errs = ingest_batch(tsdb, batch, durable,
                                   tenant=tenant)
            total += n
            errors += errs
    finally:
        # If ingest raised mid-stream the producer may be blocked on
        # q.put (maxsize bound): tell it to stop consuming the stream,
        # then drain until its None sentinel and join. The drain is
        # time-bounded: a producer wedged *reading* the chunk source
        # (stalled socket) can't observe the flag, and the consumer's
        # exception must still propagate promptly — in that case the
        # daemon thread is abandoned to die with the process.
        cancelled.set()
        while batch is not None:
            try:
                batch = q.get(timeout=1.0)
            except queue.Empty:
                break
        t.join(timeout=5.0)
    if fail:
        raise fail[0]
    return total, errors


def ingest_batch(tsdb, batch: DecodedBatch, durable: bool = True,
                 tenant: str = "default") -> tuple[int, list[str]]:
    """Feed a decoded batch into the TSDB via the columnar write path.

    Series are ingested independently: one series failing (unknown
    metric, conflicting duplicate, throttle) does not drop the others —
    matching the per-line put semantics. Returns (points_written,
    per-series error strings). One argsort groups points by series;
    no per-series full-array masks.
    """
    n = 0
    errors: list[str] = []
    if len(batch.sid) == 0:
        return 0, errors
    order = np.argsort(batch.sid, kind="stable")
    sid_sorted = batch.sid[order]
    starts = np.concatenate(
        ([0], np.flatnonzero(np.diff(sid_sorted)) + 1, [len(order)]))
    # Under WAL group commit each per-series put skips its own barrier
    # (sync=False) and ONE covering barrier runs before this returns —
    # the batch pays a single fsync wait instead of one per series,
    # while the caller's ack still only happens after that fsync. The
    # try/finally keeps the guarantee when a put raises mid-batch:
    # series already written are barriered before the error surfaces.
    try:
        for i in range(len(starts) - 1):
            run = order[starts[i]:starts[i + 1]]
            s = int(sid_sorted[starts[i]])
            metric, tag_map = batch.series[s]
            try:
                n += tsdb.add_batch(
                    metric, batch.timestamps[run], batch.fvalues[run],
                    tag_map, durable=durable,
                    is_float=batch.is_float[run],
                    int_values=batch.ivalues[run], tenant=tenant,
                    sync=False)
            except Exception as e:
                # Stable machine-readable tags for policy refusals: the
                # server's error classifier keys on "[fenced]" /
                # "[tenant-limit]", not on exception message wording
                # that could drift. A tenant-limit refusal is
                # per-series: the tenant's EXISTING series in this
                # batch still ingested above/below — only the new one
                # refused.
                from opentsdb_tpu.core.errors import (FencedWriterError,
                                                      TenantLimitError)
                if isinstance(e, FencedWriterError):
                    tag = "[fenced] "
                elif isinstance(e, TenantLimitError):
                    tag = "[tenant-limit] "
                else:
                    tag = ""
                errors.append(f"{metric}: {tag}{e}")
    finally:
        barrier = getattr(tsdb.store, "wal_barrier", None)
        if barrier is not None:
            barrier()
    return n, errors
