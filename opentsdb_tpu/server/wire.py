"""Wire decoding: native (C++) batch parser with pure-Python fallback.

``decode_puts(buf)`` turns a byte buffer of telnet ``put`` lines into
columnar arrays plus a canonical series table — the array form the whole
ingest pipeline (TSDB.add_batch / the TPU kernels) consumes. The native
path (native/wire_decoder.cpp via ctypes) parses ~10-30x faster than
line-by-line Python; build it with ``make -C native``. The fallback is
semantically identical (differential-tested).
"""

from __future__ import annotations

import ctypes
import logging
import os
from typing import NamedTuple

import numpy as np

from opentsdb_tpu.core import tags as tags_mod

LOG = logging.getLogger(__name__)

_LIB_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "native",
                 "libtsdwire.so"),
    "libtsdwire.so",
)


class DecodedBatch(NamedTuple):
    timestamps: np.ndarray   # int64 [N]
    fvalues: np.ndarray      # float64 [N]
    ivalues: np.ndarray      # int64 [N] (exact ints where ~is_float)
    is_float: np.ndarray     # bool [N]
    sid: np.ndarray          # int32 [N] index into series
    series: list[tuple[str, dict[str, str]]]  # sid -> (metric, tags)
    errors: list[str]
    consumed: int            # bytes of complete lines consumed


def _load_native():
    for path in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(os.path.abspath(path)
                              if os.path.sep in path else path)
        except OSError:
            continue
        lib.tsd_parse.restype = ctypes.c_void_p
        lib.tsd_parse.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        for fn in ("tsd_npoints", "tsd_nseries", "tsd_nerrors",
                   "tsd_consumed"):
            getattr(lib, fn).restype = ctypes.c_size_t
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.tsd_copy_points.restype = None
        lib.tsd_copy_points.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32)]
        lib.tsd_series_name.restype = ctypes.c_char_p
        lib.tsd_series_name.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.tsd_error.restype = ctypes.c_char_p
        lib.tsd_error.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.tsd_free.restype = None
        lib.tsd_free.argtypes = [ctypes.c_void_p]
        LOG.info("native wire decoder loaded from %s", path)
        return lib
    return None


_NATIVE = _load_native()


def native_available() -> bool:
    return _NATIVE is not None


def _parse_series_name(name: str) -> tuple[str, dict[str, str]]:
    parts = name.split(" ")
    tag_map: dict[str, str] = {}
    for t in parts[1:]:
        k, _, v = t.partition("=")
        tag_map[k] = v
    return parts[0], tag_map


def decode_puts(buf: bytes, use_native: bool | None = None) -> DecodedBatch:
    if use_native is None:
        use_native = _NATIVE is not None
    if use_native and _NATIVE is not None:
        return _decode_native(buf)
    return _decode_python(buf)


def _decode_native(buf: bytes) -> DecodedBatch:
    arena = _NATIVE.tsd_parse(buf, len(buf))
    try:
        n = _NATIVE.tsd_npoints(arena)
        ts = np.empty(n, np.int64)
        fv = np.empty(n, np.float64)
        iv = np.empty(n, np.int64)
        isf = np.empty(n, np.uint8)
        sid = np.empty(n, np.int32)
        if n:
            _NATIVE.tsd_copy_points(
                arena,
                ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                fv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                iv.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                isf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                sid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        series = [
            _parse_series_name(
                _NATIVE.tsd_series_name(arena, i).decode())
            for i in range(_NATIVE.tsd_nseries(arena))]
        errors = [_NATIVE.tsd_error(arena, i).decode()
                  for i in range(_NATIVE.tsd_nerrors(arena))]
        consumed = _NATIVE.tsd_consumed(arena)
    finally:
        _NATIVE.tsd_free(arena)
    return DecodedBatch(ts, fv, iv, isf.astype(bool), sid, series,
                        errors, consumed)


def _decode_python(buf: bytes) -> DecodedBatch:
    ts_l: list[int] = []
    fv_l: list[float] = []
    iv_l: list[int] = []
    isf_l: list[bool] = []
    sid_l: list[int] = []
    series: list[tuple[str, dict[str, str]]] = []
    series_ids: dict[str, int] = {}
    errors: list[str] = []
    consumed = buf.rfind(b"\n") + 1
    for raw in buf[:consumed].split(b"\n"):
        line = raw.decode("utf-8", "replace").rstrip("\r")
        words = tags_mod.split_string(line)
        if not words:
            continue
        try:
            if words[0] != "put":
                raise ValueError(f"unknown command: {words[0]}")
            if len(words) < 5:
                raise ValueError(f"not enough arguments: {line}")
            metric = words[1]
            tags_mod.validate_string("metric name", metric)
            try:
                ts = tags_mod.parse_long(words[2])
            except ValueError:
                raise ValueError(
                    f"invalid timestamp: {words[2]}") from None
            if ts <= 0 or ts > 0xFFFFFFFF:
                raise ValueError(f"invalid timestamp: {words[2]}")
            tag_map: dict[str, str] = {}
            for t in words[4:]:
                tags_mod.parse(tag_map, t)
                k, _, v = t.partition("=")
                tags_mod.validate_string("tag name", k)
                tags_mod.validate_string("tag value", v)
            if not tag_map:
                raise ValueError("need at least one tag")
            isf, iv, fv = tags_mod.parse_value(words[3])
        except ValueError as e:
            errors.append(str(e))
            continue
        canon = metric + "".join(
            f" {k}={v}" for k, v in sorted(tag_map.items()))
        sid = series_ids.get(canon)
        if sid is None:
            sid = len(series)
            series_ids[canon] = sid
            series.append((metric, tag_map))
        ts_l.append(ts)
        fv_l.append(fv)
        iv_l.append(iv)
        isf_l.append(isf)
        sid_l.append(sid)
    return DecodedBatch(
        np.asarray(ts_l, np.int64), np.asarray(fv_l, np.float64),
        np.asarray(iv_l, np.int64), np.asarray(isf_l, bool),
        np.asarray(sid_l, np.int32), series, errors, consumed)


def pipelined_ingest(tsdb, chunks, durable: bool = True,
                     use_native: bool | None = None,
                     max_queue: int = 2,
                     tenant: str = "default") -> tuple[int, list[str]]:
    """Two-stage host pipeline over a stream of byte chunks: a worker
    thread decodes chunk N+1 while the caller's thread ingests batch N —
    the pipeline-parallelism analog for this workload (SURVEY.md §2.9 PP
    row; the reference's nearest analog is async callback pipelining of
    scan->compact->aggregate, src/core/TsdbQuery.java:240-285). The
    native decoder drops the GIL inside ``tsd_parse``, so the stages
    genuinely overlap. Partial trailing lines carry into the next chunk
    (the stream analog of LineBasedFrameDecoder framing).

    Returns (points_written, error strings).
    """
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=max_queue)
    fail: list[BaseException] = []
    cancelled = threading.Event()

    def producer():
        try:
            carry = b""
            for chunk in chunks:
                if cancelled.is_set():
                    return
                buf = carry + chunk
                batch = decode_puts(buf, use_native)
                carry = buf[batch.consumed:]
                q.put(batch)
            if carry.strip():
                q.put(decode_puts(carry + b"\n", use_native))
        except BaseException as e:  # surface in the consumer thread
            fail.append(e)
        finally:
            q.put(None)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    total = 0
    errors: list[str] = []
    batch = None
    try:
        while (batch := q.get()) is not None:
            errors += batch.errors  # parse errors, like the one-shot path
            n, errs = ingest_batch(tsdb, batch, durable,
                                   tenant=tenant)
            total += n
            errors += errs
    finally:
        # If ingest raised mid-stream the producer may be blocked on
        # q.put (maxsize bound): tell it to stop consuming the stream,
        # then drain until its None sentinel and join. The drain is
        # time-bounded: a producer wedged *reading* the chunk source
        # (stalled socket) can't observe the flag, and the consumer's
        # exception must still propagate promptly — in that case the
        # daemon thread is abandoned to die with the process.
        cancelled.set()
        while batch is not None:
            try:
                batch = q.get(timeout=1.0)
            except queue.Empty:
                break
        t.join(timeout=5.0)
    if fail:
        raise fail[0]
    return total, errors


def ingest_batch(tsdb, batch: DecodedBatch, durable: bool = True,
                 tenant: str = "default") -> tuple[int, list[str]]:
    """Feed a decoded batch into the TSDB via the columnar write path.

    Series are ingested independently: one series failing (unknown
    metric, conflicting duplicate, throttle) does not drop the others —
    matching the per-line put semantics. Returns (points_written,
    per-series error strings). One argsort groups points by series;
    no per-series full-array masks.
    """
    n = 0
    errors: list[str] = []
    if len(batch.sid) == 0:
        return 0, errors
    order = np.argsort(batch.sid, kind="stable")
    sid_sorted = batch.sid[order]
    starts = np.concatenate(
        ([0], np.flatnonzero(np.diff(sid_sorted)) + 1, [len(order)]))
    for i in range(len(starts) - 1):
        run = order[starts[i]:starts[i + 1]]
        s = int(sid_sorted[starts[i]])
        metric, tag_map = batch.series[s]
        try:
            n += tsdb.add_batch(
                metric, batch.timestamps[run], batch.fvalues[run],
                tag_map, durable=durable, is_float=batch.is_float[run],
                int_values=batch.ivalues[run], tenant=tenant)
        except Exception as e:
            # Stable machine-readable tags for policy refusals: the
            # server's error classifier keys on "[fenced]" /
            # "[tenant-limit]", not on exception message wording that
            # could drift. A tenant-limit refusal is per-series:
            # the tenant's EXISTING series in this batch still
            # ingested above/below — only the new one refused.
            from opentsdb_tpu.core.errors import (FencedWriterError,
                                                  TenantLimitError)
            if isinstance(e, FencedWriterError):
                tag = "[fenced] "
            elif isinstance(e, TenantLimitError):
                tag = "[tenant-limit] "
            else:
                tag = ""
            errors.append(f"{metric}: {tag}{e}")
    return n, errors
