"""opentsdb_tpu — a TPU-native time-series database framework.

A ground-up re-design of the capabilities of OpenTSDB (reference:
/root/reference, surveyed in SURVEY.md): high-rate ``metric timestamp value
tag=value`` ingestion over telnet-style and HTTP protocols, UID-dictionary
byte-packed storage, background row compaction, and aggregated / downsampled /
rate queries with tag group-by.

Unlike the Java reference — sequential pull-iterators over HBase cells — the
compute path here is *columnar*: storage rows decode into fixed-shape padded
arrays and every aggregation (compaction merge, downsample, rate, lerp
alignment, group-by reduction, t-digest / HLL sketches) runs as a batched
JAX/XLA segment reduction, jit-compiled for TPU, sharded over a
``jax.sharding.Mesh`` for multi-chip. The byte codec survives only at the
storage and wire boundaries for ``scan --import`` round-trip compatibility.

Layering (see SURVEY.md §7):
    core     codecs & schema (pure), TSDB facade, compaction
    storage  embedded ordered-KV engine (memtable + WAL)
    uid      name<->id dictionaries
    ops      TPU kernels: segment reductions, downsample, rate, sketches
    parallel mesh shardings + cross-chip merges
    query    planner/executor + Aggregators registry
    server   asyncio telnet + HTTP front-end
    tools    tsdb-style CLI
    stats    self-monitoring counters & latency digests
    graph    PNG / JSON rendering
"""

__version__ = "0.1.0"
