"""Tag and metric-name grammar: parsing, validation, resolution helpers.

Parity with reference src/core/Tags.java: the ``k=v`` pair grammar (:77-91),
``metric{k=v,k2=v2}`` combined grammar (:101-125), the allowed character set
``[a-zA-Z0-9-_./]`` (:282-297), fast whitespace splitting (:46-67), and
O(1)-space integer parsing (:137-178).
"""

from __future__ import annotations

import re

from opentsdb_tpu.core.const import MAX_NUM_TAGS

_ALLOWED = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_./")


def split_string(s: str, sep: str = " ") -> list[str]:
    """Split on single-char separator, skipping empty runs.

    Matches reference Tags.splitString used by the telnet word splitter:
    consecutive separators yield no empty tokens.
    """
    return [tok for tok in s.split(sep) if tok]


def validate_string(what: str, s: str) -> None:
    """Ensure s is non-empty and uses only the legal character set."""
    if not s:
        raise ValueError(f"Invalid {what}: empty string")
    for c in s:
        if c not in _ALLOWED:
            raise ValueError(
                f"Invalid {what} (\"{s}\"): illegal character: {c}")


def parse(tags: dict[str, str], tag: str) -> None:
    """Parse one "name=value" into the dict; duplicate names must agree."""
    eq = tag.find("=")
    if eq < 1 or eq == len(tag) - 1:
        raise ValueError(f"invalid tag: {tag}")
    name, value = tag[:eq], tag[eq + 1:]
    if tags.get(name, value) != value:
        raise ValueError(f"duplicate tag: {tag}, tags={tags}")
    tags[name] = value


def parse_with_metric(expr: str, tags: dict[str, str]) -> str:
    """Parse "metric" or "metric{k=v,...}" filling tags; returns the metric.

    An empty tag list inside braces ("metric{}") is invalid, matching the
    reference's strictness (Tags.java:101-125).
    """
    curly = expr.find("{")
    if curly < 0:
        return expr
    if curly == 0:
        raise ValueError(f"Missing metric name: {expr}")
    if not expr.endswith("}"):
        raise ValueError(f"Missing '}}' at the end of: {expr}")
    metric = expr[:curly]
    inner = expr[curly + 1:-1]
    if not inner:
        raise ValueError(f"Empty tag list in: {expr}")
    for tag in inner.split(","):
        parse(tags, tag)
    return metric


def parse_long(s: str) -> int:
    """Parse a signed base-10 int64, rejecting junk and overflow."""
    if not s:
        raise ValueError("empty string")
    body = s[1:] if s[0] in "+-" else s
    if not body or not body.isdigit():
        raise ValueError(f"Invalid character in {s}")
    v = int(s)
    if not -0x8000000000000000 <= v <= 0x7FFFFFFFFFFFFFFF:
        raise ValueError(f"number overflow: {s}")
    return v


def looks_like_integer(s: str) -> bool:
    """Cheap sniff used by the ingest path to pick int vs float encoding."""
    if not s:
        return False
    body = s[1:] if s[0] in "+-" else s
    return body.isdigit()


_FLOAT_RE = re.compile(r"[+-]?(\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?")


def parse_value(s: str) -> tuple[bool, int, float]:
    """Parse a wire value into (is_float, int_value, float_value).

    The float grammar is strict — [+-]?(digits[.digits] | .digits)[exp] —
    and shared byte-for-byte with the native decoder, so acceptance never
    depends on which parser handled the line (no hex floats, no
    underscore literals, no nan/inf).
    """
    if looks_like_integer(s):
        iv = parse_long(s)
        return False, iv, float(iv)
    if not _FLOAT_RE.fullmatch(s):
        raise ValueError(f"invalid value: {s}")
    return True, 0, float(s)


def check_metric_and_tags(metric: str, tags: dict[str, str]) -> None:
    """Validate a full (metric, tags) pair before ingest.

    Parity: reference IncomingDataPoints.checkMetricAndTags (:83-104) —
    non-empty tags, at most MAX_NUM_TAGS, charset-clean names/values.
    """
    if not tags:
        raise ValueError(
            f"Need at least one tag (metric={metric}, tags={tags})")
    if len(tags) > MAX_NUM_TAGS:
        raise ValueError(
            f"Too many tags: {len(tags)} maximum allowed: {MAX_NUM_TAGS}")
    validate_string("metric name", metric)
    for k, v in tags.items():
        validate_string("tag name", k)
        validate_string("tag value", v)
