"""Core schema, codecs, and the TSDB facade."""

from opentsdb_tpu.core.const import (
    FLAG_BITS,
    FLAG_FLOAT,
    FLAGS_MASK,
    LENGTH_MASK,
    MAX_NUM_TAGS,
    MAX_TIMESPAN,
    TIMESTAMP_BYTES,
)
from opentsdb_tpu.core.errors import IllegalDataError
