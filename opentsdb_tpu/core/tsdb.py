"""TSDB — the thread-safe facade over storage, UIDs, and compaction.

Parity target: reference src/core/TSDB.java. Holds the KV store, the three
UID dictionaries (metrics/tagk/tagv, width 3), and the CompactionQueue; the
write path builds row keys, encodes values on their smallest width, and
schedules rows for compaction (:327-352).

TPU-first departures:
- ``add_batch`` is the real ingest path: a columnar batch for one series is
  sorted/deduped/encoded into one *pre-compacted* cell per row-hour before
  it ever hits storage, eliminating the reference's write-then-compact
  amplification (one put per point + one rewrite per row per hour).
- ``read_row`` decodes cells straight into columnar arrays (codec_np), so
  queries never iterate cells point by point.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Iterator

import numpy as np

from opentsdb_tpu.core import codec, codec_np, tags as tags_mod
from opentsdb_tpu.core.compaction import CompactionQueue
from opentsdb_tpu.core.const import (MAX_TIMESPAN, TIMESTAMP_BYTES,
                                     UID_WIDTH)
from opentsdb_tpu.core.errors import NoSuchUniqueName, PleaseThrottleError
from opentsdb_tpu.storage.kv import KVStore
from opentsdb_tpu.storage.sstable import series_hash
from opentsdb_tpu.uid.uniqueid import UniqueId
from opentsdb_tpu.utils.config import Config

LOG = logging.getLogger(__name__)

FAMILY = b"t"


class TSDB:
    def __init__(self, store: KVStore, config: Config | None = None,
                 start_compaction_thread: bool = True) -> None:
        self.config = config or Config()
        self.store = store
        store.ensure_table(self.config.table)
        store.ensure_table(self.config.uidtable)
        self.table = self.config.table
        uidtable = self.config.uidtable
        self.metrics = UniqueId(store, uidtable, "metrics", 3)
        self.tagk = UniqueId(store, uidtable, "tagk", 3)
        self.tagv = UniqueId(store, uidtable, "tagv", 3)
        self.compactionq = CompactionQueue(
            self, start_thread=start_compaction_thread)
        # Write-side sstable codec (compress/): pushed onto the store
        # so checkpoint spills and compaction merges re-encode into
        # the configured format. Only a non-default config value
        # overrides a store the embedder configured directly; replicas
        # never spill, so the read side stays format-sniffed per file.
        codec = getattr(self.config, "sstable_codec", "none") or "none"
        if codec != "none":
            if codec != "tsst4":
                raise ValueError(
                    f"unknown sstable_codec {codec!r} "
                    f"(one of: none, tsst4)")
            if hasattr(store, "sstable_codec"):
                store.sstable_codec = codec
        # WAL group commit (storage/kv.py): pushed onto the store the
        # same way; replicas never append so the knob is writer-only.
        group_ms = float(getattr(self.config, "wal_group_ms", 0.0) or 0.0)
        if group_ms > 0 and hasattr(store, "wal_group_ms") \
                and not getattr(store, "read_only", False):
            store.wal_group_ms = group_ms
        # Spill-encode pipelining (storage/sstable.py module knob —
        # the writer pool is shared across stores/shards).
        from opentsdb_tpu.storage import sstable as _sstable_mod
        _sstable_mod.set_encode_workers(
            int(getattr(self.config, "spill_encode_workers", 0) or 0))
        self._lock = threading.Lock()
        # Serializes checkpoint() end to end so the rollup tier's spill
        # bracketing (begin_spill ... fold_after_spill) pairs 1:1 with
        # an actual store spill. Without it, a manual checkpoint racing
        # the compaction thread's timer checkpoint gets rows=0 from the
        # store ("merge already in flight"), drains empty spill keys,
        # and then clears the CONCURRENT checkpoint's in-flight window
        # set and flips the tier state to ok while that spill is still
        # uncommitted — windows neither pending nor in-flight nor
        # folded, so stale summaries get served (and a crash in the gap
        # skips the rebuild).
        self._checkpoint_lock = threading.Lock()
        # Cluster write tier (cluster/): the epoch file this daemon's
        # store is governed by (None = not a cluster member). Set by
        # the CLI when --cluster is on; collect_stats exports the
        # current epoch as tsd.cluster.epoch so the self-monitoring
        # loop makes epoch SKEW between daemons alertable
        # (`tsdb check --skew`).
        self.cluster_epoch_path: str | None = None
        # Optional deregistration hook: the CLI's open-TSDB sweep list
        # (tools/cli._OPEN_TSDBS) sets this so shutdown() removes the
        # entry — embedders calling make_tsdb() outside main() would
        # otherwise accumulate hard references that pin closed stores
        # (and their memtables) against GC forever.
        self._deregister = None
        # ingest stats
        self.datapoints_added = 0
        # Streaming sketch state (stats/livesketch.py): loaded from the
        # checkpoint snapshot when one exists (then re-folding only the
        # WAL-replayed memtable), else rebuilt from a full storage scan.
        self.sketches = None
        if self.config.enable_sketches:
            self._init_sketches()
        # Device-resident columnar hot window (storage/devstore.py):
        # ingest mirrors into HBM so queries skip the host->device
        # upload. CPU-oracle deployments skip it (nothing to upload to).
        self.devwindow = None
        # A replica never ingests, so nothing would keep the window
        # (or its completeness bookkeeping) in sync with the writer's
        # appends arriving via store.refresh() — a boot-warmed window
        # would serve STALE resident answers while claiming coverage.
        # Replicas use the scan path. (Sketches stay: they reload on
        # every replica rebuild — reload_sketches() — so their lag is
        # bounded by the writer's checkpoint cadence + the poll.)
        # Checked locally, NOT written back into config: the Config
        # object is caller-owned and may be shared with a writer TSDB.
        use_devwindow = (self.config.device_window
                        and not getattr(store, "read_only", False))
        if use_devwindow and self.config.backend != "cpu":
            if self.config.devwindow_shards > 0:
                # Mesh-sharded hot set: logical shards round-robined
                # over the mesh devices (storage/devshard.py) so
                # capacity and stage throughput scale with mesh width.
                from opentsdb_tpu.storage.devshard import \
                    ShardedDeviceWindow

                self.devwindow = ShardedDeviceWindow(
                    devices=self._devwindow_devices(),
                    n_shards=self.config.devwindow_shards,
                    staging_points=self.config.device_window_staging,
                    max_points=self.config.device_window_points)
            else:
                from opentsdb_tpu.storage.devstore import DeviceWindow

                self.devwindow = DeviceWindow(
                    staging_points=self.config.device_window_staging,
                    max_points=self.config.device_window_points)
            self._warm_devwindow()
        # Materialized rollup tier (rollup/tier.py): daemons with a
        # persistent store only — an in-memory store never spills, so
        # every window would stay memtable-dirty and the planner could
        # never serve a summary. Writers own the fold and the tier's
        # state file; replicas open the same stores READ-ONLY
        # (ReadOnlyRollupTier) so the planner serves summaries on the
        # serve tier too, refreshed by refresh_replica().
        # Tenant cardinality control plane (opentsdb_tpu/tenant/):
        # per-tenant series accounting + heavy hitters + admission
        # limits, fed from this write path's series-identity hash.
        # Writers only — a replica neither admits nor snapshots.
        self.tenants = None
        self.tenant_limits = None
        # skey -> "metric{k=v,...}" memo for the heavy-hitter summary:
        # the label is invariant per series, so the per-point ingest
        # path must not rebuild (sort + join) it every point. Cleared
        # wholesale at the cap — churn past it is the hostile regime
        # where the rebuild cost is the attacker's, not the steady
        # workload's.
        self._series_labels: dict[bytes, str] = {}
        if (self.config.tenant_accounting
                and not getattr(store, "read_only", False)):
            self._init_tenants()
        self.rollups = None
        if (self.config.enable_rollups
                and getattr(store, "_wal_path", None)):
            if getattr(store, "read_only", False):
                from opentsdb_tpu.rollup.tier import ReadOnlyRollupTier
                try:
                    self.rollups = ReadOnlyRollupTier(self, self.config)
                except Exception:
                    # A replica must come up even when the writer's
                    # tier is mid-rebuild/foreign: serve raw, let the
                    # refresh cycle adopt the tier when it settles.
                    LOG.exception("replica rollup tier unavailable; "
                                  "serving raw")
            else:
                from opentsdb_tpu.rollup.tier import RollupTier

                self.rollups = RollupTier(self, self.config)

    def _devwindow_devices(self):
        """The mesh device list the sharded hot set pins its shards to
        (mesh_shape when set, else all local devices). Import failure
        or an unbuildable mesh degrades to default placement — the
        sharded path still runs, single-device."""
        try:
            import jax

            if self.config.mesh_shape:
                from opentsdb_tpu.parallel.plan import (
                    build_mesh, flatten_series_mesh)
                mesh = flatten_series_mesh(
                    build_mesh(self.config.mesh_shape))
                return list(mesh.devices.reshape(-1))
            return list(jax.local_devices())
        except Exception:
            return [None]

    def _warm_devwindow(self) -> None:
        """Mirror pre-existing storage (WAL-replayed memtable + sstable
        tiers) into the device window so it covers history from before
        this process started, not just new ingest.

        Corrupt storage (conflicting duplicates — IllegalDataError, the
        fsck signal) disables the window outright: a partially-warmed
        window would claim coverage it doesn't have, and fsck must be
        able to run against exactly this data."""
        from opentsdb_tpu.core.errors import IllegalDataError

        try:
            for key, cols in self.scan_columns(b"", b"\xff" * 64):
                if len(cols.timestamps) == 0:
                    continue
                pr = codec.parse_row_key(key)
                self.devwindow.append(pr.metric_uid,
                                      codec.series_key(key),
                                      cols.timestamps, cols.values)
        except IllegalDataError:
            self.devwindow = None

    # ------------------------------------------------------------------
    # Streaming sketches
    # ------------------------------------------------------------------

    def _sketch_path(self) -> str | None:
        wal = getattr(self.store, "_wal_path", None)
        return wal + ".sketches" if wal else None

    def _init_sketches(self) -> None:
        import os as _os

        from opentsdb_tpu.stats.livesketch import LiveSketches

        path = self._sketch_path()
        cfg = self.config
        if path and _os.path.exists(path):
            self.sketches = LiveSketches.load(
                path, flush_points=cfg.sketch_flush_points)
            # The snapshot covers the sstable tier (committed in the
            # checkpoint window, before the WAL truncation); the live
            # memtable holds the WAL-replayed post-checkpoint writes —
            # re-fold only those, reading rows WITHOUT tier merging so
            # spilled cells aren't folded twice.
            keys = getattr(self.store, "memtable_keys", None)
            cells = getattr(self.store, "memtable_cells", None)
            if keys is not None and cells is not None:
                self._refold(
                    (k, self.read_row(k, cells(self.table, k, FAMILY)))
                    for k in keys(self.table))
                return
        else:
            self.sketches = LiveSketches(
                compression=cfg.sketch_compression,
                hll_p=cfg.sketch_hll_p,
                flush_points=cfg.sketch_flush_points)
            if not getattr(self.store, "memtable_keys", None):
                return
        # No snapshot (or unknown store shape): rebuild from everything.
        self._refold(self.scan_columns(b"", b"\xff" * 64))

    def refresh_replica(self) -> bool:
        """One full replica catch-up cycle: raw store refresh (WAL
        suffix replay, or a rebuild when the writer checkpointed),
        sketch snapshot reload when a rebuild happened, then the
        read-only rollup tier — in THAT order, which is what makes
        replica-served rollup answers safe (ReadOnlyRollupTier's
        docstring carries the proof). The compaction timer (legacy
        --read-only daemons) and the serve tier's WalTailer both
        drive this. Returns True when the raw view changed."""
        if not getattr(self.store, "read_only", False):
            raise ValueError("refresh_replica() is for read-only "
                             "replica stores")
        before = getattr(self.store, "rebuilds", 0)
        changed = self.store.refresh()
        if getattr(self.store, "rebuilds", 0) != before:
            self.reload_sketches()
        tier = self.rollups
        if (tier is None and self.config.enable_rollups
                and getattr(self.store, "_wal_path", None)):
            # Construction failed at boot (writer mid-rebuild, torn
            # state file): keep trying each cycle so the tier is
            # adopted once the writer settles — a replica must not
            # serve raw forever over a transient boot race.
            from opentsdb_tpu.rollup.tier import ReadOnlyRollupTier
            try:
                self.rollups = tier = ReadOnlyRollupTier(self,
                                                         self.config)
            except Exception as e:
                LOG.debug("replica rollup tier still unavailable: %r",
                          e)
        if tier is not None and getattr(tier, "read_only", False):
            tier.refresh()
        return changed

    def promote(self, writer_epoch: int, epoch_guard=None) -> None:
        """Replica → writer takeover (the cluster failover's storage
        half; cluster/promote.py and the ``/promote`` endpoint drive
        it). The caller has already bumped the persisted epoch.

        Order matters: the store takes ownership first (fresh-inode
        WAL + epoch header, storage/kv.promote_writable), then the
        sketch state re-initializes in WRITER mode (snapshot load +
        memtable re-fold — the boot path), then the read-only rollup
        view swaps for the owning tier (adopting ROLLUP.json; a tier
        the dead writer left mid-fold rebuilds through the standard
        pending-marker catch-up). The store + sketch swap runs under
        the checkpoint lock; the rollup tier swap runs OUTSIDE it
        (lock discipline below). The device window stays off — a
        replica never had one, and a promoted writer serves through
        the scan path until its next restart."""
        with self._checkpoint_lock:
            self.store.promote_writable(writer_epoch,
                                        epoch_guard=epoch_guard)
            try:
                if self.config.enable_sketches:
                    self._init_sketches()
                if self.config.tenant_accounting:
                    # The promoted writer owns admission now: adopt
                    # the dead writer's TENANTS.json (or rebuild).
                    self._init_tenants()
                old = self.rollups
                self.rollups = None
            except BaseException:
                # The store already committed its takeover; a failure
                # in the post-store steps (torn sketch snapshot, EIO)
                # must not leave a HALF-promoted daemon — writable
                # store + bumped epoch but role still replica, which
                # would make a retried /promote short-circuit on
                # "already writer" over broken serving state. Demote
                # the store back so the caller's recovery (re-attach a
                # tailer, let the router try the next candidate) acts
                # on a genuine replica.
                self.tenants = None
                self.tenant_limits = None
                try:
                    self.store.demote_readonly()
                except Exception:
                    LOG.exception("rollback demote after failed "
                                  "promotion")
                raise
        # Rollup tier swap OUTSIDE the checkpoint lock — the same
        # discipline shutdown() documents: close() joins the tier's
        # catch-up thread, and the rebuild-completion commit takes
        # THIS lock (sync catch-up takes it in the constructor), so
        # doing either under it deadlocks. The window is safe in the
        # daemon flow: a promoting replica's compaction timer has
        # checkpoint_interval 0 until _do_promote restores it after
        # this returns, so no spill can race the tier-less gap.
        if old is not None:
            try:
                old.close()
            except Exception:
                LOG.exception("closing replica rollup view during "
                              "promotion")
        if (self.config.enable_rollups
                and getattr(self.store, "_wal_path", None)):
            from opentsdb_tpu.rollup.tier import RollupTier
            try:
                self.rollups = RollupTier(self, self.config)
            except Exception:
                # The promoted writer must SERVE even when the old
                # writer's tier is torn; raw answers stay exact and
                # the operator sees rollup.ready=0.
                LOG.exception("promoted writer rollup tier "
                              "unavailable; serving raw")

    def demote(self) -> None:
        """Writer → tailing replica, in place (a deposed writer that
        came back and was told so). The owning rollup tier closes
        BEFORE the store flips — its catch-up thread reads the raw
        store — then the store drops WAL + flock and rebuilds through
        the replica recovery path, sketches reload from the (new)
        writer's snapshot, and the read-only rollup view is adopted
        exactly as a replica boot would."""
        # The owning tier closes FIRST and OUTSIDE the checkpoint lock
        # (the shutdown() discipline): close() joins the catch-up
        # thread, which acquires this very lock for its completion
        # commit — joining it while holding the lock deadlocks the
        # daemon inside /demote. Detach the tier before closing so no
        # concurrent checkpoint brackets a spill against a
        # half-closed tier.
        with self._checkpoint_lock:
            old = self.rollups
            self.rollups = None
        if old is not None:
            try:
                old.close()
            except Exception:
                LOG.exception("closing rollup tier during demotion")
        with self._checkpoint_lock:
            # Queued row compactions are writer work: a demoted daemon
            # would only log ReadOnlyStoreError noise trying to write
            # them back. They're reconstructible soft state — the new
            # writer re-queues and compacts as it reads.
            with self.compactionq._lock:
                self.compactionq._queue.clear()
            self.store.demote_readonly()
            self.reload_sketches()
            # A replica neither admits nor snapshots tenant state —
            # the new writer owns TENANTS.json now.
            self.tenants = None
            self.tenant_limits = None
        if (self.config.enable_rollups
                and getattr(self.store, "_wal_path", None)):
            from opentsdb_tpu.rollup.tier import ReadOnlyRollupTier
            try:
                self.rollups = ReadOnlyRollupTier(self, self.config)
            except Exception:
                # refresh_replica retries adoption every cycle.
                LOG.exception("demoted daemon rollup view "
                              "unavailable; serving raw")

    def reload_sketches(self) -> None:
        """Replica catch-up: re-load the writer's sketch snapshot and
        re-fold the (freshly rebuilt) memtable on top. The refresh
        timer calls this whenever store.refresh() REBUILT — which
        happens on every writer checkpoint — so replica sketch lag is
        bounded by the writer's checkpoint cadence plus the poll
        interval (suffix replays between checkpoints are not folded;
        re-folding the whole memtable per poll would be O(window)
        every few seconds). Queries racing the swap keep a coherent
        reference to the previous sketch set."""
        if self.config.enable_sketches:
            self._init_sketches()

    def _refold(self, rows) -> None:
        for key, cols in rows:
            if len(cols.timestamps) == 0:
                continue
            pr = codec.parse_row_key(key)
            self.sketches.observe(
                codec.series_key(key), cols.values,
                [(pr.metric_uid, k, v) for k, v in pr.tag_uids])
        self.sketches.flush()

    def _observe(self, series_key: bytes, metric_uid: bytes,
                 pairs: list[tuple[bytes, bytes]],
                 values: np.ndarray) -> None:
        """Ingest-side sketch fold; callers pass the UIDs they already
        resolved (no row-key re-parse on the hot path)."""
        if self.sketches is None:
            return
        self.sketches.observe(
            series_key, values, [(metric_uid, k, v) for k, v in pairs])

    # ------------------------------------------------------------------
    # Tenant cardinality control plane (opentsdb_tpu/tenant/)
    # ------------------------------------------------------------------

    def _tenants_path(self) -> str | None:
        """TENANTS.json next to the WAL: inside the store directory
        for sharded stores (the SHARDS.json/EPOCH.json convention),
        ``<wal>.tenants.json`` for a single-file WAL (several single
        stores may share one directory in tests)."""
        wal = getattr(self.store, "_wal_path", None)
        if not wal:
            return None
        from opentsdb_tpu.tenant.accounting import STATE_NAME
        if getattr(self.store, "shard_count", None) is not None:
            # The sharded store's _wal_path is its <dir>/store naming
            # root (not a real directory); the snapshot lives beside
            # SHARDS.json at the store root.
            return os.path.join(os.path.dirname(wal), STATE_NAME)
        return wal + ".tenants.json"

    def _init_tenants(self) -> None:
        """Boot (or promotion) path: load the snapshot and re-fold the
        WAL-replayed memtable's series on top — the snapshot commits
        BEFORE each spill, so it always covers the sstable tier and
        the memtable delta is everything it can be missing. A torn or
        foreign state file rebuilds from a full storage scan instead
        (totals exact; per-tenant splits land on the default tenant,
        declared via recovered_series)."""
        from opentsdb_tpu.tenant.accounting import TenantAccountant
        from opentsdb_tpu.tenant.limits import (TenantLimiter,
                                                parse_overrides)

        cfg = self.config
        self.tenant_limits = TenantLimiter(
            max_series=getattr(cfg, "tenant_max_series", 0),
            global_max=getattr(cfg, "tenant_global_max_series", 0),
            mode=getattr(cfg, "tenant_limit_mode", "enforce"),
            overrides=parse_overrides(
                getattr(cfg, "tenant_overrides", ())))
        path = self._tenants_path()
        acct = None
        if path and os.path.exists(path):
            try:
                acct = TenantAccountant.load(
                    path, exact_cutoff=cfg.tenant_exact_cutoff,
                    hll_p=cfg.tenant_hll_p, topk=cfg.tenant_topk)
            except Exception as e:
                LOG.warning("TENANTS.json at %s torn/foreign (%r); "
                            "rebuilding tenant accounting from "
                            "storage", path, e)
        if acct is not None:
            # Delta fold: only series the WAL replayed past the
            # snapshot (the sketches _init_sketches discipline).
            keys = getattr(self.store, "memtable_keys", None)
            if keys is not None:
                acct.fold_recovered(
                    series_hash(codec.series_key(k))
                    for k in keys(self.table))
            else:
                acct.fold_recovered(self._storage_series_hashes())
        else:
            torn = bool(path and os.path.exists(path))
            acct = TenantAccountant(
                path=path, exact_cutoff=cfg.tenant_exact_cutoff,
                hll_p=cfg.tenant_hll_p, topk=cfg.tenant_topk)
            if torn or self.tenant_limits.enabled:
                # The full scan is semantically REQUIRED under
                # enforcement (the limiter must never refuse a
                # pre-existing series as "new"), and a torn snapshot
                # means accounting was live here — recover it exactly.
                acct.fold_recovered(self._storage_series_hashes())
            else:
                # Observability-only mode on a store with no snapshot
                # (first boot, or a pre-tenancy store upgrading):
                # don't block the constructor on a full raw-storage
                # scan nobody's limits need. No snapshot also means
                # no checkpoint ever committed, so any stored rows
                # live in the WAL-replayed memtable — fold just that
                # delta (sstable-backed stores only lack a snapshot
                # on upgrade, where counts re-attribute to their REAL
                # tenants as series next ingest and the first
                # checkpoint makes this a one-time transition).
                keys = getattr(self.store, "memtable_keys", None)
                if keys is not None:
                    acct.fold_recovered(
                        series_hash(codec.series_key(k))
                        for k in keys(self.table))
            acct.rebuilt = torn
        self.tenants = acct

    def _storage_series_hashes(self):
        """Every distinct series-identity hash currently in storage
        (raw key scan, no cell decode) — the rebuild source when the
        snapshot is gone."""
        seen: set[int] = set()
        for key, _items in self.store.scan_raw(self.table, b"",
                                               b"\xff" * 64):
            h = series_hash(codec.series_key(key))
            if h not in seen:
                seen.add(h)
                yield h

    def _admit_series(self, tenant: str, skey: bytes,
                      metric: str) -> None:
        """Tenant admission + accounting for one about-to-be-written
        series; raises TenantLimitError (enforce mode) when the series
        is NEW and the tenant (or the directory) is over budget.
        Counting happens here, BEFORE the storage put, mirroring the
        sketch directory's note_series placement: over-counting a
        series whose put then fails hard is harmless and bounded by
        the error count, while counting after would let a throttled
        partial batch leave stored rows that look refusable forever."""
        acct = self.tenants
        if acct is None:
            return
        h = series_hash(skey)
        if acct.seen(h):
            return
        self.tenant_limits.admit_new_series(acct, tenant)
        acct.note_new_series(tenant, h, metric)

    _SERIES_LABEL_CAP = 65536

    def _account_points(self, tenant: str, metric: str,
                        tag_map: dict, n: int, skey: bytes) -> None:
        if self.tenants is None or n <= 0:
            return
        label = self._series_labels.get(skey)
        if label is None:
            label = metric
            if tag_map:
                label += "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(tag_map.items())) + "}"
            if len(self._series_labels) >= self._SERIES_LABEL_CAP:
                self._series_labels.clear()
            self._series_labels[skey] = label
        self.tenants.note_points(tenant, label, n)

    # ------------------------------------------------------------------
    # Row-key construction
    # ------------------------------------------------------------------

    def resolve_tags(self, tag_map: dict[str, str],
                     create: bool = True) -> list[tuple[bytes, bytes]]:
        """Resolve tag names/values to UID pairs, sorted by tagk id.

        Sorting by the tag *name UID* matches the reference's
        resolveOrCreateAll + sort (Tags.java:308-348): row keys for one
        logical series are byte-identical regardless of input order.
        """
        get_k = self.tagk.get_or_create_id if create else self.tagk.get_id
        get_v = self.tagv.get_or_create_id if create else self.tagv.get_id
        pairs = [(get_k(k), get_v(v)) for k, v in tag_map.items()]
        pairs.sort()
        return pairs

    def _row_parts(self, metric: str, tag_map: dict[str, str],
                   create_metric: bool | None = None,
                   create_tags: bool = True,
                   ) -> tuple[bytes, list[tuple[bytes, bytes]]]:
        """(metric_uid, sorted tag UID pairs) for a series — the resolved
        parts row_key_for assembles, exposed so the write path can reuse
        them (sketch folds) without re-parsing the key it just built."""
        tags_mod.check_metric_and_tags(metric, tag_map)
        if create_metric is None:
            create_metric = self.config.auto_create_metrics
        metric_uid = (self.metrics.get_or_create_id(metric) if create_metric
                      else self.metrics.get_id(metric))
        return metric_uid, self.resolve_tags(tag_map, create_tags)

    def _row_parts_admitted(self, tenant: str, metric: str,
                            tag_map: dict[str, str],
                            ) -> tuple[bytes, list[tuple[bytes, bytes]]]:
        """``_row_parts`` behind the tenant gate. With enforcement on,
        resolve WITHOUT creating first: a missing UID means the series
        is certainly NEW, so the tenant/global budget check runs
        before ``get_or_create`` allocates durable UID mappings — a
        refused series must not grow the metric/tagk/tagv maps, since
        that growth is exactly the resource the limiter protects. When
        every UID resolves the combination may still be new, but the
        probe minted nothing and ``_admit_series`` settles it against
        the seen-set once the series hash exists."""
        if (self.tenants is None or not self.tenant_limits.enabled
                or self.tenant_limits.mode != "enforce"):
            return self._row_parts(metric, tag_map)
        try:
            return self._row_parts(metric, tag_map, create_metric=False,
                                   create_tags=False)
        except NoSuchUniqueName:
            if not self.config.auto_create_metrics:
                # The metric itself may be the missing piece, and it
                # can never be created here — that put dies as
                # "unknown metric" regardless of any budget, so it
                # must not masquerade as (or count toward) a tenant
                # refusal. Re-raises NoSuchUniqueName if so.
                self.metrics.get_id(metric)
            self.tenant_limits.admit_new_series(self.tenants, tenant)
            return self._row_parts(metric, tag_map)

    def row_key_for(self, metric: str, tag_map: dict[str, str],
                    base_ts: int, create_metric: bool | None = None,
                    create_tags: bool = True) -> bytes:
        metric_uid, pairs = self._row_parts(metric, tag_map,
                                            create_metric, create_tags)
        return codec.row_key(metric_uid, base_ts, pairs)

    # ------------------------------------------------------------------
    # Write path
    # ------------------------------------------------------------------

    def add_point(self, metric: str, timestamp: int, value: int | float,
                  tag_map: dict[str, str], durable: bool = True,
                  tenant: str = "default") -> None:
        """Store one data point (reference TSDB.addPoint :236-352)."""
        if timestamp & ~0xFFFFFFFF:
            raise ValueError(
                f"{'negative' if timestamp < 0 else 'bad'} "
                f"timestamp={timestamp} when trying to add value={value} "
                f"to metric={metric}, tags={tag_map}")
        if isinstance(value, bool):
            raise ValueError("boolean value")
        if isinstance(value, float):
            buf, flags = codec.encode_float(value)
        else:
            buf, flags = codec.encode_long(value)
        base_ts = codec.base_time(timestamp)
        metric_uid, pairs = self._row_parts_admitted(tenant, metric,
                                                     tag_map)
        row = codec.row_key(metric_uid, base_ts, pairs)
        qual = codec.encode_qualifier(timestamp - base_ts, flags)
        skey = codec.series_key(row)
        # Tenant admission first (a refused NEW series must leave no
        # trace — _row_parts_admitted already gated UID creation the
        # same way), then directory registration, then the put (see
        # add_batch for the ordering argument).
        self._admit_series(tenant, skey, metric)
        if self.sketches is not None:
            self.sketches.note_series(skey)
        self.store.put(self.table, row, FAMILY, qual, buf, durable=durable)
        # Scalar puts bypass the delta-fold feed (add_batch): their
        # coarse window must fall back to the full fold rescan.
        delta = getattr(self.rollups, "delta", None)
        if delta is not None:
            delta.invalidate(skey, base_ts)
        if self.config.enable_compactions:
            self.compactionq.add(row)
        self.datapoints_added += 1
        self._account_points(tenant, metric, tag_map, 1, skey)
        self._observe(skey, metric_uid, pairs,
                      np.asarray([value], np.float64))
        if self.devwindow is not None:
            self.devwindow.append(metric_uid, skey,
                                  np.asarray([timestamp], np.int64),
                                  np.asarray([value], np.float32))

    def add_batch(self, metric: str, timestamps: np.ndarray,
                  values: np.ndarray, tag_map: dict[str, str],
                  durable: bool = True,
                  is_float: np.ndarray | None = None,
                  int_values: np.ndarray | None = None,
                  tenant: str = "default", sync: bool = True) -> int:
        """Columnar ingest for one series: pre-compacted cell per row-hour.

        ``values`` may be an integer or floating dtype; float points are
        stored as 4-byte floats (matching telnet ingest), int points on
        their smallest widths. Pass ``is_float`` to type points
        individually within a float-dtyped ``values`` array (mixed series,
        like per-line telnet/import ingest produces) — and ``int_values``
        (int64) alongside it to keep integers above 2^53 exact, since
        float64 cannot represent them. ``sync=False`` skips the per-call
        WAL group-commit barrier so a multi-series caller can batch many
        series under one covering ``store.wal_barrier()`` before acking
        (no-op when group commit is off). Returns the points written.
        """
        timestamps = np.asarray(timestamps, dtype=np.int64)
        if timestamps.size == 0:
            return 0
        if (timestamps & ~np.int64(0xFFFFFFFF)).any():
            raise ValueError("timestamp out of range in batch")
        if is_float is not None:
            fmask = np.asarray(is_float, dtype=bool)
            fvals = np.asarray(values, dtype=np.float64)
            if int_values is not None:
                ivals = np.asarray(int_values, dtype=np.int64)
            else:
                ivals = np.where(fmask, 0, fvals).astype(np.int64)
        elif np.issubdtype(np.asarray(values).dtype, np.floating):
            fvals = np.asarray(values, dtype=np.float64)
            ivals = np.zeros_like(timestamps)
            fmask = np.ones(timestamps.shape, dtype=bool)
        else:
            ivals = np.asarray(values, dtype=np.int64)
            fvals = ivals.astype(np.float64)
            fmask = np.zeros(timestamps.shape, dtype=bool)

        # One vectorized pass for the whole series: global sort + dedup
        # (same-timestamp points are same-hour by definition), then all
        # row-hours' cells encoded in one flat-buffer pass.
        ts_s, f_s, i_s, m_s = codec_np.sort_dedup(
            timestamps, fvals, ivals, fmask)
        base = ts_s - ts_s % MAX_TIMESPAN
        deltas = ts_s - base
        row_starts = np.concatenate(
            ([0], np.flatnonzero(np.diff(base)) + 1))
        quals, vals = codec_np.encode_cells_multi(deltas, f_s, i_s, m_s,
                                                  row_starts)
        metric_uid, pairs = self._row_parts_admitted(tenant, metric,
                                                     tag_map)
        tmpl = bytes(codec.row_key(metric_uid, 0, pairs))
        # All row keys in one vectorized pass: broadcast the template,
        # stamp the base-time bytes, keep the CONTIGUOUS blob. The
        # per-row struct.pack + bytearray copy loop was ~15% of batch
        # ingest; the per-cell (key, qual, value) tuple list after it
        # was another ~1 us/row-hour, so the blob now flows straight
        # into put_many_columnar (which also writes it to the WAL
        # record as-is).
        L = len(tmpl)
        keys = np.tile(np.frombuffer(tmpl, np.uint8), (len(quals), 1))
        keys[:, UID_WIDTH:UID_WIDTH + TIMESTAMP_BYTES] = (
            base[row_starts].astype(">u4").view(np.uint8).reshape(-1, 4))
        kb = keys.tobytes()
        # The series enters the sketch slot DIRECTORY before any row
        # becomes visible in storage: the executor's bloom-pruning
        # hint treats the directory as a complete superset of series
        # with stored data, and registering after the put would leave
        # a window where a concurrent query prunes the shard holding
        # this series' first rows. (Values fold after the put as
        # before; over-registering an unapplied series is harmless.)
        skey = codec.series_key(kb[:L])
        # Tenant admission precedes both the directory registration
        # and the put: a NEW series from an over-budget tenant refuses
        # here (TenantLimitError, declared on the wire) before any
        # byte lands — existing series pass the seen-set check and
        # keep ingesting regardless of the tenant's budget.
        self._admit_series(tenant, skey, metric)
        if self.sketches is not None:
            self.sketches.note_series(skey)
        # Rows that already held cells BEFORE the put become multi-cell
        # and must be queued so the per-batch compacted cells merge into
        # one; the store reports that per row in a single locked pass.
        # A mid-batch throttle still queues the rows that DID apply.
        delta = getattr(self.rollups, "delta", None)
        try:
            existed = self.store.put_many_columnar(
                self.table, FAMILY, kb, L, quals, vals, durable=durable,
                sync=sync)
        except PleaseThrottleError as e:
            # Which rows landed is unknowable from here; the batch's
            # rollup windows can no longer be folded incrementally.
            if delta is not None:
                delta.kill_batch(skey, base[row_starts])
            existed = getattr(e, "partial_existed", [])
            if self.config.enable_compactions:
                for i, ex in enumerate(existed):
                    if ex:
                        self.compactionq.add(kb[i * L:(i + 1) * L])
            # Rows that DID apply are now in storage but will never be
            # appended to the device window (this raise skips it), and a
            # later retry of the batch would fail its monotonicity check
            # anyway — drop the metric's window so queries fall back to
            # the scan path instead of silently serving a partial view.
            if self.devwindow is not None:
                self.devwindow.invalidate(metric_uid)
            raise
        # any() is a C-level scan: the sustained-ingest shape is
        # all-new rows, where enumerating millions of False flags per
        # batch would cost more than the batch's dict inserts.
        if self.config.enable_compactions and any(existed):
            for i, e in enumerate(existed):
                if e:
                    self.compactionq.add(kb[i * L:(i + 1) * L])
        # Rollup delta accumulators (rollup/delta.py): the applied
        # batch's columns ARE what a checkpoint fold's raw rescan
        # would decode, so buffer them for the incremental fold path.
        if delta is not None:
            delta.feed(skey, ts_s, f_s, i_s, m_s, base, row_starts,
                       existed)
        n = len(ts_s)
        self.datapoints_added += n
        self._account_points(tenant, metric, tag_map, n, skey)
        # Sketch fold covers fully applied batches only (a throttled
        # batch raised above); values as stored, floats and ints alike.
        # One float32 conversion shared by both consumers (the digests
        # quantize to f32 anyway; the window stores f32).
        if self.sketches is not None or self.devwindow is not None:
            f32 = f_s.astype(np.float32)
            self._observe(skey, metric_uid, pairs, f32)
            if self.devwindow is not None:
                self.devwindow.append(metric_uid, skey, ts_s, f32)
        return n

    # ------------------------------------------------------------------
    # Compaction
    # ------------------------------------------------------------------

    def compact_row(self, key: bytes) -> None:
        """Merge all cells of a row into one compacted cell in storage.

        Parity: reference CompactionQueue.compact (:243-437) — single-cell
        rows are left alone (modulo the legacy float fix), the merged cell
        is written before the originals are deleted, and an original cell
        that already equals the merged form is never deleted-after-write.
        """
        delta = getattr(self.rollups, "delta", None)
        if delta is None:
            self._compact_row(key)
            return
        # Compaction preserves the row's point set: mark this thread's
        # deletes as preserving so the store delete hook doesn't kill
        # the row's rollup delta window (rollup/delta.py).
        delta.preserve.on = True
        try:
            self._compact_row(key)
        finally:
            delta.preserve.on = False

    def _compact_row(self, key: bytes) -> None:
        cells = self.store.get(self.table, key, FAMILY)
        if len(cells) <= 1:
            if cells:
                qual, val = cells[0].qualifier, cells[0].value
                if len(qual) == 2 and codec.needs_float_fix(qual[1], val):
                    fixed_val = codec.fix_float_value(qual[1], val)
                    fixed_qual = bytes([
                        qual[0],
                        codec.fix_qualifier_flags(qual[1], len(fixed_val))])
                    self.store.put(self.table, key, FAMILY, fixed_qual,
                                   fixed_val)
                    if fixed_qual != qual:
                        self.store.delete(self.table, key, FAMILY, [qual])
            return
        qual, val = codec.compact_cells(
            [(c.qualifier, c.value) for c in cells])
        existing = {c.qualifier: c.value for c in cells}
        if existing.get(qual) != val:
            self.store.put(self.table, key, FAMILY, qual, val)
            self.compactionq.written_cells += 1
        to_delete = [c.qualifier for c in cells if c.qualifier != qual]
        if to_delete:
            self.store.delete(self.table, key, FAMILY, to_delete)
            self.compactionq.deleted_cells += len(to_delete)

    def compact_cells(self, cells) -> tuple[bytes, bytes]:
        """In-memory merge used by the query path (no storage writes)."""
        return codec.compact_cells([(c.qualifier, c.value) for c in cells])

    # ------------------------------------------------------------------
    # Read path helpers
    # ------------------------------------------------------------------

    def read_row(self, key: bytes,
                 cells: list | None = None) -> codec.Columns:
        """Decode one row (possibly multi-cell) into sorted columnar arrays."""
        if cells is None:
            cells = self.store.get(self.table, key, FAMILY)
        base_ts = codec.key_base_time(key)
        kept = [c for c in cells
                if len(c.qualifier) % 2 == 0 and c.qualifier]
        if not kept:
            return codec.columns_concat([])
        ts, f, i, isf, _ = codec_np.decode_cells_flat(
            [c.qualifier for c in kept], [c.value for c in kept],
            np.full(len(kept), base_ts, np.int64))
        if len(kept) == 1:
            # compacted cells are sorted by construction
            return codec.Columns(ts, f, i, isf)
        d, f, i, isf = codec_np.sort_dedup(ts, f, i, isf)
        return codec.Columns(d, f, i, isf)

    def scan_rows(self, start_key: bytes, stop_key: bytes,
                  key_regexp: bytes | None = None,
                  ) -> Iterator[tuple[bytes, codec.Columns]]:
        """Ordered scan yielding (row_key, decoded columns)."""
        for cells in self.store.scan(self.table, start_key, stop_key,
                                     family=FAMILY, key_regexp=key_regexp):
            yield cells[0].key, self.read_row(cells[0].key, cells)

    def scan_columns(self, start_key: bytes, stop_key: bytes,
                     key_regexp: bytes | None = None,
                     batch_cells: int = 1 << 16,
                     series_hint=None,
                     ) -> Iterator[tuple[bytes, codec.Columns]]:
        """Batched scan decode: same rows as scan_rows, but cells decode
        in vectorized passes of ~``batch_cells`` cells
        (codec_np.decode_cells_flat) — the query read hot path, where
        per-row decode overhead would otherwise dominate wide scans.
        Yields per row at row-aligned batch boundaries, so peak memory
        holds one batch of raw bytes + its decoded arrays, not the whole
        range's (scan_rows-style streaming with the vectorized win)."""
        rows: list[tuple[bytes, int]] = []
        quals: list[bytes] = []
        vals: list[bytes] = []
        bases: list[int] = []

        def decode_batch():
            ts, f, i, isf, cop = codec_np.decode_cells_flat(
                quals, vals, np.asarray(bases, np.int64))
            starts = np.zeros(len(quals) + 1, np.int64)
            if len(quals):
                np.cumsum(np.bincount(cop, minlength=len(quals)),
                          out=starts[1:])
            out = []
            ci = 0
            for key, ncells in rows:
                a, b = int(starts[ci]), int(starts[ci + ncells])
                ci += ncells
                if ncells > 1:
                    d, ff, ii, mm = codec_np.sort_dedup(
                        ts[a:b], f[a:b], i[a:b], isf[a:b])
                    cols = codec.Columns(d, ff, ii, mm)
                else:
                    cols = codec.Columns(ts[a:b], f[a:b], i[a:b],
                                         isf[a:b])
                out.append((key, cols))
            rows.clear(), quals.clear(), vals.clear(), bases.clear()
            return out

        for key, items in self.store.scan_raw(
                self.table, start_key, stop_key,
                family=FAMILY, key_regexp=key_regexp,
                series_hint=series_hint):
            base = codec.key_base_time(key)
            kept = 0
            for q, v in items:
                if len(q) % 2 != 0 or not q:
                    continue  # foreign/annotation cells: skipped like
                    # read_row
                quals.append(q)
                vals.append(v)
                bases.append(base)
                kept += 1
            rows.append((key, kept))
            if len(quals) >= batch_cells:
                yield from decode_batch()
        if rows:
            yield from decode_batch()

    def scan_series(self, start_key: bytes, stop_key: bytes,
                    key_regexp: bytes | None = None,
                    batch_cells: int = 1 << 18,
                    series_hint=None):
        """Whole-range columnar scan regrouped BY SERIES in vectorized
        passes: returns (series_keys, per_series Columns dict) with one
        global (series, timestamp) lexsort + one vectorized dedup pass
        instead of per-row Columns objects and per-series
        re-concatenation. Profiled on the cold query path (the row-hour
        layout means ~10 points/row): per-row namedtuple construction +
        columns_concat of ~168 hour-parts per series cost more than the
        decode itself; here both collapse into a handful of
        whole-range numpy ops. Duplicate (series, ts) points collapse
        when value-equal and raise IllegalDataError otherwise —
        sort_dedup's rule (reference complexCompact :600-679)."""
        from opentsdb_tpu.core.errors import IllegalDataError
        quals: list[bytes] = []
        vals: list[bytes] = []
        bases: list[int] = []
        cell_sid: list[int] = []
        skey_index: dict[bytes, int] = {}
        skeys: list[bytes] = []
        parts: list[tuple] = []     # decoded (ts, f, i, isf, sid) batches

        def decode_batch():
            ts, f, i, isf, cop = codec_np.decode_cells_flat(
                quals, vals, np.asarray(bases, np.int64))
            sid = np.asarray(cell_sid, np.int64)[cop]
            parts.append((ts, f, i, isf, sid))
            quals.clear(), vals.clear(), bases.clear(), cell_sid.clear()

        for key, items in self.store.scan_raw(
                self.table, start_key, stop_key,
                family=FAMILY, key_regexp=key_regexp,
                series_hint=series_hint):
            base = codec.key_base_time(key)
            skey = codec.series_key(key)
            si = skey_index.get(skey)
            if si is None:
                si = skey_index[skey] = len(skeys)
                skeys.append(skey)
            for q, v in items:
                if len(q) % 2 != 0 or not q:
                    continue
                quals.append(q)
                vals.append(v)
                bases.append(base)
                cell_sid.append(si)
            if len(quals) >= batch_cells:
                decode_batch()
        if quals:
            decode_batch()
        if not parts:
            return skeys, {}
        ts = np.concatenate([p[0] for p in parts])
        f = np.concatenate([p[1] for p in parts])
        i = np.concatenate([p[2] for p in parts])
        isf = np.concatenate([p[3] for p in parts])
        sid = np.concatenate([p[4] for p in parts])
        order = np.lexsort((ts, sid))
        ts, f, i, isf, sid = (ts[order], f[order], i[order], isf[order],
                              sid[order])
        if len(ts) > 1:
            dup = (sid[1:] == sid[:-1]) & (ts[1:] == ts[:-1])
            if dup.any():
                same = ((isf[1:] == isf[:-1])
                        & np.where(isf[1:], f[1:] == f[:-1],
                                   i[1:] == i[:-1]))
                if (dup & ~same).any():
                    bad = int(ts[1:][dup & ~same][0])
                    raise IllegalDataError(
                        f"Found out of order or duplicate data: "
                        f"ts={bad} -- run an fsck.")
                keep = np.concatenate(([True], ~dup))
                ts, f, i, isf, sid = (ts[keep], f[keep], i[keep],
                                      isf[keep], sid[keep])
        bounds = np.searchsorted(sid, np.arange(len(skeys) + 1))
        per_series = {
            skeys[s]: codec.Columns(ts[a:b], f[a:b], i[a:b], isf[a:b])
            for s, (a, b) in enumerate(zip(bounds[:-1], bounds[1:]))
            if b > a}
        return skeys, per_series

    # ------------------------------------------------------------------
    # Suggest / admin / lifecycle
    # ------------------------------------------------------------------

    def suggest_metrics(self, prefix: str = "") -> list[str]:
        return self.metrics.suggest(prefix)

    def suggest_tag_names(self, prefix: str = "") -> list[str]:
        return self.tagk.suggest(prefix)

    def suggest_tag_values(self, prefix: str = "") -> list[str]:
        return self.tagv.suggest(prefix)

    def drop_caches(self) -> None:
        self.metrics.drop_caches()
        self.tagk.drop_caches()
        self.tagv.drop_caches()

    def flush(self) -> None:
        """Flush compactions then the storage engine (reference :384-417)."""
        self.compactionq.flush(cutoff=int(time.time()) - MAX_TIMESPAN - 1)
        self.store.flush()

    def checkpoint(self) -> int:
        """Spill memtable state to the sstable tier and truncate the WAL
        (the TPU build's checkpoint/resume story, SURVEY §5.4). Returns
        rows spilled, 0 when the store is non-persistent.

        The sketch snapshot commits BEFORE the storage spill: the spill
        truncates the WAL, so committing after would mean a crash in
        between loses every fold since the previous snapshot (nothing
        left to replay). Committing first over-covers instead — a crash
        before the spill leaves a snapshot that already includes the
        still-replayable memtable, and recovery's re-fold double-counts
        it: exact for HLLs (register max is idempotent), within sketch
        tolerance for digests (the tradeoff the module doc accepts)."""
        if getattr(self.store, "read_only", False):
            # A replica owns neither the sketch snapshot nor the spill
            # tier; writing either would race the writer daemon.
            return 0
        # One checkpoint at a time (see _checkpoint_lock): the rollup
        # bracketing below is only sound when THIS call's store spill is
        # the one between its begin_spill and fold_after_spill.
        with self._checkpoint_lock:
            path = self._sketch_path()
            if self.sketches is not None and path:
                self.sketches.save(path)
            # Tenant accounting snapshot, same bracket position and
            # the same coverage argument: committed BEFORE the spill,
            # so a loaded TENANTS.json always covers the sstable tier
            # and boot only re-folds the replayed memtable's series.
            if self.tenants is not None:
                self.tenants.save()
            # Rollup tier brackets the spill: mark the about-to-spill
            # windows in flight (and the tier pending on disk) BEFORE the
            # raw spill, fold the spilled keys into summary records after —
            # a crash in between leaves the pending marker and the next
            # open rebuilds (rollup/tier.py consistency contract).
            rollups = getattr(self, "rollups", None)  # early-timer safety
            if rollups is not None:
                rollups.begin_spill()
            ckpt = getattr(self.store, "checkpoint", None)
            rows = ckpt() if ckpt else 0
            if rollups is not None:
                rollups.fold_after_spill()
            return rows

    def shutdown(self) -> None:
        # Idempotent: the CLI dispatcher sweeps any TSDB a command
        # opened (exception/early-return safety net), which may run
        # after the command already shut down cleanly itself.
        if getattr(self, "_shutdown_done", False):
            return
        self._shutdown_done = True
        try:
            self.compactionq.shutdown()
            if self.sketches is not None and self._sketch_path():
                # Spill + snapshot in one window: the snapshot's
                # coverage contract (== the sstable tier) must hold on
                # the next boot, where the replayed memtable is
                # re-folded on top of it.
                self.checkpoint()
            elif self.tenants is not None and self.tenants.path:
                # Tenant snapshot WITHOUT forcing a spill: accounting
                # folds are idempotent by series hash, so a snapshot
                # covering MORE than the sstable tier is harmless on
                # the next boot (the WAL replay's re-fold dedups) —
                # and it keeps exact per-tenant attribution for the
                # memtable-resident series instead of re-attributing
                # them to the default tenant at reopen.
                self.tenants.save()
            self.store.flush()
        finally:
            # Rollups close FIRST: their close() stops + joins the
            # catch-up thread, which READS the raw store — closing the
            # store before the thread stops would make the rebuild die
            # on closed fds with _stop unset and be misrecorded as a
            # catch-up FAILURE (spurious _rebuild_error) instead of an
            # orderly shutdown abort.
            try:
                if getattr(self, "rollups", None) is not None:
                    self.rollups.close()
            finally:
                # The store MUST close even when checkpoint/flush (or
                # the rollup close) raise — ENOSPC is a first-class
                # path: close releases the WAL's single-writer flock,
                # without which every later open of this path in the
                # process is refused.
                try:
                    close = getattr(self.store, "close", None)
                    if close:
                        close()
                finally:
                    dereg, self._deregister = self._deregister, None
                    if dereg:
                        dereg()

    def collect_stats(self, collector) -> None:
        """Push internal counters into a StatsCollector (reference :129-175)."""
        collector.record("datapoints.added", self.datapoints_added)
        for uid in (self.metrics, self.tagk, self.tagv):
            kind = uid.kind()
            collector.record("uid.cache-hit", uid.cache_hits, f"kind={kind}")
            collector.record("uid.cache-miss", uid.cache_misses,
                             f"kind={kind}")
            collector.record("uid.cache-size", uid.cache_size(),
                             f"kind={kind}")
        wal_errs = getattr(self.store, "wal_swallowed_flush_errors", None)
        if wal_errs is not None:
            collector.record("storage.wal.swallowed_flush_errors",
                             wal_errs)
        nshards = getattr(self.store, "shard_count", None)
        if nshards is not None:
            collector.record("storage.shards", nshards)
        rows_fn = getattr(self.store, "memtable_row_counts", None)
        if rows_fn is not None:
            # Live-memtable row count per shard: the skew view (one
            # hot shard = one slow spill join) the per-shard spill
            # timers explain after the fact; this shows it live.
            for i, n in enumerate(rows_fn(self.table)):
                collector.record("storage.memtable.rows", n,
                                 f"shard={i}")
        fmt_fn = getattr(self.store, "sstable_format_bytes", None)
        if fmt_fn is not None:
            for fmt, nbytes in sorted(fmt_fn().items()):
                collector.record("sstable.bytes", nbytes,
                                 f"format=v{fmt}")
        comp_fn = getattr(self.store, "compress_stats", None)
        if comp_fn is not None:
            raw, enc = comp_fn()
            if enc:
                # Uncompressed-record bytes per stored byte across the
                # v4 generations — `tsdb check --stats-metric
                # tsd.compress.ratio -x lt 1.5` alerts on a corpus
                # that stopped compressing.
                collector.record("compress.ratio",
                                 round(raw / enc, 4))
        bloom_files = getattr(self.store, "bloom_files_skipped", None)
        if bloom_files is not None:
            collector.record("bloom.files_skipped", bloom_files)
        bloom_shards = getattr(self.store, "bloom_shards_skipped", None)
        if bloom_shards is not None:
            collector.record("bloom.shards_skipped", bloom_shards)
        bloom_points = getattr(self.store, "bloom_point_skips", None)
        if bloom_points is not None:
            collector.record("bloom.point_skips", bloom_points)
        dirty = getattr(self.store, "dirty_bases", None)
        if dirty is not None:
            collector.record("dirty_set.size",
                             int(len(dirty(self.table))))
        if self.cluster_epoch_path:
            # Writers export the epoch they OWN; replicas (and a
            # fenced ex-writer) export the persisted file's view —
            # divergence between daemons is exactly the skew signal
            # the check tool alerts on.
            epoch = getattr(self.store, "writer_epoch", None)
            if epoch is None:
                from opentsdb_tpu.cluster.epoch import read_epoch
                try:
                    epoch, _ = read_epoch(self.cluster_epoch_path)
                except (OSError, ValueError, KeyError):
                    epoch = None
            if epoch is not None:
                collector.record("cluster.epoch", int(epoch))
            guard = getattr(self.store, "epoch_guard", None)
            if guard is not None:
                collector.record("cluster.fenced", int(guard.fenced))
            refused = getattr(self.store, "fenced_bytes_refused", 0)
            if refused:
                collector.record("cluster.fenced_bytes_refused",
                                 refused)
        cq = self.compactionq
        collector.record("compaction.count", cq.written_cells)
        collector.record("compaction.deleted_cells", cq.deleted_cells)
        collector.record("compaction.errors", cq.errors)
        collector.record("compaction.queue.size", len(cq))
        if self.sketches is not None:
            collector.record("sketches.series",
                             self.sketches.series_count())
        if self.tenants is not None:
            self.tenants.collect_stats(collector)
        if self.devwindow is not None:
            self.devwindow.collect_stats(collector)
        if self.rollups is not None:
            self.rollups.collect_stats(collector)
