"""Exceptions shared across the framework."""


class IllegalDataError(Exception):
    """Corrupt or semantically invalid stored data.

    Raised by the codec / compaction paths on out-of-order duplicates,
    undecodable cells, or malformed values (parity with the reference's
    net.opentsdb.core.IllegalDataException).
    """


class BadRequestError(Exception):
    """An HTTP 400-class client error (reference src/tsd/BadRequestException.java)."""

    def __init__(self, message: str, status: int = 400):
        super().__init__(message)
        self.status = status


class PleaseThrottleError(Exception):
    """Backpressure signal from the storage engine.

    Parity with asynchbase's PleaseThrottleException: callers should slow
    down, switch to synchronous writes, or re-enqueue the work (reference
    CompactionQueue.java:797-808, TextImporter.java:106-126).
    """


class ReadOnlyStoreError(Exception):
    """A mutation was attempted on a read-only store replica.

    Read-only stores open another daemon's WAL/sstable state without
    the single-writer lock (the N-TSDs-over-one-store deployment
    shape, reference README:8-17); every write path refuses with this.

    Subclasses Exception (like PleaseThrottleError), NOT OSError: a
    broad ``except OSError`` around storage I/O must never silently
    swallow a replica's write refusal as if it were a disk error.
    """


class OverloadedError(Exception):
    """The admission controller shed this request (serve/admission.py).

    Carries the Retry-After hint (seconds) and the HTTP status the
    server should answer with: 429 for a per-tenant quota breach, 503
    for process-wide load shedding. Raised by the query path when the
    degraded (rollup-only) ladder step cannot serve a query at all.
    """

    def __init__(self, message: str, retry_after: float = 1.0,
                 status: int = 503):
        super().__init__(message)
        self.retry_after = max(float(retry_after), 0.0)
        self.status = status


class TenantLimitError(Exception):
    """A NEW series was refused by the tenant cardinality limiter
    (opentsdb_tpu/tenant/limits.py).

    Declared, not transient: the telnet face is a distinct
    ``put: tenant series limit exceeded`` line and the HTTP face is a
    429 naming the limit — a collector (or the router) must NOT treat
    this like a throttle and retry, because retrying a refused series
    can never succeed until the operator raises the limit (or a
    per-tenant override). The accountant is deliberately MONOTONIC —
    deleting series never lowers a tenant's count (the HLL tier
    cannot forget, and the exact tier matches it so behavior doesn't
    change at the cutoff); only a limit change, or a full
    storage-scan rebuild after a lost TENANTS.json, moves the count
    down. Existing series keep ingesting.

    Subclasses Exception (the ReadOnlyStoreError precedent), NOT
    OSError: broad ``except OSError`` storage handlers must never
    swallow a policy refusal as a disk hiccup.
    """

    status = 429

    def __init__(self, tenant: str, limit: int, count: int,
                 scope: str = "tenant"):
        super().__init__(
            f"{'global' if scope == 'global' else f'tenant {tenant!r}'}"
            f" series limit exceeded: {count} >= {limit} "
            f"(new series refused; existing series keep ingesting)")
        self.tenant = tenant
        self.limit = limit
        self.count = count
        self.scope = scope


class FencedWriterError(Exception):
    """This writer's epoch has been superseded (cluster/epoch.py).

    A monotonically increasing writer epoch is persisted next to the
    WAL (EPOCH.json); a replica promotion bumps it. A deposed writer
    that keeps running — wedged through its health grace, then woken —
    sees the bump on its next fence check and every mutation refuses
    with this error instead of silently split-braining the store.

    Subclasses Exception (the ReadOnlyStoreError precedent), NOT
    OSError: broad ``except OSError`` handlers around storage I/O must
    never swallow a fence refusal as a disk hiccup — the writer is no
    longer the writer, and the caller has to hear it.
    """

    def __init__(self, message: str, own_epoch: int = 0,
                 current_epoch: int = 0):
        super().__init__(message)
        self.own_epoch = own_epoch
        self.current_epoch = current_epoch


class NoSuchUniqueName(Exception):
    """Name -> UID lookup failed (reference src/uid/NoSuchUniqueName.java)."""

    def __init__(self, kind: str, name: str):
        super().__init__(f"No such name for '{kind}': '{name}'")
        self.kind = kind
        self.name = name


class NoSuchUniqueId(Exception):
    """UID -> name lookup failed (reference src/uid/NoSuchUniqueId.java)."""

    def __init__(self, kind: str, uid: bytes):
        super().__init__(f"No such unique ID for '{kind}': {uid.hex()}")
        self.kind = kind
        self.id = uid
