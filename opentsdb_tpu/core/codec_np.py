"""Vectorized batch codecs: byte cells <-> columnar numpy arrays.

The scalar codec (codec.py) is the semantics oracle; this module is the hot
path. Batch ingest encodes thousands of points per call (one compacted cell
per row-hour, skipping the reference's write-then-compact amplification
entirely), and queries decode compacted cells straight into the arrays the
TPU kernels consume — no per-point Python.

Wire format is identical to codec.py (and the reference): qualifiers are
big-endian uint16 ``(delta << 4) | flags``; int values big-endian two's
complement on the smallest of 1/2/4/8 bytes; floats IEEE754 single (4 B).
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.core.codec import Columns
from opentsdb_tpu.core.const import FLAG_BITS, FLAG_FLOAT, LENGTH_MASK
from opentsdb_tpu.core.errors import IllegalDataError

_INT_WIDTH_BOUNDS = (
    (1, -0x80, 0x7F),
    (2, -0x8000, 0x7FFF),
    (4, -0x80000000, 0x7FFFFFFF),
    (8, -0x8000000000000000, 0x7FFFFFFFFFFFFFFF),
)


def int_widths(int_values: np.ndarray) -> np.ndarray:
    """Per-point smallest encoding width (1/2/4/8) for int64 values."""
    w = np.full(int_values.shape, 8, dtype=np.int64)
    for width, lo, hi in _INT_WIDTH_BOUNDS[:3][::-1]:
        w = np.where((int_values >= lo) & (int_values <= hi), width, w)
    return w


def encode_cell(deltas: np.ndarray, float_values: np.ndarray,
                int_values: np.ndarray, is_float: np.ndarray,
                ) -> tuple[bytes, bytes]:
    """Encode one row's points into a compacted (qualifier, value) cell.

    Inputs must be sorted by delta and deduplicated (see ``sort_dedup``).
    Floats are stored on 4 bytes (IEEE754 single), matching the reference's
    telnet ingest (TSDB.java:321-328); ints on their smallest width.
    Returns (qualifier_bytes, value_bytes) — with the trailing 0x00 meta
    byte only for multi-point cells: a 2-byte qualifier means "single data
    point, raw value" on the wire, so single-point cells omit it.
    """
    if len(deltas) == 0:
        raise ValueError("empty cell")
    return encode_cells_multi(deltas, float_values, int_values, is_float,
                              np.array([0]))[0]


def encode_cells_multi(deltas: np.ndarray, float_values: np.ndarray,
                       int_values: np.ndarray, is_float: np.ndarray,
                       row_starts: np.ndarray,
                       ) -> list[tuple[bytes, bytes]]:
    """Encode MANY rows' points in one vectorized pass.

    Points must be sorted by row then delta, deduplicated, with
    ``row_starts`` marking each row's first index (ascending, starting at
    0). All qualifier/value bytes are computed in two flat buffers and
    sliced per row — no per-point Python. Returns one (qualifier, value)
    cell per row, with the trailing meta byte on multi-point cells.
    """
    n = len(deltas)
    if n == 0:
        raise ValueError("empty batch")
    deltas = np.asarray(deltas, dtype=np.int64)
    if ((deltas < 0) | (deltas >= 3600)).any():
        raise ValueError("time delta out of range in batch")
    is_float = np.asarray(is_float, dtype=bool)
    widths = np.where(is_float, 4, int_widths(np.asarray(int_values)))
    flags = np.where(is_float, FLAG_FLOAT | 0x3, widths - 1)
    quals = ((deltas << FLAG_BITS) | flags).astype(">u2").tobytes()

    offsets = np.zeros(n, dtype=np.int64)
    np.cumsum(widths[:-1], out=offsets[1:])
    total = int(offsets[-1] + widths[-1]) if n else 0
    buf = np.zeros(total, dtype=np.uint8)
    if is_float.any():
        fbytes = np.asarray(float_values)[is_float].astype(">f4") \
            .view(np.uint8).reshape(-1, 4)
        pos = offsets[is_float, None] + np.arange(4)
        buf[pos.ravel()] = fbytes.ravel()
    ivals = np.asarray(int_values)
    for width in (1, 2, 4, 8):
        m = (~is_float) & (widths == width)
        if not m.any():
            continue
        wbytes = ivals[m].astype(">i8").view(np.uint8) \
            .reshape(-1, 8)[:, 8 - width:]
        pos = offsets[m, None] + np.arange(width)
        buf[pos.ravel()] = wbytes.ravel()
    vbytes = buf.tobytes()

    row_starts = np.asarray(row_starts, dtype=np.int64)
    row_ends = np.append(row_starts[1:], n)
    val_starts = offsets[row_starts]
    val_ends = np.append(val_starts[1:], total)
    out = []
    for i in range(len(row_starts)):
        a, b = int(row_starts[i]), int(row_ends[i])
        va, vb = int(val_starts[i]), int(val_ends[i])
        v = vbytes[va:vb]
        if b - a > 1:
            v += b"\x00"
        out.append((quals[2 * a:2 * b], v))
    return out


def decode_cell(qual: bytes, value: bytes, base_ts: int) -> Columns:
    """Decode a cell (single-point or compacted) into columnar arrays.

    Vectorized equivalent of codec.explode_cell + cells_to_columns, with the
    same validation: trailing 0x00 meta byte on compacted cells, exact value
    consumption, legacy 8-byte float repair on single cells.
    """
    nq = len(qual)
    if nq == 0 or nq % 2 != 0:
        raise IllegalDataError(f"invalid qualifier length {nq}")
    quals = np.frombuffer(qual, dtype=">u2").astype(np.int64)
    deltas = quals >> FLAG_BITS
    flags = quals & (FLAG_FLOAT | LENGTH_MASK)
    is_float = (flags & FLAG_FLOAT) != 0
    widths = (flags & LENGTH_MASK) + 1

    vbuf = np.frombuffer(value, dtype=np.uint8)
    if nq == 2:
        # Single cell: tolerate the legacy float-on-8-bytes encoding and
        # ints whose length disagrees with the flags (flags were unreliable
        # pre-compaction; the value length is the truth, like the
        # reference's RowSeq extractors).
        if is_float[0] and widths[0] == 4 and len(value) == 8:
            if value[:4] != b"\x00\x00\x00\x00":
                raise IllegalDataError(
                    f"Corrupted floating point value: {value.hex()}")
            vbuf = vbuf[4:]
        widths[0] = len(vbuf)
    else:
        if len(value) == 0 or value[-1] != 0:
            raise IllegalDataError(
                "compacted value lacks the 0x00 meta byte (future format?)")
    offsets = np.zeros(len(widths), dtype=np.int64)
    np.cumsum(widths[:-1], out=offsets[1:])
    consumed = int(offsets[-1] + widths[-1])
    if nq > 2 and consumed != len(value) - 1:
        raise IllegalDataError(
            f"Corrupted value: couldn't break down into individual values "
            f"(consumed {consumed} bytes, but was expecting to consume "
            f"{len(value) - 1})")
    if nq == 2 and consumed != len(vbuf):
        raise IllegalDataError("single-cell value length mismatch")

    n = len(deltas)
    fvals = np.zeros(n, dtype=np.float64)
    ivals = np.zeros(n, dtype=np.int64)

    fmask = is_float & (widths == 4)
    if fmask.any():
        pos = offsets[fmask, None] + np.arange(4)
        fvals[fmask] = vbuf[pos.ravel()].reshape(-1, 4) \
            .view(">f4").astype(np.float64).ravel()
    dmask = is_float & (widths == 8)
    if dmask.any():
        pos = offsets[dmask, None] + np.arange(8)
        fvals[dmask] = vbuf[pos.ravel()].reshape(-1, 8).view(">f8").ravel()
    bad_float = is_float & ~(widths == 4) & ~(widths == 8)
    if bad_float.any():
        raise IllegalDataError("unsupported float width in cell")
    bad_int = (~is_float) & ~np.isin(widths, (1, 2, 4, 8))
    if bad_int.any():
        raise IllegalDataError(
            f"Invalid integer value length {int(widths[bad_int][0])}")
    for width, dtype in ((1, ">i1"), (2, ">i2"), (4, ">i4"), (8, ">i8")):
        m = (~is_float) & (widths == width)
        if not m.any():
            continue
        pos = offsets[m, None] + np.arange(width)
        ivals[m] = vbuf[pos.ravel()].reshape(-1, width) \
            .view(dtype).astype(np.int64).ravel()
    fvals = np.where(is_float, fvals, ivals.astype(np.float64))
    return Columns(base_ts + deltas, fvals, ivals, is_float)


def sort_dedup(deltas: np.ndarray, float_values: np.ndarray,
               int_values: np.ndarray, is_float: np.ndarray,
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Sort one row's points by delta and drop duplicate deltas.

    Equal (delta, type, value) duplicates collapse silently; conflicting
    values at one delta raise IllegalDataError — the same tombstone-or-fsck
    rule as the compaction merge (reference complexCompact :600-679).
    Last-writer order within the input is irrelevant because conflicts are
    errors, not overwrites.
    """
    deltas = np.asarray(deltas)
    order = np.argsort(deltas, kind="stable")
    d = deltas[order]
    f = np.asarray(float_values)[order]
    i = np.asarray(int_values)[order]
    isf = np.asarray(is_float)[order]
    if len(d) > 1:
        dup = d[1:] == d[:-1]
        if dup.any():
            same_type = isf[1:] == isf[:-1]
            same_val = np.where(isf[1:], f[1:] == f[:-1], i[1:] == i[:-1])
            if (dup & ~(same_type & same_val)).any():
                bad = int(d[1:][dup & ~(same_type & same_val)][0])
                raise IllegalDataError(
                    f"Found out of order or duplicate data: delta={bad}"
                    " -- run an fsck.")
            keep = np.concatenate(([True], ~dup))
            d, f, i, isf = d[keep], f[keep], i[keep], isf[keep]
    return d, f, i, isf
