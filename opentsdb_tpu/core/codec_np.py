"""Vectorized batch codecs: byte cells <-> columnar numpy arrays.

The scalar codec (codec.py) is the semantics oracle; this module is the hot
path. Batch ingest encodes thousands of points per call (one compacted cell
per row-hour, skipping the reference's write-then-compact amplification
entirely), and queries decode compacted cells straight into the arrays the
TPU kernels consume — no per-point Python.

Wire format is identical to codec.py (and the reference): qualifiers are
big-endian uint16 ``(delta << 4) | flags``; int values big-endian two's
complement on the smallest of 1/2/4/8 bytes; floats IEEE754 single (4 B).
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.core.codec import Columns
from opentsdb_tpu.core.const import FLAG_BITS, FLAG_FLOAT, LENGTH_MASK
from opentsdb_tpu.core.errors import IllegalDataError
from opentsdb_tpu.utils.nativeext import ext as _EXT

_INT_WIDTH_BOUNDS = (
    (1, -0x80, 0x7F),
    (2, -0x8000, 0x7FFF),
    (4, -0x80000000, 0x7FFFFFFF),
    (8, -0x8000000000000000, 0x7FFFFFFFFFFFFFFF),
)


def int_widths(int_values: np.ndarray) -> np.ndarray:
    """Per-point smallest encoding width (1/2/4/8) for int64 values."""
    w = np.full(int_values.shape, 8, dtype=np.int64)
    for width, lo, hi in _INT_WIDTH_BOUNDS[:3][::-1]:
        w = np.where((int_values >= lo) & (int_values <= hi), width, w)
    return w


def encode_cell(deltas: np.ndarray, float_values: np.ndarray,
                int_values: np.ndarray, is_float: np.ndarray,
                ) -> tuple[bytes, bytes]:
    """Encode one row's points into a compacted (qualifier, value) cell.

    Inputs must be sorted by delta and deduplicated (see ``sort_dedup``).
    Floats are stored on 4 bytes (IEEE754 single), matching the reference's
    telnet ingest (TSDB.java:321-328); ints on their smallest width.
    Returns (qualifier_bytes, value_bytes) — with the trailing 0x00 meta
    byte only for multi-point cells: a 2-byte qualifier means "single data
    point, raw value" on the wire, so single-point cells omit it.
    """
    if len(deltas) == 0:
        raise ValueError("empty cell")
    qs, vs = encode_cells_multi(deltas, float_values, int_values,
                                is_float, np.array([0]))
    return qs[0], vs[0]


def encode_cells_multi(deltas: np.ndarray, float_values: np.ndarray,
                       int_values: np.ndarray, is_float: np.ndarray,
                       row_starts: np.ndarray,
                       ) -> tuple[list[bytes], list[bytes]]:
    """Encode MANY rows' points in one vectorized pass.

    Points must be sorted by row then delta, deduplicated, with
    ``row_starts`` marking each row's first index (ascending, starting at
    0). All qualifier/value bytes are computed in two flat buffers and
    sliced per row — no per-point Python. Returns (qualifiers, values):
    two parallel lists with one entry per row, the trailing meta byte on
    multi-point cells' values.
    """
    n = len(deltas)
    if n == 0:
        raise ValueError("empty batch")
    deltas = np.asarray(deltas, dtype=np.int64)
    if ((deltas < 0) | (deltas >= 3600)).any():
        raise ValueError("time delta out of range in batch")
    is_float = np.asarray(is_float, dtype=bool)
    all_float = bool(is_float.all())
    if all_float:
        # The telnet/collector hot shape: every point a 4-byte float,
        # so the value buffer is just the packed f32 column — no width
        # computation, no offset cumsum, no fancy-index scatter (the
        # scatter alone cost ~0.5 s per 10M points).
        widths = None
        flags = np.int64(FLAG_FLOAT | 0x3)
        quals = ((deltas << FLAG_BITS) | flags).astype(">u2").tobytes()
        vbytes = np.asarray(float_values).astype(">f4").tobytes()
        offsets = None
    else:
        widths = np.where(is_float, 4, int_widths(np.asarray(int_values)))
        flags = np.where(is_float, FLAG_FLOAT | 0x3, widths - 1)
        quals = ((deltas << FLAG_BITS) | flags).astype(">u2").tobytes()

        offsets = np.zeros(n, dtype=np.int64)
        np.cumsum(widths[:-1], out=offsets[1:])
        total = int(offsets[-1] + widths[-1]) if n else 0
        buf = np.zeros(total, dtype=np.uint8)
        if is_float.any():
            fbytes = np.asarray(float_values)[is_float].astype(">f4") \
                .view(np.uint8).reshape(-1, 4)
            pos = offsets[is_float, None] + np.arange(4)
            buf[pos.ravel()] = fbytes.ravel()
        ivals = np.asarray(int_values)
        for width in (1, 2, 4, 8):
            m = (~is_float) & (widths == width)
            if not m.any():
                continue
            wbytes = ivals[m].astype(">i8").view(np.uint8) \
                .reshape(-1, 8)[:, 8 - width:]
            pos = offsets[m, None] + np.arange(width)
            buf[pos.ravel()] = wbytes.ravel()
        vbytes = buf.tobytes()

    row_starts = np.asarray(row_starts, dtype=np.int64)
    row_ends = np.append(row_starts[1:], n)
    if all_float:
        val_starts = row_starts * 4
        val_ends = row_ends * 4
    else:
        val_starts = offsets[row_starts]
        val_ends = np.append(val_starts[1:], total)
    if _EXT is not None:
        return _EXT.slice_cells(
            quals, vbytes,
            np.ascontiguousarray(row_starts).tobytes(),
            np.ascontiguousarray(row_ends).tobytes(),
            np.ascontiguousarray(val_starts, np.int64).tobytes(),
            np.ascontiguousarray(val_ends, np.int64).tobytes())
    # tolist() yields native ints once (indexing numpy scalars per row
    # plus int() casts cost ~2.7 us/row across millions of row-hours);
    # list comprehensions beat an append loop by ~30% on top. Two
    # parallel lists, not tuples: the caller feeds put_many_columnar,
    # and a tuple per row-hour was ~1 us of pure allocation.
    rs, re_ = row_starts.tolist(), row_ends.tolist()
    out_quals = [quals[2 * a:2 * b] for a, b in zip(rs, re_)]
    out_vals = [
        vbytes[va:vb] + b"\x00" if b - a > 1 else vbytes[va:vb]
        for a, b, va, vb in zip(rs, re_, val_starts.tolist(),
                                val_ends.tolist())]
    return out_quals, out_vals


def decode_cell(qual: bytes, value: bytes, base_ts: int) -> Columns:
    """Decode a cell (single-point or compacted) into columnar arrays.

    Thin wrapper over ``decode_cells_flat`` (C=1) so there is exactly one
    decode implementation: same validation (trailing 0x00 meta byte on
    compacted cells, exact value consumption, legacy 8-byte float repair
    on single cells) — the vectorized equivalent of codec.explode_cell +
    cells_to_columns.
    """
    ts, fvals, ivals, is_float, _ = decode_cells_flat(
        [qual], [value], np.asarray([base_ts], np.int64))
    return Columns(ts, fvals, ivals, is_float)



def sort_dedup(deltas: np.ndarray, float_values: np.ndarray,
               int_values: np.ndarray, is_float: np.ndarray,
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Sort one row's points by delta and drop duplicate deltas.

    Equal (delta, type, value) duplicates collapse silently; conflicting
    values at one delta raise IllegalDataError — the same tombstone-or-fsck
    rule as the compaction merge (reference complexCompact :600-679).
    Last-writer order within the input is irrelevant because conflicts are
    errors, not overwrites.
    """
    deltas = np.asarray(deltas)
    if len(deltas) > 1 and (deltas[1:] >= deltas[:-1]).all():
        # The collector pattern: batches arrive time-sorted, and one
        # O(n) monotonicity check beats the O(n log n) argsort + four
        # gathers it replaces (~8% of sustained batch ingest).
        d = deltas
        f = np.asarray(float_values)
        i = np.asarray(int_values)
        isf = np.asarray(is_float)
    else:
        order = np.argsort(deltas, kind="stable")
        d = deltas[order]
        f = np.asarray(float_values)[order]
        i = np.asarray(int_values)[order]
        isf = np.asarray(is_float)[order]
    if len(d) > 1:
        dup = d[1:] == d[:-1]
        if dup.any():
            same_type = isf[1:] == isf[:-1]
            same_val = np.where(isf[1:], f[1:] == f[:-1], i[1:] == i[:-1])
            if (dup & ~(same_type & same_val)).any():
                bad = int(d[1:][dup & ~(same_type & same_val)][0])
                raise IllegalDataError(
                    f"Found out of order or duplicate data: delta={bad}"
                    " -- run an fsck.")
            keep = np.concatenate(([True], ~dup))
            d, f, i, isf = d[keep], f[keep], i[keep], isf[keep]
    return d, f, i, isf


def decode_cells_flat(cell_quals: list[bytes], cell_vals: list[bytes],
                      base_ts: np.ndarray):
    """Decode MANY cells (across many rows) in one vectorized pass.

    The per-cell ``decode_cell`` pays fixed numpy overhead per call,
    which dominates scans of compacted single-cell rows; here the whole
    scan's qualifier/value buffers concatenate into two flat arrays and
    every step (flag split, width resolution, offset cumsum, per-width
    value extraction, validation) runs once. Semantics are identical to
    decode_cell per cell — differential-tested.

    Args:
      cell_quals / cell_vals: per-cell byte strings.
      base_ts: [C] int64 row base time per cell.

    Returns (ts, fvals, ivals, is_float, cell_of_point) flat arrays over
    all points, cells in input order, points in qualifier order.
    """
    C = len(cell_quals)
    if C == 0:
        e = np.empty(0, np.int64)
        return e, np.empty(0, np.float64), e.copy(), \
            np.empty(0, bool), e.copy().astype(np.int32)
    nq = np.fromiter((len(q) for q in cell_quals), np.int64, C)
    if ((nq == 0) | (nq % 2 != 0)).any():
        bad = int(nq[(nq == 0) | (nq % 2 != 0)][0])
        raise IllegalDataError(f"invalid qualifier length {bad}")
    npts = nq // 2
    vlens = np.fromiter((len(v) for v in cell_vals), np.int64, C)

    quals = np.frombuffer(b"".join(cell_quals), dtype=">u2") \
        .astype(np.int64)
    cell_of_point = np.repeat(np.arange(C, dtype=np.int32), npts)
    deltas = quals >> FLAG_BITS
    flags = quals & (FLAG_FLOAT | LENGTH_MASK)
    is_float = (flags & FLAG_FLOAT) != 0
    widths = (flags & LENGTH_MASK) + 1

    vbuf = np.frombuffer(b"".join(cell_vals), dtype=np.uint8)
    vstarts = np.zeros(C, np.int64)
    np.cumsum(vlens[:-1], out=vstarts[1:])

    single = npts == 1
    multi = ~single
    first_pt = np.zeros(C, np.int64)
    np.cumsum(npts[:-1], out=first_pt[1:])

    # Single cells: legacy 8-byte float repair (leading 4 zero bytes) and
    # width := value length (pre-compaction flags were unreliable; the
    # value length is the truth, like the reference's RowSeq extractors).
    adj_vstart = vstarts.copy()
    adj_vlen = vlens.copy()
    rep = single & is_float[first_pt] & (widths[first_pt] == 4) \
        & (vlens == 8)
    if rep.any():
        pos = vstarts[rep, None] + np.arange(4)
        lead = vbuf[pos.ravel()].reshape(-1, 4)
        if lead.any():
            ci = int(np.flatnonzero(rep)[int(lead.any(axis=1).argmax())])
            raise IllegalDataError(
                "Corrupted floating point value: "
                f"{cell_vals[ci].hex()}")
        adj_vstart[rep] += 4
        adj_vlen[rep] -= 4
    widths = widths.copy()
    widths[first_pt[single]] = adj_vlen[single]

    # Multi-point (compacted) cells end with the 0x00 meta byte. The
    # zero-length check must come first: a -1 index would read another
    # cell's byte (or raise IndexError on an empty buffer).
    if multi.any():
        if (vlens[multi] == 0).any():
            raise IllegalDataError(
                "compacted value lacks the 0x00 meta byte (future format?)")
        metas = vbuf[vstarts[multi] + vlens[multi] - 1]
        if metas.any():
            raise IllegalDataError(
                "compacted value lacks the 0x00 meta byte (future format?)")

    # Per-point value offsets: global running sum rebased per cell.
    gcum = np.zeros(len(widths) + 1, np.int64)
    np.cumsum(widths, out=gcum[1:])
    offsets = gcum[:-1] - gcum[first_pt][cell_of_point] \
        + adj_vstart[cell_of_point]
    # Single cells can't mismatch: their one width was just set from the
    # value length, so only compacted cells need the consumed check.
    consumed = gcum[first_pt + npts] - gcum[first_pt]
    bad = multi & (consumed != adj_vlen - 1)
    if bad.any():
        i = int(np.flatnonzero(bad)[0])
        raise IllegalDataError(
            f"Corrupted value: couldn't break down into individual "
            f"values (consumed {int(consumed[i])} bytes, but was "
            f"expecting to consume {int(adj_vlen[i] - 1)})")

    n = len(deltas)
    fvals = np.zeros(n, np.float64)
    ivals = np.zeros(n, np.int64)
    fmask = is_float & (widths == 4)
    if fmask.any():
        pos = offsets[fmask, None] + np.arange(4)
        fvals[fmask] = vbuf[pos.ravel()].reshape(-1, 4) \
            .view(">f4").astype(np.float64).ravel()
    dmask = is_float & (widths == 8)
    if dmask.any():
        pos = offsets[dmask, None] + np.arange(8)
        fvals[dmask] = vbuf[pos.ravel()].reshape(-1, 8).view(">f8").ravel()
    if (is_float & ~(widths == 4) & ~(widths == 8)).any():
        raise IllegalDataError("unsupported float width in cell")
    legal_w = ((widths == 1) | (widths == 2) | (widths == 4)
               | (widths == 8))
    bad_int = (~is_float) & ~legal_w
    if bad_int.any():
        raise IllegalDataError(
            f"Invalid integer value length {int(widths[bad_int][0])}")
    for width, dtype in ((1, ">i1"), (2, ">i2"), (4, ">i4"), (8, ">i8")):
        m = (~is_float) & (widths == width)
        if not m.any():
            continue
        pos = offsets[m, None] + np.arange(width)
        ivals[m] = vbuf[pos.ravel()].reshape(-1, width) \
            .view(dtype).astype(np.int64).ravel()
    fvals = np.where(is_float, fvals, ivals.astype(np.float64))
    ts = base_ts[cell_of_point] + deltas
    return ts, fvals, ivals, is_float, cell_of_point
