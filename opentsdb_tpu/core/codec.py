"""Byte codecs for row keys, qualifiers, values, and compacted cells.

This module is the *only* place that knows the byte-packed cell format; the
compute path decodes rows into columnar numpy arrays (see ``to_columns``) and
never touches bytes again. Format parity with the reference:

  row key    = [metric:3][base_time:4][tagk:3 tagv:3]*   (13..19+ bytes)
               reference src/core/IncomingDataPoints.java:109-135
  qualifier  = 2 bytes big-endian: (delta << 4) | flags, delta in [0, 3599]
               reference src/core/TSDB.java:340-344
  flags      = FLAG_FLOAT(0x8) | (value_len - 1)
               ints: 1/2/4/8-byte big-endian two's complement (smallest fit,
               reference src/core/TSDB.java:240-249); floats: 4-byte IEEE754
               single (flags 0xB), doubles: 8-byte (flags 0xF,
               reference src/core/TSDB.java:276-328)
  compacted  = concatenated 2-byte qualifiers || concatenated values || 0x00
               meta byte (reference src/core/CompactionQueue.java:450-474)

The historical float-encoding bug (4-byte float stored on 8 bytes with 4
leading zero bytes, flags claiming 4) is detected and repaired exactly like
reference CompactionQueue.fixFloatingPointValue (:519-544).
"""

from __future__ import annotations

import struct
from typing import Iterable, NamedTuple

import numpy as np

from opentsdb_tpu.core.const import (
    FLAG_BITS,
    FLAG_FLOAT,
    FLAGS_MASK,
    LENGTH_MASK,
    MAX_TIMESPAN,
    TIMESTAMP_BYTES,
    UID_WIDTH,
)
from opentsdb_tpu.core.errors import IllegalDataError

_INT8 = struct.Struct(">b")
_INT16 = struct.Struct(">h")
_INT32 = struct.Struct(">i")
_INT64 = struct.Struct(">q")
_FLOAT32 = struct.Struct(">f")
_FLOAT64 = struct.Struct(">d")
_UINT16 = struct.Struct(">H")
_UINT32 = struct.Struct(">I")


# ---------------------------------------------------------------------------
# Values
# ---------------------------------------------------------------------------

def encode_long(value: int) -> tuple[bytes, int]:
    """Encode an integer on the smallest of 1/2/4/8 big-endian bytes.

    Returns (value_bytes, flags). Parity: reference TSDB.java:240-249.
    """
    if -0x80 <= value <= 0x7F:
        return _INT8.pack(value), 0
    if -0x8000 <= value <= 0x7FFF:
        return _INT16.pack(value), 1
    if -0x80000000 <= value <= 0x7FFFFFFF:
        return _INT32.pack(value), 3
    if -0x8000000000000000 <= value <= 0x7FFFFFFFFFFFFFFF:
        return _INT64.pack(value), 7
    raise ValueError(f"value out of int64 range: {value}")


def encode_float(value: float) -> tuple[bytes, int]:
    """Encode a float on 4 IEEE754 bytes. Parity: reference TSDB.java:321-328."""
    if value != value or value in (float("inf"), float("-inf")):
        raise ValueError(f"value is NaN or Infinite: {value}")
    return _FLOAT32.pack(value), FLAG_FLOAT | 0x3


def encode_double(value: float) -> tuple[bytes, int]:
    """Encode a double on 8 bytes. Parity: reference TSDB.java:276-290."""
    if value != value or value in (float("inf"), float("-inf")):
        raise ValueError(f"value is NaN or Infinite: {value}")
    return _FLOAT64.pack(value), FLAG_FLOAT | 0x7


def decode_value(buf: bytes, flags: int) -> int | float:
    """Decode a value given its qualifier flags.

    Parity: reference RowSeq.extractIntegerValue/extractFloatingPointValue
    (:194-226), including tolerance for the 8-bytes-with-leading-zeros float.
    """
    if flags & FLAG_FLOAT:
        length = (flags & LENGTH_MASK) + 1
        if length == 4:
            if len(buf) == 8:
                # Historical mis-encoding: real float in the last 4 bytes.
                if buf[:4] != b"\x00\x00\x00\x00":
                    raise IllegalDataError(
                        f"Corrupted floating point value: {buf.hex()} flags="
                        f"{flags:#x} -- first 4 bytes are expected to be zeros")
                buf = buf[4:]
            return _FLOAT32.unpack(buf)[0]
        if length == 8:
            return _FLOAT64.unpack(buf)[0]
        raise IllegalDataError(
            f"Unsupported float length {length} (flags={flags:#x})")
    length = len(buf)
    if length == 1:
        return _INT8.unpack(buf)[0]
    if length == 2:
        return _INT16.unpack(buf)[0]
    if length == 4:
        return _INT32.unpack(buf)[0]
    if length == 8:
        return _INT64.unpack(buf)[0]
    raise IllegalDataError(f"Invalid integer value length {length}")


# ---------------------------------------------------------------------------
# Qualifiers
# ---------------------------------------------------------------------------

def encode_qualifier(delta: int, flags: int) -> bytes:
    """Pack (delta seconds within the row, flags) into the 2-byte qualifier."""
    if not 0 <= delta < MAX_TIMESPAN:
        raise ValueError(f"time delta out of range: {delta}")
    return _UINT16.pack((delta << FLAG_BITS) | (flags & FLAGS_MASK))


def decode_qualifier(qual: bytes) -> tuple[int, int]:
    """Unpack a 2-byte qualifier into (delta, flags)."""
    q = _UINT16.unpack(qual)[0]
    return q >> FLAG_BITS, q & FLAGS_MASK


def fix_qualifier_flags(flags: int, val_len: int) -> int:
    """Zero every flag bit but FLAG_FLOAT; set length from the actual value.

    Parity: reference CompactionQueue.fixQualifierFlags (:490-501).
    """
    return (flags & ~(FLAGS_MASK >> 1)) | (val_len - 1)


def needs_float_fix(flags: int, value: bytes) -> bool:
    """True for the historical float-on-8-bytes bug (flags say 4 bytes)."""
    return bool(flags & FLAG_FLOAT) and (flags & LENGTH_MASK) == 0x3 \
        and len(value) == 8


def fix_float_value(flags: int, value: bytes) -> bytes:
    """Strip the 4 leading zero bytes off a mis-encoded float value.

    Parity: reference CompactionQueue.fixFloatingPointValue (:519-544).
    """
    if needs_float_fix(flags, value):
        if value[:4] != b"\x00\x00\x00\x00":
            raise IllegalDataError(
                f"Corrupted floating point value: {value.hex()} flags="
                f"{flags:#x} -- first 4 bytes are expected to be zeros")
        return value[4:]
    return value


# ---------------------------------------------------------------------------
# Row keys
# ---------------------------------------------------------------------------

def base_time(timestamp: int) -> int:
    """Row base time: timestamp floored to the MAX_TIMESPAN boundary."""
    return timestamp - (timestamp % MAX_TIMESPAN)


def row_key(metric_uid: bytes, base_ts: int,
            tag_uids: Iterable[tuple[bytes, bytes]]) -> bytes:
    """Build [metric][base_time][tagk tagv]* — tag pairs must be pre-sorted.

    Parity: reference IncomingDataPoints.rowKeyTemplate (:109-135).
    """
    parts = [metric_uid, _UINT32.pack(base_ts & 0xFFFFFFFF)]
    for tagk, tagv in tag_uids:
        parts.append(tagk)
        parts.append(tagv)
    return b"".join(parts)


def row_key_template(metric_uid: bytes,
                     tag_uids: Iterable[tuple[bytes, bytes]]) -> bytearray:
    """Row key with a zeroed base-time slot, for reuse across rows."""
    return bytearray(row_key(metric_uid, 0, tag_uids))


def set_base_time(key: bytearray, base_ts: int) -> None:
    """Patch the base-time slot of a row-key template in place."""
    key[UID_WIDTH:UID_WIDTH + TIMESTAMP_BYTES] = \
        _UINT32.pack(base_ts & 0xFFFFFFFF)


class ParsedRowKey(NamedTuple):
    metric_uid: bytes
    base_time: int
    tag_uids: tuple[tuple[bytes, bytes], ...]


def parse_row_key(key: bytes) -> ParsedRowKey:
    """Split a row key back into (metric, base_time, ((tagk, tagv), ...))."""
    prefix = UID_WIDTH + TIMESTAMP_BYTES
    if len(key) < prefix or (len(key) - prefix) % (2 * UID_WIDTH) != 0:
        raise IllegalDataError(f"invalid row key length {len(key)}")
    metric = key[:UID_WIDTH]
    base_ts = _UINT32.unpack(key[UID_WIDTH:prefix])[0]
    tags = []
    for off in range(prefix, len(key), 2 * UID_WIDTH):
        tags.append((key[off:off + UID_WIDTH],
                     key[off + UID_WIDTH:off + 2 * UID_WIDTH]))
    return ParsedRowKey(metric, base_ts, tuple(tags))


def key_base_time(key: bytes) -> int:
    """Just the base-time field of a row key — the scan hot loop calls
    this per row, where parse_row_key's full tag-tuple build would be
    ~3x the row's entire decode budget."""
    return _UINT32.unpack(key[UID_WIDTH:UID_WIDTH + TIMESTAMP_BYTES])[0]


def series_key(key: bytes) -> bytes:
    """The row key minus its base-time bytes: identifies one time series.

    Two rows belong to the same Span iff their series keys are equal —
    parity with reference TsdbQuery.SpanCmp (:594-623), which compares keys
    ignoring the timestamp bytes.
    """
    return key[:UID_WIDTH] + key[UID_WIDTH + TIMESTAMP_BYTES:]


def series_tag_uids(skey: bytes) -> dict[bytes, bytes]:
    """Tag (tagk_uid -> tagv_uid) pairs of a SERIES key (metric UID then
    alternating tagk/tagv UIDs — no base-time bytes). The one definition
    of the series-key tag layout; query planning and the devwindow
    series directory both parse through here."""
    w = UID_WIDTH
    return {skey[i:i + w]: skey[i + w:i + 2 * w]
            for i in range(w, len(skey), 2 * w)}


# ---------------------------------------------------------------------------
# Cells and compaction-format helpers
# ---------------------------------------------------------------------------

class Cell(NamedTuple):
    """One (qualifier, value) pair; sort order is by qualifier bytes.

    Parity: reference CompactionQueue.Cell (:690-743 environs).
    """
    qualifier: bytes  # always 2 bytes here (single data point)
    value: bytes

    @property
    def delta(self) -> int:
        return decode_qualifier(self.qualifier)[0]

    @property
    def flags(self) -> int:
        return decode_qualifier(self.qualifier)[1]

    def decode(self) -> int | float:
        return decode_value(self.value, self.flags)


def is_compacted_qualifier(qual: bytes) -> bool:
    """A qualifier longer than 2 (even) bytes marks a compacted cell."""
    return len(qual) > 2 and len(qual) % 2 == 0


def explode_cell(qual: bytes, value: bytes) -> list[Cell]:
    """Break a cell (single or compacted) into individual fixed-up Cells.

    Parity: reference CompactionQueue.breakDownValues (:690-743): validates
    the trailing 0x00 meta byte and exact value-length consumption.
    """
    if len(qual) == 2:
        flags = qual[1] & FLAGS_MASK
        fixed = fix_float_value(flags, value)
        if len(fixed) != len(value) or \
                fix_qualifier_flags(qual[1], len(fixed)) != qual[1]:
            qual = bytes([qual[0], fix_qualifier_flags(qual[1], len(fixed))])
        return [Cell(qual, fixed)]
    if len(qual) % 2 != 0 or len(qual) == 0:
        raise IllegalDataError(f"invalid qualifier length {len(qual)}")
    if value[-1] != 0:
        raise IllegalDataError(
            f"Don't know how to read this value: {value.hex()} -- this "
            "compacted value might have been written by a future version, "
            "or could be corrupt.")
    cells = []
    val_idx = 0
    for i in range(0, len(qual), 2):
        q = qual[i:i + 2]
        vlen = (q[1] & LENGTH_MASK) + 1
        v = value[val_idx:val_idx + vlen]
        if len(v) != vlen:
            raise IllegalDataError(
                f"Corrupted value: ran out of bytes at qualifier {i // 2}")
        val_idx += vlen
        cells.append(Cell(q, v))
    if val_idx != len(value) - 1:
        raise IllegalDataError(
            f"Corrupted value: couldn't break down into individual values "
            f"(consumed {val_idx} bytes, but was expecting to consume "
            f"{len(value) - 1})")
    return cells


def merge_cells(cells: list[Cell]) -> tuple[bytes, bytes]:
    """Merge sorted-deduped Cells into one compacted (qualifier, value).

    Appends the trailing 0x00 meta byte for multi-point cells. A merge that
    collapses to a single point yields a plain single-value cell (2-byte
    qualifier, raw value): on the wire a 2-byte qualifier always means "raw
    value, no meta byte". Callers must have sorted and deduplicated (see
    ``compact_cells``).
    """
    quals = b"".join(c.qualifier for c in cells)
    vals = b"".join(c.value for c in cells)
    if len(cells) != 1:
        vals += b"\x00"
    return quals, vals


def compact_cells(raw: list[tuple[bytes, bytes]]) -> tuple[bytes, bytes]:
    """Full compaction merge of a row's cells -> one (qualifier, value).

    Explodes compacted cells, sorts by qualifier, drops exact duplicates
    (same delta, flags, and value), and raises IllegalDataError on same-delta
    conflicts — parity with reference CompactionQueue.complexCompact
    (:600-679). Works for the trivial all-single-cell case too.
    """
    cells: list[Cell] = []
    for qual, value in raw:
        if len(qual) % 2 != 0 or len(qual) == 0:
            continue  # junk / future format: skip, stay forward-compatible
        cells.extend(explode_cell(qual, value))
    cells.sort(key=lambda c: c.qualifier)
    out: list[Cell] = []
    last_delta = -1
    for cell in cells:
        delta = cell.delta
        if delta == last_delta:
            prev = out[-1]
            if cell.qualifier[1] != prev.qualifier[1] or \
                    cell.value != prev.value:
                raise IllegalDataError(
                    f"Found out of order or duplicate data: delta={delta}, "
                    f"cell={cell}, prev={prev} -- run an fsck.")
            continue  # true duplicate: skip
        last_delta = delta
        out.append(cell)
    return merge_cells(out)


# ---------------------------------------------------------------------------
# Columnar decode — the bridge into the TPU compute path
# ---------------------------------------------------------------------------

class Columns(NamedTuple):
    """A decoded row (or span of rows) as parallel arrays.

    ``timestamps`` are absolute epoch seconds (int64); ``values`` carries
    every point as float64 (lossless for floats and for ints up to 2^53 —
    beyond that the exact int64 is preserved in ``int_values``);
    ``is_float`` marks which points were stored as floating point.
    """
    timestamps: np.ndarray  # int64 (n,)
    values: np.ndarray      # float64 (n,)
    int_values: np.ndarray  # int64 (n,) — valid where ~is_float
    is_float: np.ndarray    # bool (n,)


def cells_to_columns(base_ts: int, cells: list[Cell]) -> Columns:
    """Decode a row's Cells into columnar arrays for batched compute."""
    n = len(cells)
    ts = np.empty(n, dtype=np.int64)
    vals = np.empty(n, dtype=np.float64)
    ints = np.zeros(n, dtype=np.int64)
    isf = np.empty(n, dtype=bool)
    for i, cell in enumerate(cells):
        delta, flags = decode_qualifier(cell.qualifier)
        ts[i] = base_ts + delta
        v = decode_value(cell.value, flags)
        isf[i] = bool(flags & FLAG_FLOAT)
        vals[i] = float(v)
        if not isf[i]:
            ints[i] = v
    return Columns(ts, vals, ints, isf)


def columns_concat(parts: list[Columns]) -> Columns:
    """Concatenate per-row Columns (already time-ordered) into one span."""
    if not parts:
        empty_i = np.empty(0, dtype=np.int64)
        return Columns(empty_i, np.empty(0, dtype=np.float64),
                       empty_i.copy(), np.empty(0, dtype=bool))
    return Columns(
        np.concatenate([p.timestamps for p in parts]),
        np.concatenate([p.values for p in parts]),
        np.concatenate([p.int_values for p in parts]),
        np.concatenate([p.is_float for p in parts]),
    )
