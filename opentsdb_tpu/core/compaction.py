"""Background row-compaction queue.

Parity target: reference src/core/CompactionQueue.java — a set of "dirty" row
keys flushed by a daemon thread once their hour has passed, merging each
row's cells into one compacted cell and deleting the originals. Differences
by design (TPU-first):

- The merge itself is the vectorized ``codec_np`` path (sort/dedup on
  columnar arrays), not a per-cell pull loop.
- The queue is a plain dict row_key -> base_time; the flush scan is O(queue)
  per wake-up, which replaces the skip-list-ordered iteration (:936-950)
  without needing ordered traversal.

Error discipline matches the reference: PleaseThrottle re-enqueues the row
(:797-808), unexpected errors are counted and dropped, and on memory
pressure the whole queue can be discarded — it is reconstructible soft state
(SURVEY.md §5.4).
"""

from __future__ import annotations

import logging
import threading
import time

from opentsdb_tpu.core import codec
from opentsdb_tpu.core.const import MAX_TIMESPAN
from opentsdb_tpu.core.errors import IllegalDataError, PleaseThrottleError

LOG = logging.getLogger(__name__)


class CompactionQueue:
    """Queue of row keys awaiting compaction, with a background flusher."""

    def __init__(self, tsdb, start_thread: bool = True) -> None:
        self._tsdb = tsdb
        self._queue: dict[bytes, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        cfg = tsdb.config
        self.flush_interval = cfg.flush_interval
        self.min_flush_threshold = cfg.compaction_min_flush_threshold
        self.max_concurrent_flushes = cfg.compaction_max_concurrent_flushes
        self.flush_speed = cfg.compaction_flush_speed
        self.checkpoint_interval = cfg.checkpoint_interval
        self._last_checkpoint = time.time()
        self.checkpoints = 0
        # stats (reference :118-132)
        self.trivial_compactions = 0
        self.complex_compactions = 0
        self.written_cells = 0
        self.deleted_cells = 0
        self.errors = 0
        if start_thread and cfg.enable_compactions:
            self._thread = threading.Thread(
                target=self._loop, name="CompactionThread", daemon=True)
            self._thread.start()

    def __len__(self) -> int:
        return len(self._queue)

    def add(self, row_key: bytes) -> None:
        """Mark a row dirty (cheap, called on every write)."""
        base_ts = codec.parse_row_key(row_key).base_time
        with self._lock:
            self._queue[row_key] = base_ts

    def flush(self, cutoff: int | None = None,
              max_flushes: int | None = None) -> int:
        """Compact every queued row with base_time <= cutoff; returns count.

        With no cutoff, flush everything (shutdown path, reference
        TSDB.java:384-417)."""
        if cutoff is None:
            cutoff = 2**62
        if max_flushes is None:
            max_flushes = 2**31
        with self._lock:
            eligible = [k for k, bt in self._queue.items() if bt <= cutoff]
            eligible.sort(key=lambda k: self._queue[k])  # oldest first
            eligible = eligible[:max_flushes]
            for k in eligible:
                del self._queue[k]
        done = 0
        for idx, key in enumerate(eligible):
            try:
                self._tsdb.compact_row(key)
                done += 1
            except PleaseThrottleError:
                with self._lock:  # re-enqueue and stop pushing the engine
                    for k in eligible[idx:]:
                        self._queue[k] = codec.parse_row_key(k).base_time
                break
            except IllegalDataError:
                self.errors += 1
                LOG.exception("Uncompactable row %s", key.hex())
            except Exception:
                self.errors += 1
                LOG.exception("WTF? Uncaught exception compacting %s",
                              key.hex())
        return done

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_interval):
            try:
                now = time.time()
                if (self.checkpoint_interval
                        and now - self._last_checkpoint
                        >= self.checkpoint_interval):
                    store = self._tsdb.store
                    if getattr(store, "read_only", False):
                        # Replica daemon: the timer polls the writer's
                        # durable state instead of spilling (raw
                        # refresh + sketch reload on rebuild + the
                        # read-only rollup tier, in contract order).
                        # Serve-tier replicas (Config.role="replica")
                        # run the SAME call from the WalTailer at
                        # tail_interval_s instead.
                        self._tsdb.refresh_replica()
                    else:
                        self._tsdb.checkpoint()
                    self._last_checkpoint = now
                    self.checkpoints += 1
                size = len(self._queue)
                if size <= self.min_flush_threshold:
                    continue
                # Adaptive rate: flush at FLUSH_SPEED x the pace rows age
                # out, bounded by max_concurrent_flushes (reference
                # :881-928).
                max_flushes = min(
                    self.max_concurrent_flushes,
                    max(self.min_flush_threshold, 1,
                        int(size * self.flush_interval * self.flush_speed
                            / MAX_TIMESPAN)))
                cutoff = int(time.time()) - MAX_TIMESPAN - 1
                self.flush(cutoff, max_flushes)
            except MemoryError:
                # Discard the whole queue: it's reconstructible soft state.
                with self._lock:
                    self._queue.clear()
                LOG.error("OOM in compaction thread; queue discarded")
            except Exception:
                LOG.exception("Uncaught exception in compaction thread")

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.flush()
