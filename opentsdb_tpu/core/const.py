"""Wire-format constants.

Parity with reference src/core/Const.java:19-41. These values are load-bearing:
they pin the on-disk/row-key format so ``tsdb scan --import`` output from the
reference round-trips through this framework.
"""

# Number of bytes on which a (base) timestamp is encoded inside a row key.
TIMESTAMP_BYTES = 4

# Maximum number of tags allowed per data point.
MAX_NUM_TAGS = 8

# Number of LSBs in a qualifier reserved for flags.
FLAG_BITS = 4

# Qualifier flag bit: value is floating point (else integer).
FLAG_FLOAT = 0x8

# Mask selecting the (length-1) of a value from the qualifier flags.
LENGTH_MASK = 0x7

# All flag bits.
FLAGS_MASK = FLAG_FLOAT | LENGTH_MASK

# Max time delta (seconds) storable in a column qualifier => seconds per row.
MAX_TIMESPAN = 3600

# Width in bytes of every UID kind (metrics, tagk, tagv).
UID_WIDTH = 3

# The interpolation-free aggregator family and its underlying moment
# reductions (query-language names from later OpenTSDB; the 1.1 reference
# predates them). Canonical mapping — kernels, oracle, and the registry
# all derive from this.
NOLERP_AGGS = {"zimsum": "sum", "mimmin": "min", "mimmax": "max"}
