"""Mergeable sketch kernels: t-digest percentiles and HyperLogLog counts.

These replace the reference's exact-but-sequential structures per the north
star (BASELINE.json): src/stats/Histogram.java's fixed buckets give way to
t-digest quantiles; distinct-tag-value counting (which the reference can
only do by materializing every group) becomes HyperLogLog.

Both sketches are designed for XLA:
- Fixed-size state resident in HBM: a t-digest is exactly (means[K],
  weights[K]); an HLL is registers[M]. No data-dependent shapes.
- Batch-compress instead of per-point control flow: t-digest updates
  concatenate centroids with the new batch, sort once, assign each point a
  cluster via the scale function k(q) = delta/(2pi) * asin(2q-1) evaluated
  on cumulative weights, and segment-reduce — the one-pass vectorized form
  of the MergingDigest algorithm (Dunning, arXiv:1902.04023). HLL updates
  are one segment_max.
- Merging across chips is elementwise max (HLL) or concatenate+recompress
  (t-digest), so cross-shard fan-in rides psum/all_gather (see
  opentsdb_tpu.parallel).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.parallel.compile import jit_plan
from opentsdb_tpu.parallel.plan import ExecPlan

# Sketch kernels compile through the mesh execution plane
# (parallel/plan.py + parallel/compile.py): with no mesh each plan is
# the per-site jax.jit it replaced; the sketch folds' batch axis is the
# series-hash axis (merges are psum/pmax-shaped, so mesh fan-in rides
# the sharded kernels in parallel/sharded.py).

# ---------------------------------------------------------------------------
# t-digest
# ---------------------------------------------------------------------------

DEFAULT_COMPRESSION = 128  # max centroids (delta)


def tdigest_init(compression: int = DEFAULT_COMPRESSION):
    """Empty digest state: (means[K], weights[K]) with zero weights."""
    return (jnp.zeros(compression, jnp.float32),
            jnp.zeros(compression, jnp.float32))


@jit_plan(ExecPlan(name="sketch.tdigest_compress", axis="series",
                   static_argnames=("compression",)))
def _compress(means: jnp.ndarray, weights: jnp.ndarray, *,
              compression: int):
    """Sort centroids and merge them into <= compression clusters.

    Cluster assignment uses the k1 scale function on cumulative quantiles:
    k(q) = (delta / (2*pi)) * asin(2q - 1); cluster id = floor(k(q_mid) +
    delta/4), which concentrates resolution at the tails.
    """
    # Sort with empty (weight-0) slots pushed to the end so they never
    # perturb the quantile positions of real centroids.
    key = jnp.where(weights > 0, means, jnp.inf)
    order = jnp.argsort(key)
    m = means[order]
    w = weights[order]
    total = jnp.maximum(w.sum(), 1e-30)
    cum = jnp.cumsum(w)
    q_mid = (cum - w / 2) / total
    q_mid = jnp.clip(q_mid, 1e-7, 1 - 1e-7)
    delta = jnp.float32(compression)
    # k1 scale spanning the full [0, compression] range (asin covers
    # [-pi/2, pi/2], so the delta/pi coefficient uses every slot).
    k = delta / jnp.pi * jnp.arcsin(2 * q_mid - 1) + delta / 2
    cluster = jnp.clip(k.astype(jnp.int32), 0, compression - 1)
    # Empty (weight 0) entries go to a trash cluster.
    cluster = jnp.where(w > 0, cluster, compression)
    wsum = jax.ops.segment_sum(w, cluster, compression + 1)[:-1]
    msum = jax.ops.segment_sum(m * w, cluster, compression + 1)[:-1]
    new_means = jnp.where(wsum > 0, msum / jnp.maximum(wsum, 1e-30), 0.0)
    return new_means, wsum


@jit_plan(ExecPlan(name="sketch.tdigest_add", axis="series",
                   static_argnames=("compression",)))
def tdigest_add(means: jnp.ndarray, weights: jnp.ndarray,
                values: jnp.ndarray, valid: jnp.ndarray, *,
                compression: int = DEFAULT_COMPRESSION):
    """Fold a batch of values (with padding mask) into the digest."""
    m = jnp.concatenate([means, values.astype(jnp.float32)])
    w = jnp.concatenate([weights, valid.astype(jnp.float32)])
    return _compress(m, w, compression=compression)


@jit_plan(ExecPlan(name="sketch.tdigest_merge", axis="series",
                   static_argnames=("compression",)))
def tdigest_merge(means_a, weights_a, means_b, weights_b, *,
                  compression: int = DEFAULT_COMPRESSION):
    """Merge two digests (associative, commutative up to compression error)."""
    m = jnp.concatenate([means_a, means_b])
    w = jnp.concatenate([weights_a, weights_b])
    return _compress(m, w, compression=compression)


@jit_plan(ExecPlan(name="sketch.tdigest_quantile"))
def tdigest_quantile(means: jnp.ndarray, weights: jnp.ndarray,
                     q: jnp.ndarray):
    """Estimate quantiles q (in [0,1]) by interpolating between centroids.

    Zero-weight (empty) centroid slots are excluded: they sort to the end
    and both the search and the support clamps only see real centroids —
    otherwise empties (mean 0.0) would drag extreme quantiles toward zero
    for data not spanning zero.
    """
    key = jnp.where(weights > 0, means, jnp.inf)
    order = jnp.argsort(key)
    m = means[order]
    w = weights[order]
    nreal = jnp.maximum((weights > 0).sum(), 1)
    last = nreal - 1
    total = jnp.maximum(w.sum(), 1e-30)
    cum = jnp.cumsum(w)
    centers = (cum - w / 2) / total  # quantile at each centroid center
    # Empty slots all have centers == 1.0; push them past any target.
    centers = jnp.where(jnp.arange(len(m)) < nreal, centers, jnp.inf)

    def one(qi):
        target = jnp.clip(qi, 0.0, 1.0)
        # Index of first real centroid whose center >= target.
        idx = jnp.searchsorted(centers, target)
        lo = jnp.clip(idx - 1, 0, last)
        hi = jnp.clip(idx, 0, last)
        c0, c1 = centers[lo], centers[hi]
        m0, m1 = m[lo], m[hi]
        frac = jnp.where(c1 > c0, (target - c0) / jnp.maximum(c1 - c0, 1e-30),
                         0.0)
        frac = jnp.clip(frac, 0.0, 1.0)
        est = m0 + frac * (m1 - m0)
        # Clamp to the digest's support where q falls outside centers.
        est = jnp.where(target <= centers[0], m[0], est)
        est = jnp.where(target >= centers[last], m[last], est)
        return est

    return jax.vmap(one)(jnp.atleast_1d(jnp.asarray(q, jnp.float32)))


def tdigest_count(weights: jnp.ndarray) -> jnp.ndarray:
    return weights.sum()


# ---------------------------------------------------------------------------
# HyperLogLog
# ---------------------------------------------------------------------------

DEFAULT_HLL_P = 14  # 2^14 = 16384 registers -> ~0.8% standard error


def hll_init(p: int = DEFAULT_HLL_P):
    return jnp.zeros(1 << p, jnp.int32)


@jit_plan(ExecPlan(name="sketch.hash32"))
def hash32(x: jnp.ndarray) -> jnp.ndarray:
    """32-bit avalanche mixer (murmur3 finalizer) over int32/uint32 input."""
    h = x.astype(jnp.uint32)
    h ^= h >> 16
    h *= jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    h *= jnp.uint32(0xC2B2AE35)
    h ^= h >> 16
    return h


@jit_plan(ExecPlan(name="sketch.hll_add", axis="series",
                   static_argnames=("p",)))
def hll_add(registers: jnp.ndarray, items: jnp.ndarray,
            valid: jnp.ndarray, *, p: int = DEFAULT_HLL_P):
    """Fold hashed items (e.g. tagv UIDs as int32) into the registers."""
    h = hash32(items)
    idx = (h >> (32 - p)).astype(jnp.int32)
    w = (h << p) >> p  # low (32-p) bits
    # rank = leading-zero count within (32-p) bits, + 1. floor(log2) via
    # float32 exponent is exact for w < 2^24 (here w < 2^18 when p=14).
    lg = jnp.frexp(w.astype(jnp.float32))[1] - 1  # floor(log2(w)), w>0
    rank = jnp.where(w > 0, (32 - p) - lg, (32 - p) + 1).astype(jnp.int32)
    idx = jnp.where(valid, idx, 1 << p)  # trash register for padding
    new = jax.ops.segment_max(rank, idx, (1 << p) + 1)[:-1]
    return jnp.maximum(registers, new)


@jit_plan(ExecPlan(name="sketch.hll_merge", axis="series"))
def hll_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.maximum(a, b)


@jit_plan(ExecPlan(name="sketch.hll_estimate"))
def hll_estimate(registers: jnp.ndarray) -> jnp.ndarray:
    """Cardinality estimate with small/large-range corrections."""
    m = registers.shape[0]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv = jnp.sum(jnp.exp2(-registers.astype(jnp.float32)))
    raw = alpha * m * m / inv
    zeros = jnp.sum(registers == 0).astype(jnp.float32)
    small = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    est = jnp.where((raw <= 2.5 * m) & (zeros > 0), small, raw)
    two32 = jnp.float32(2.0) ** 32
    est = jnp.where(est > two32 / 30.0,
                    -two32 * jnp.log1p(-est / two32), est)
    return est


# ---------------------------------------------------------------------------
# Moment sketches (power sums; arXiv:1803.01969)
# ---------------------------------------------------------------------------

DEFAULT_MOMENT_K = 8


def moment_init(k: int = DEFAULT_MOMENT_K):
    """Empty moment state: (count, min, max, moments[k]). Exactly
    mergeable — fold and merge are pure additions/extrema, so unlike
    the t-digest there is no compression error to accumulate."""
    return (jnp.zeros((), jnp.float32), jnp.full((), jnp.inf),
            jnp.full((), -jnp.inf), jnp.zeros(k, jnp.float32))


@jit_plan(ExecPlan(name="sketch.moment_add", axis="series",
                   static_argnames=("k",)))
def moment_add(count, vmin, vmax, moments, values, valid, *,
               k: int = DEFAULT_MOMENT_K):
    """Fold a (padded) batch into the moment state: one vectorized
    cumulative-product pass builds x^1..x^k for every point, masked
    sums add them in — the batched device sibling of the host fold
    (sketch/moment.py; the rollup spill path runs the host twin —
    this kernel is for device-side aggregation pipelines). Padded
    lanes are neutralized BEFORE the power ladder: a large pad value
    would overflow to inf and inf * 0 poisons the sums with NaN.
    float32 dynamic range bounds |x|^k — at the default k=8, values
    beyond ~6e4 overflow; pre-scale such feeds (the host twin is
    float64)."""
    v = jnp.where(valid, values.astype(jnp.float32), 1.0)
    ok = valid.astype(jnp.float32)
    powers = jnp.cumprod(
        jnp.broadcast_to(v, (k, v.shape[0])), axis=0)     # [k, N]
    vv = values.astype(jnp.float32)
    return (count + ok.sum(),
            jnp.minimum(vmin, jnp.where(valid, vv, jnp.inf).min()),
            jnp.maximum(vmax, jnp.where(valid, vv, -jnp.inf).max()),
            moments + (powers * ok[None, :]).sum(axis=1))


@jit_plan(ExecPlan(name="sketch.moment_merge", axis="series"))
def moment_merge(count_a, vmin_a, vmax_a, mom_a,
                 count_b, vmin_b, vmax_b, mom_b):
    """Merge two moment states — pure addition (associative AND
    exact), so cross-shard fan-in is a psum."""
    return (count_a + count_b, jnp.minimum(vmin_a, vmin_b),
            jnp.maximum(vmax_a, vmax_b), mom_a + mom_b)


@jit_plan(ExecPlan(name="sketch.moment_fold_windows", axis="series"))
def moment_fold_windows(states):
    """Batched read-side fold: [W, D] per-window moment rows (count,
    min, max, moments...) reduce to one merged row — the addition
    fold the planner's bucket merge uses (min/max columns fold by
    extremum, everything else by sum)."""
    total = states.sum(axis=0)
    return total.at[1].set(states[:, 1].min()).at[2].set(
        states[:, 2].max())


# ---------------------------------------------------------------------------
# Numpy oracles (for tests)
# ---------------------------------------------------------------------------

def exact_quantile(values: np.ndarray, q: float) -> float:
    return float(np.quantile(np.asarray(values, dtype=np.float64), q))


def exact_distinct(values: np.ndarray) -> int:
    return int(len(np.unique(values)))
