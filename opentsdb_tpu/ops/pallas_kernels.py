"""Pallas TPU kernel for segment reductions + the measured dispatch story.

The query hot loop (ops/kernels.py downsample_group) is a pair of segment
reductions over a flat point stream — the vectorized replacement for the
reference's pull-iterator stack (SpanGroup.SGIterator,
Span.DownsamplingIterator; reference src/core/SpanGroup.java:370-796).

``pallas_segment_sum`` implements the reduction as an MXU one-hot matmul:
a [C]-point chunk scatter-adds into [T] segment bins as
``one_hot(seg)ᵀ @ features`` — systolic-array work with zero dynamic
indexing. It streams point chunks through VMEM with a 2-D grid
(segment-tile × chunk); each output tile stays resident in VMEM while all
chunks accumulate into it, so HBM traffic is one read of the points per
segment tile plus one write of the bins.

**Measured on a real v5e chip (2026-07, scripts/tpu_probe.py):** XLA's
own lowering of a rank-1 f32 ``jax.ops.segment_sum`` is HBM-bound and
excellent at every segment count — ~0.1 ms for N=10M points into 1.7M
segments, and within noise of the Pallas kernel at small counts
(N=1M points: pallas 0.03/0.08/0.09 ms vs XLA 0.05/0.07/0.08 ms at
nseg=256/1024/4096). What IS slow on TPU is the shape, not the scatter:
feature-stacked [N, K] scatters (~1000 ms for [10M, 3]) and
segment_min/max (~240 ms) fall off the fast path. The production kernels
therefore issue one rank-1 segment_sum per needed statistic
(ops/kernels.py _segment_moments) and no longer route through a stacked
feature matrix; the Pallas kernel is kept as a validated alternative (and
the interpret-mode semantics oracle for tests), not as the default path.

``segment_sum_features`` remains the stacked-API entry point for callers
that want K features reduced together; it unstacks into rank-1 XLA
segment_sums, which beats both the stacked scatter and the one-hot matmul
on hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Chunk of points processed per grid step; segment-bin tile held in VMEM.
# [CHUNK, SEG_TILE] one-hot (f32) = 2 MB of VMEM — well under the ~16 MB
# budget with double buffering. CHUNK is 1024 because XLA lays out 1-D
# int32 operands with a 1024-element tile and Mosaic requires the block
# to match it.
CHUNK = 1024
SEG_TILE = 512


def _seg_sum_kernel(seg_ref, feat_ref, out_ref):
    """One (segment-tile i, chunk j) cell: accumulate this chunk's
    contribution to segment bins [i*SEG_TILE, (i+1)*SEG_TILE)."""
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    seg = seg_ref[:]                          # [CHUNK] int32
    local = seg - i * SEG_TILE                # position within this tile
    cols = jax.lax.broadcasted_iota(jnp.int32, (CHUNK, SEG_TILE), 1)
    onehot = (local[:, None] == cols).astype(jnp.float32)  # [CHUNK, SEG_TILE]
    # Scatter-as-matmul on the MXU: binsᵀ += one_hotᵀ @ features.
    # HIGHEST precision: the default lowers f32 matmuls to bf16 MXU
    # passes, which loses ~3 mantissa digits — caught by the hardware
    # parity test (interpret mode computes in full f32 and never sees it).
    out_ref[:] += jnp.dot(onehot.T, feat_ref[:],
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def pallas_segment_sum(feat: jnp.ndarray, seg: jnp.ndarray,
                       num_segments: int, *, interpret: bool = False):
    """Segment-sum [N, K] features by [N] segment ids → [num_segments, K].

    Out-of-range ids (e.g. the padding trash segment) drop out naturally:
    their one-hot row is all-zero in every tile. N pads up to CHUNK and
    num_segments up to SEG_TILE internally; K should be small (a feature
    stack like [valid, value, rel_ts], not a wide matrix).
    """
    n, k = feat.shape
    n_pad = -n % CHUNK
    if n_pad:
        feat = jnp.pad(feat, ((0, n_pad), (0, 0)))
        seg = jnp.pad(seg, (0, n_pad), constant_values=-1)
    n_chunks = (n + n_pad) // CHUNK
    t_pad = -num_segments % SEG_TILE
    nseg_pad = num_segments + t_pad
    n_tiles = nseg_pad // SEG_TILE

    # Under shard_map the out_shape needs the inputs' varying-manual-axes
    # set, or tracing rejects the pallas_call (check_vma). Older jax
    # (pre-typeof/vma) has no such check — a plain struct is correct.
    try:
        out_shape = jax.ShapeDtypeStruct((nseg_pad, k), jnp.float32,
                                         vma=jax.typeof(feat).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct((nseg_pad, k), jnp.float32)
    out = pl.pallas_call(
        _seg_sum_kernel,
        grid=(n_tiles, n_chunks),
        in_specs=[
            # 1-D chunk of ids (last dim CHUNK % 128 == 0) and a
            # [CHUNK, k] feature block (full last dim, CHUNK % 8 == 0) —
            # the Mosaic tiling rules for VMEM blocks.
            pl.BlockSpec((CHUNK,), lambda i, j: (j,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((CHUNK, k), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((SEG_TILE, k), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_shape,
        interpret=interpret,
    )(seg, feat)
    return out[:num_segments]


# Retained for callers that tune dispatch; at or below this count the
# one-hot matmul matches XLA on hardware (see module docstring), above it
# the nseg_pad FLOPs blow-up loses. The default path no longer consults
# it — rank-1 XLA segment_sum won everywhere on the measured chip.
PALLAS_MAX_SEGMENTS = 4096


def segment_sum_features(feat: jnp.ndarray, seg: jnp.ndarray,
                         num_segments: int):
    """Segment-sum K stacked features: K rank-1 XLA segment_sums.

    Rank-1 f32 scatter-adds are the measured fast path on TPU (see
    module docstring); the stacked [N, K] scatter this API used to issue
    is ~1000x slower on hardware, and the Pallas one-hot matmul only ever
    ties XLA. Semantics are identical to
    ``jax.ops.segment_sum(feat, seg, num_segments)``.
    """
    return jnp.stack(
        [jax.ops.segment_sum(feat[:, i], seg, num_segments)
         for i in range(feat.shape[1])], axis=1)
