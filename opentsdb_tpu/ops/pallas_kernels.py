"""Pallas TPU kernels for the hot segment reductions.

The query hot loop (ops/kernels.py downsample_group) is a pair of segment
reductions over a flat point stream — the vectorized replacement for the
reference's pull-iterator stack (SpanGroup.SGIterator,
Span.DownsamplingIterator; reference src/core/SpanGroup.java:370-796).
XLA lowers ``jax.ops.segment_sum`` to sort/scatter sequences that run on
the VPU's scalar-ish scatter path; on TPU the same reduction can ride the
MXU instead: a [C]-point chunk scatter-adds into [T] segment bins as the
matmul ``one_hot(seg)ᵀ @ features`` — 128×128 systolic work with zero
dynamic indexing (pallas_guide: keep the FLOPs on the MXU, avoid scalar
loops).

``pallas_segment_sum`` streams point chunks through VMEM with a 2-D grid
(segment-tile × chunk); each output tile stays resident in VMEM while all
chunks accumulate into it (revisiting output blocks across the innermost
grid dimension), so HBM traffic is one read of the points per segment
tile plus one write of the bins.

Dispatch: ``segment_sum_features`` uses the Pallas path on real TPU
backends and falls back to ``jax.ops.segment_sum`` elsewhere (CPU tests
run the kernel in interpret mode to pin semantics).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Chunk of points processed per grid step; segment-bin tile held in VMEM.
# [CHUNK, SEG_TILE] one-hot (f32) = 2 MB of VMEM — well under the ~16 MB
# budget with double buffering. CHUNK is 1024 because XLA lays out 1-D
# int32 operands with a 1024-element tile and Mosaic requires the block
# to match it.
CHUNK = 1024
SEG_TILE = 512


def _seg_sum_kernel(seg_ref, feat_ref, out_ref):
    """One (segment-tile i, chunk j) cell: accumulate this chunk's
    contribution to segment bins [i*SEG_TILE, (i+1)*SEG_TILE)."""
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    seg = seg_ref[:]                          # [CHUNK] int32
    local = seg - i * SEG_TILE                # position within this tile
    cols = jax.lax.broadcasted_iota(jnp.int32, (CHUNK, SEG_TILE), 1)
    onehot = (local[:, None] == cols).astype(jnp.float32)  # [CHUNK, SEG_TILE]
    # Scatter-as-matmul on the MXU: binsᵀ += one_hotᵀ @ features.
    out_ref[:] += jnp.dot(onehot.T, feat_ref[:],
                          preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def pallas_segment_sum(feat: jnp.ndarray, seg: jnp.ndarray,
                       num_segments: int, *, interpret: bool = False):
    """Segment-sum [N, K] features by [N] segment ids → [num_segments, K].

    Out-of-range ids (e.g. the padding trash segment) drop out naturally:
    their one-hot row is all-zero in every tile. N pads up to CHUNK and
    num_segments up to SEG_TILE internally; K should be small (a feature
    stack like [valid, value, rel_ts], not a wide matrix).
    """
    n, k = feat.shape
    n_pad = -n % CHUNK
    if n_pad:
        feat = jnp.pad(feat, ((0, n_pad), (0, 0)))
        seg = jnp.pad(seg, (0, n_pad), constant_values=-1)
    n_chunks = (n + n_pad) // CHUNK
    t_pad = -num_segments % SEG_TILE
    nseg_pad = num_segments + t_pad
    n_tiles = nseg_pad // SEG_TILE

    # Under shard_map the out_shape needs the inputs' varying-manual-axes
    # set, or tracing rejects the pallas_call (check_vma).
    out_shape = jax.ShapeDtypeStruct((nseg_pad, k), jnp.float32,
                                     vma=jax.typeof(feat).vma)
    out = pl.pallas_call(
        _seg_sum_kernel,
        grid=(n_tiles, n_chunks),
        in_specs=[
            # 1-D chunk of ids (last dim CHUNK % 128 == 0) and a
            # [CHUNK, k] feature block (full last dim, CHUNK % 8 == 0) —
            # the Mosaic tiling rules for VMEM blocks.
            pl.BlockSpec((CHUNK,), lambda i, j: (j,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((CHUNK, k), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((SEG_TILE, k), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_shape,
        interpret=interpret,
    )(seg, feat)
    return out[:num_segments]


# The one-hot matmul does 2·N·nseg_pad·K FLOPs vs the scatter's O(N·K):
# it wins while the MXU's throughput advantage over the scatter path
# covers the nseg_pad blow-up, i.e. for bucket-grid-sized segment counts
# (a query's series×buckets), not for huge UID-sized ones.
PALLAS_MAX_SEGMENTS = 4096


def _use_pallas() -> bool:
    """Pallas path only on real TPU backends (Mosaic target)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def segment_sum_features(feat: jnp.ndarray, seg: jnp.ndarray,
                         num_segments: int):
    """Dispatch: MXU one-hot matmul kernel on TPU, XLA segment_sum off-TPU
    (and for segment counts past the matmul's FLOPs break-even).

    Identical semantics either way; golden tests run the Pallas kernel in
    interpret mode against the XLA path.
    """
    if num_segments <= PALLAS_MAX_SEGMENTS and _use_pallas():
        return pallas_segment_sum(feat, seg, num_segments)
    return jax.ops.segment_sum(feat, seg, num_segments)
