"""Numpy float64 oracle for query math — exact reference semantics.

Every kernel in ops/kernels.py must agree with these functions (to float32
tolerance). Semantics pinned here, with reference citations:

- Downsampling (reference Span.DownsamplingIterator :309-430): two modes.
  'legacy' reproduces 1.1 behavior — data-driven windows [t_first,
  t_first + interval) where t_first is the first point not in a previous
  window; 'aligned' uses epoch-aligned buckets (ts - ts % interval), the
  XLA-friendly mode (and what OpenTSDB 2.x standardized on). In both modes
  the emitted timestamp is the integer mean of member timestamps, unless
  bucket_ts='start' (aligned mode only) which emits the bucket start —
  making grids identical across series so group-agg needs no interpolation.
- Group aggregation (reference SpanGroup.SGIterator :370-796): emit at the
  union of member timestamps clipped to [start, end]; a span contributes
  its exact value at its own timestamps, a linear interpolation between its
  surrounding points elsewhere, and nothing outside [first, last] of its
  own points.
- Rate (reference :736-784): per span, (v_i - v_{i-1}) / (t_i - t_{i-1})
  emitted at t_i, step-held between points at aggregation time. The
  reference's bogus first-point rate (prev initialized to 0@0, yielding
  y0/x0) is deliberately NOT reproduced — rates start at each span's
  second point, as OpenTSDB 2.x fixed it.
- Aggregators (reference Aggregators.java): sum, min, max, avg,
  dev = population standard deviation (Welford, sqrt(M2/n), :196-243).
- Integer aggregation truncates toward zero at the end (runLong returns
  long); the oracle returns float64 and lets callers truncate.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.core.const import NOLERP_AGGS

AGGS = ("sum", "min", "max", "avg", "dev", "count")


def agg_reduce(values: np.ndarray, agg: str) -> float:
    """Aggregate a 1-D array per the reference aggregator semantics.

    Percentile aggregators are named pNN / pNNN ('p50', 'p999'): numpy
    linear-interpolated quantiles.
    """
    if len(values) == 0:
        raise ValueError("empty aggregation")
    if agg == "sum":
        return float(np.sum(values))
    if agg == "min":
        return float(np.min(values))
    if agg == "max":
        return float(np.max(values))
    if agg == "avg":
        return float(np.mean(values))
    if agg == "dev":
        if len(values) == 1:
            return 0.0
        return float(np.sqrt(np.var(values)))  # population (M2/n)
    if agg == "count":
        return float(len(values))
    if agg in NOLERP_AGGS:
        # The interpolation-free family reduces like its base op; the
        # difference is purely which values reach it (interp='none').
        return agg_reduce(values, NOLERP_AGGS[agg])
    if len(agg) > 1 and agg[0] == "p" and agg[1:].isdigit():
        q = int(agg[1:]) / 10 ** len(agg[1:])
        return float(np.quantile(values, q))
    raise ValueError(f"unknown aggregator: {agg}")


# ---------------------------------------------------------------------------
# Downsampling
# ---------------------------------------------------------------------------

def downsample(timestamps: np.ndarray, values: np.ndarray, interval: int,
               agg: str, mode: str = "aligned", bucket_ts: str = "avg",
               ) -> tuple[np.ndarray, np.ndarray]:
    """Downsample one sorted series; returns (bucket_ts, bucket_values)."""
    ts = np.asarray(timestamps, dtype=np.int64)
    vals = np.asarray(values, dtype=np.float64)
    if len(ts) == 0:
        return ts.copy(), vals.copy()
    if mode == "aligned":
        starts = ts - ts % interval
        bounds = np.flatnonzero(np.diff(starts)) + 1
    elif mode == "legacy":
        # Data-driven windows: each bucket spans [first_ts, first_ts + iv).
        bounds = []
        i = 0
        n = len(ts)
        while i < n:
            end = ts[i] + interval
            j = i + 1
            while j < n and ts[j] < end:
                j += 1
            if j < n:
                bounds.append(j)
            i = j
        bounds = np.array(bounds, dtype=np.int64)
    else:
        raise ValueError(f"unknown downsample mode: {mode}")
    groups = np.split(np.arange(len(ts)), bounds)
    out_ts = np.empty(len(groups), dtype=np.int64)
    out_v = np.empty(len(groups), dtype=np.float64)
    for k, idx in enumerate(groups):
        if bucket_ts == "avg":
            out_ts[k] = int(np.sum(ts[idx])) // len(idx)  # integer mean
        elif bucket_ts == "start":
            if mode != "aligned":
                raise ValueError("bucket_ts='start' requires aligned mode")
            out_ts[k] = ts[idx[0]] - ts[idx[0]] % interval
        else:
            raise ValueError(f"unknown bucket_ts: {bucket_ts}")
        out_v[k] = agg_reduce(vals[idx], agg)
    return out_ts, out_v


# ---------------------------------------------------------------------------
# Rate
# ---------------------------------------------------------------------------

def rate(timestamps: np.ndarray, values: np.ndarray,
         counter_max: float | None = None, reset_value: float | None = None,
         ) -> tuple[np.ndarray, np.ndarray]:
    """Per-point rate of change, emitted at the later point of each pair.

    ``counter_max`` enables monotonic-counter rollover correction (a 2.x
    capability): a negative delta is treated as a wrap at counter_max;
    ``reset_value`` zeroes rates whose magnitude exceeds it (counter reset).
    """
    ts = np.asarray(timestamps, dtype=np.int64)
    vals = np.asarray(values, dtype=np.float64)
    if len(ts) < 2:
        return ts[:0], vals[:0]
    dt = np.diff(ts).astype(np.float64)
    dv = np.diff(vals)
    if counter_max is not None:
        dv = np.where(dv < 0, dv + counter_max, dv)
    r = dv / dt
    if reset_value is not None:
        r = np.where(np.abs(r) > reset_value, 0.0, r)
    return ts[1:], r


# ---------------------------------------------------------------------------
# Group aggregation with linear interpolation
# ---------------------------------------------------------------------------

def group_aggregate(series: list[tuple[np.ndarray, np.ndarray]], agg: str,
                    start: int | None = None, end: int | None = None,
                    interp: str = "lerp",
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Aggregate spans on the union of their timestamps, interpolating gaps.

    ``series`` is a list of (sorted_ts, values). ``interp``: 'lerp' (normal
    aggregation) or 'step' (last-value hold, used for rates). A span
    contributes only inside [its first ts, its last ts]. Returns
    (grid_ts, aggregated values).
    """
    filtered = []
    for ts, vals in series:
        ts = np.asarray(ts, dtype=np.int64)
        vals = np.asarray(vals, dtype=np.float64)
        if start is not None or end is not None:
            m = np.ones(len(ts), dtype=bool)
            if start is not None:
                m &= ts >= start
            if end is not None:
                m &= ts <= end
            ts, vals = ts[m], vals[m]
        if len(ts):
            filtered.append((ts, vals))
    if not filtered:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.float64))
    grid = np.unique(np.concatenate([ts for ts, _ in filtered]))
    contrib = np.full((len(filtered), len(grid)), np.nan)
    for s, (ts, vals) in enumerate(filtered):
        in_range = (grid >= ts[0]) & (grid <= ts[-1])
        x = grid[in_range]
        if interp == "lerp":
            contrib[s, in_range] = np.interp(x, ts, vals)
        elif interp == "step":
            idx = np.searchsorted(ts, x, side="right") - 1
            contrib[s, in_range] = vals[idx]
        elif interp == "none":
            # zimsum/mimmin/mimmax family: contribute only at own samples.
            idx = np.searchsorted(ts, x)
            exact = ts[np.minimum(idx, len(ts) - 1)] == x
            sub = np.full(len(x), np.nan)
            sub[exact] = vals[idx[exact]]
            contrib[s, in_range] = sub
        else:
            raise ValueError(f"unknown interp: {interp}")
    out = np.empty(len(grid), dtype=np.float64)
    for g in range(len(grid)):
        out[g] = agg_reduce(contrib[:, g][~np.isnan(contrib[:, g])], agg)
    return grid, out
