"""TPU compute kernels: batched reductions, downsampling, rate, alignment.

``oracle`` holds exact numpy (float64) implementations of the reference
semantics — the ground truth for golden tests. ``kernels`` holds the jitted
JAX equivalents operating on fixed-shape padded arrays with masks, vmapped
over series and shardable over a device mesh (see opentsdb_tpu.parallel).
"""
