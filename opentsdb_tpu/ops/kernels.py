"""Jitted JAX kernels for the query/compaction compute path.

Design rules (SURVEY.md §7.4):
- Fixed shapes everywhere: callers pad to static sizes and pass masks or
  counts. No data-dependent Python control flow; everything lowers to one
  XLA computation per (shape, static-arg) combination.
- The primary layout is FLAT: all points of all series in a query live in
  one [N] array with a parallel [N] series-id array, so ragged series waste
  no compute. Downsample + group-by is then one fused pair of segment
  reductions (points -> series x bucket -> bucket), which XLA maps onto the
  VPU with no gather/scatter loops — this replaces the reference's k-way
  merge iterator stack (SpanGroup.SGIterator, Span.DownsamplingIterator).
- Timestamps enter as int32 *offsets from the query start*; values as
  float32. Bucket mean-timestamps are computed relative to each bucket
  start so float32 stays exact (offsets < interval <= 2^24).

Aggregator semantics match ops/oracle.py (the numpy float64 oracle); golden
tests compare the two.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from opentsdb_tpu.core.const import NOLERP_AGGS
from opentsdb_tpu.parallel.compile import compile_with_plan, jit_plan
from opentsdb_tpu.parallel.plan import ExecPlan

# Execution plans (parallel/plan.py): every jitted kernel in this
# module compiles through the mesh execution plane. With no mesh (the
# plane's default) each plan is exactly the per-site jax.jit it
# replaced — same statics, same donation, bit-identical programs; the
# plane is where the batch axis each kernel shards over is DECLARED
# (series-hash for the window/downsample family) so mesh legs
# (parallel/sharded.py, compress/) stay partition-aware without
# per-site plumbing.
_RATE_STATICS = ("rate", "counter", "drop_resets")

# Plain Python floats: creating jnp scalars at import time would
# instantiate a device array and eagerly initialize the backend.
_NEG_INF = float("-inf")
_POS_INF = float("inf")


# ---------------------------------------------------------------------------
# Masked segment reductions
# ---------------------------------------------------------------------------

# Which per-segment statistics each aggregator's _finish needs. count is
# always computed (it doubles as the bucket-nonempty mask); the rest are
# gated off it so e.g. a sum query issues 1-2 [N] reductions, not 5. On
# TPU this matters enormously: XLA lowers a rank-1 f32 segment_sum to an
# HBM-speed sorted scatter (~0.1 ms for 10M points on v5e), while
# feature-stacked [N, K] scatters and segment_min/max run 100-1000x
# slower (measured, scripts/tpu_probe.py) — so the kernel issues one
# flat reduction per needed statistic and nothing else.
_AGG_NEEDS = {"sum": frozenset({"sum"}), "min": frozenset({"min"}),
              "max": frozenset({"max"}), "avg": frozenset({"sum"}),
              "dev": frozenset({"sum", "m2"}),
              "count": frozenset()}


def _needs(agg: str) -> frozenset:
    return _AGG_NEEDS[NOLERP_AGGS.get(agg, agg)]


def _segment_moments(vals: jnp.ndarray, seg: jnp.ndarray, valid: jnp.ndarray,
                     num_segments: int, extra: jnp.ndarray | None = None,
                     need: frozenset = frozenset({"sum", "m2", "min",
                                                  "max"})):
    """Per-segment count, sum, centered-M2, min, max over masked points.

    The second moment is centered (two-pass: mean first, then
    sum((x-mean)^2)) — the naive E[x^2]-E[x]^2 form cancels catastrophically
    in float32 when stddev << |mean|.

    ``need`` gates which statistics are materialized (see _AGG_NEEDS);
    un-needed ones return None. ``extra`` is an optional [N] feature
    summed the same way and returned as a sixth output — downsample_group
    passes bucket-relative timestamps through it.
    """
    count = jax.ops.segment_sum(valid.astype(jnp.float32), seg,
                                num_segments)
    total = m2 = mn = mx = extra_sum = None
    if "sum" in need or "m2" in need:
        total = jax.ops.segment_sum(jnp.where(valid, vals, 0.0), seg,
                                    num_segments)
    if "m2" in need:
        mean = total / jnp.maximum(count, 1.0)
        centered = jnp.where(valid, vals - mean[seg], 0.0)
        m2 = jax.ops.segment_sum(centered * centered, seg, num_segments)
    if "min" in need:
        mn = jax.ops.segment_min(jnp.where(valid, vals, _POS_INF), seg,
                                 num_segments)
    if "max" in need:
        mx = jax.ops.segment_max(jnp.where(valid, vals, _NEG_INF), seg,
                                 num_segments)
    if extra is not None:
        extra_sum = jax.ops.segment_sum(jnp.where(valid, extra, 0.0), seg,
                                        num_segments)
        return count, total, m2, mn, mx, extra_sum
    return count, total, m2, mn, mx


def _finish(agg: str, count, total, m2, mn, mx):
    """Combine segment moments (m2 = centered sum of squares) into the agg."""
    agg = NOLERP_AGGS.get(agg, agg)  # same reduction, different feed
    safe = jnp.maximum(count, 1.0)
    if agg == "sum":
        return total
    if agg == "min":
        return mn
    if agg == "max":
        return mx
    if agg == "avg":
        return total / safe
    if agg == "dev":
        return jnp.sqrt(jnp.maximum(m2, 0.0) / safe)
    if agg == "count":
        return count
    raise ValueError(f"unknown aggregator: {agg}")


_I32_BIG = int(np.int32(2**31 - 1))


def gap_fill(series_values: jnp.ndarray, series_mask: jnp.ndarray,
             num_buckets: int, *, glob_offset=0, left_idx=None,
             left_val=None, right_idx=None, right_val=None):
    """Lerp-fill each series' empty buckets between its nonempty ones.

    A series with an empty bucket between two nonempty ones contributes a
    linear interpolation (the reference lerps missing samples at group
    time, SpanGroup.java:702-784); outside its first/last nonempty bucket
    it contributes nothing. Fill via cumulative min/max index scans — no
    sort, no gather loops. Bucket starts are affine in the bucket index,
    so lerping in index space equals lerping in time space.

    The optional carry args serve the time-sharded path
    (parallel/timeshard.py), where this tile's buckets are a window
    ``[glob_offset, glob_offset + num_buckets)`` of a larger grid:
    ``left_idx/left_val`` [S] give the nearest nonempty *global* bucket
    before the window (-1 = none), ``right_idx/right_val`` the nearest
    after (sentinel 2^31-1 = none); rows with no local prev/next fall
    back to them so cross-tile lerp matches the unsharded fill exactly.

    Returns (filled [S, B], in_range [S, B]); filled is 0 outside range.
    """
    b_idx = jnp.arange(num_buckets, dtype=jnp.int32)
    glob = glob_offset + b_idx
    prev_loc = jax.lax.cummax(
        jnp.where(series_mask, b_idx[None, :], -1), axis=1)
    next_loc = jax.lax.cummin(
        jnp.where(series_mask, b_idx[None, :], num_buckets), axis=1,
        reverse=True)
    has_prev_loc = prev_loc >= 0
    has_next_loc = next_loc < num_buckets
    p = jnp.clip(prev_loc, 0, num_buckets - 1)
    q = jnp.clip(next_loc, 0, num_buckets - 1)
    y0 = jnp.take_along_axis(series_values, p, axis=1)
    y1 = jnp.take_along_axis(series_values, q, axis=1)

    if left_idx is None:
        prev_idx = jnp.where(has_prev_loc, glob_offset + prev_loc, -1)
        prev_val = y0
    else:
        prev_idx = jnp.where(has_prev_loc, glob_offset + prev_loc,
                             left_idx[:, None])
        prev_val = jnp.where(has_prev_loc, y0, left_val[:, None])
    if right_idx is None:
        next_idx = jnp.where(has_next_loc, glob_offset + next_loc, _I32_BIG)
        next_val = y1
    else:
        next_idx = jnp.where(has_next_loc, glob_offset + next_loc,
                             right_idx[:, None])
        next_val = jnp.where(has_next_loc, y1, right_val[:, None])

    in_range = (prev_idx >= 0) & (next_idx < _I32_BIG)
    dx = jnp.maximum((next_idx - prev_idx).astype(jnp.float32), 1.0)
    frac = (glob[None, :] - prev_idx).astype(jnp.float32) / dx
    filled = jnp.where(series_mask, series_values,
                       prev_val + frac * (next_val - prev_val))
    return jnp.where(in_range, filled, 0.0), in_range


def bucket_rate(series_values: jnp.ndarray, series_mask: jnp.ndarray,
                interval: int, counter_max=0.0, reset_value=0.0, *,
                counter: bool = False, drop_resets: bool = False,
                glob_offset=0, left_idx=None, left_val=None):
    """Per-series rate of change on the shared bucket grid.

    Each nonempty bucket's rate is its backward difference against the
    series' previous nonempty bucket (bucket-start timestamps, so
    dt = (b - prev_b) * interval) — the downsample-then-rate composition
    the reference builds from iterators (SpanGroup.java:736-784 computes
    rates from consecutive downsampled points). The first nonempty bucket
    of a series yields no rate, matching oracle.rate.

    The optional carry args serve the time-sharded path: ``left_idx`` [S]
    is the series' nearest nonempty *global* bucket before this tile's
    window (-1 = none) and ``left_val`` its value; a tile-first bucket
    differences against that instead of having no predecessor.
    ``glob_offset`` maps local bucket indices to global ones.

    Returns (rates [S, B] float32, ok [S, B] bool).
    """
    S, B = series_values.shape
    b_idx = jnp.arange(B, dtype=jnp.int32)
    masked_idx = jnp.where(series_mask, b_idx[None, :], -1)
    prev_incl = jax.lax.cummax(masked_idx, axis=1)
    prev_excl = jnp.concatenate(
        [jnp.full((S, 1), -1, jnp.int32), prev_incl[:, :-1]], axis=1)
    has_local = prev_excl >= 0
    p = jnp.clip(prev_excl, 0, B - 1)
    prev_val = jnp.take_along_axis(series_values, p, axis=1)
    prev_glob = glob_offset + prev_excl
    if left_idx is not None:
        use_carry = ~has_local & (left_idx[:, None] >= 0)
        prev_glob = jnp.where(use_carry, left_idx[:, None], prev_glob)
        prev_val = jnp.where(use_carry, left_val[:, None], prev_val)
        has_prev = has_local | use_carry
    else:
        has_prev = has_local
    glob = glob_offset + b_idx[None, :]
    dt = jnp.maximum((glob - prev_glob).astype(jnp.float32) * interval,
                     1e-9)
    dv = series_values - prev_val
    if counter:
        dv = jnp.where(dv < 0, dv + counter_max, dv)
    r = dv / dt
    if drop_resets:
        r = jnp.where(jnp.abs(r) > reset_value, 0.0, r)
    ok = series_mask & has_prev
    return jnp.where(ok, r, 0.0), ok


def step_fill(series_values: jnp.ndarray, series_mask: jnp.ndarray,
              num_buckets: int, *, left_idx=None, left_val=None,
              right_idx=None):
    """Last-value-hold fill of empty buckets (the rate counterpart of
    gap_fill: rates step between points, SpanGroup.java:736-784 /
    oracle.group_aggregate(interp='step')).

    A series contributes its previous bucket's value in empty buckets
    between its first and last nonempty ones, nothing outside. The carry
    args serve the time-sharded path; unlike gap_fill, only presence and
    the *left* value matter to a step hold (no distances, no right
    value), so the global-index plumbing stops at the flags: ``left_idx``
    [S] >= 0 means the series has a nonempty bucket on an earlier tile
    with value ``left_val``; ``right_idx`` [S] < 2^31-1 means one exists
    on a later tile. Returns (filled [S, B], in_range [S, B]).
    """
    b_idx = jnp.arange(num_buckets, dtype=jnp.int32)
    prev_loc = jax.lax.cummax(
        jnp.where(series_mask, b_idx[None, :], -1), axis=1)
    next_loc = jax.lax.cummin(
        jnp.where(series_mask, b_idx[None, :], num_buckets), axis=1,
        reverse=True)
    has_prev_loc = prev_loc >= 0
    has_next_loc = next_loc < num_buckets
    p = jnp.clip(prev_loc, 0, num_buckets - 1)
    y0 = jnp.take_along_axis(series_values, p, axis=1)
    if left_idx is None:
        prev_ok = has_prev_loc
        prev_val = y0
    else:
        prev_ok = has_prev_loc | (left_idx[:, None] >= 0)
        prev_val = jnp.where(has_prev_loc, y0, left_val[:, None])
    if right_idx is None:
        next_ok = has_next_loc
    else:
        next_ok = has_next_loc | (right_idx[:, None] < _I32_BIG)
    in_range = prev_ok & next_ok
    filled = jnp.where(series_mask, series_values, prev_val)
    return jnp.where(in_range, filled, 0.0), in_range


def group_moments(filled: jnp.ndarray, in_range: jnp.ndarray):
    """Masked per-bucket moments across series (axis 0): count, total,
    centered M2, mean, min, max."""
    n = in_range.astype(jnp.float32).sum(axis=0)
    total = jnp.where(in_range, filled, 0.0).sum(axis=0)
    mean = total / jnp.maximum(n, 1.0)
    centered = jnp.where(in_range, filled - mean[None, :], 0.0)
    m2 = (centered * centered).sum(axis=0)
    mn = jnp.where(in_range, filled, _POS_INF).min(axis=0)
    mx = jnp.where(in_range, filled, _NEG_INF).max(axis=0)
    return n, total, m2, mean, mn, mx


# ---------------------------------------------------------------------------
# Device-window helpers (storage/devstore.py query path)
# ---------------------------------------------------------------------------

def _window_series_stage(rel_ts, vals, sid, valid_in, lo, hi, shift, *,
                         num_series, num_buckets, interval, agg_down,
                         rate=False, counter_max=0.0, reset_value=0.0,
                         counter=False, drop_resets=False):
    """The heavy, FILTER-INDEPENDENT half of any resident-window query:
    range masking + per-series downsample [+ rate] over the N resident
    points. No include mask, no gap fill, no grouping — so ONE cached
    device-resident stage serves every panel over the same (metric,
    range, interval, downsample): different tag filters, group-bys,
    group aggregators, moments AND quantiles all reuse it, paying only
    the [S, B]-sized apply per query. On a remote-device transport this
    is the difference between ~N-scatter cost per panel and ~one
    dispatch per panel (the devwindow serving pattern; the quantile
    path proved it first, this generalizes it to moments).

    Returns (series_values [S, B] post-rate, series_mask [S, B]
    post-rate, filled [S, B], in_range [S, B], presence [S] pre-rate).
    ``filled``/``in_range`` carry the lerp (or, under rate, step) fill
    of the full grid: filling is ROW-LOCAL, so a series' filled row is
    identical whether or not other series are included — which makes
    the fill cacheable here rather than re-run per panel."""
    ok = valid_in & (rel_ts >= lo) & (rel_ts <= hi)
    out = downsample_group(
        rel_ts - shift, vals, sid, ok,
        num_series=num_series, num_buckets=num_buckets,
        interval=interval, agg_down=agg_down,
        agg_group="count", rate=rate, counter_max=counter_max,
        reset_value=reset_value, counter=counter,
        drop_resets=drop_resets)
    return _stage_tail(out["series_values"], out["series_mask"],
                       out["presence"], num_buckets=num_buckets,
                       rate=rate)


def _group_stage(filled, in_range, series_mask, gmap, *, num_groups,
                 agg_group):
    """Cross-series aggregation of a (filled, masked) [S, B] grid into
    [G, B] — row-wise segment reductions (S vector updates, never a
    flat S*B scatter)."""
    if num_groups == 1:
        g_count, g_total, g_m2, _, g_mn, g_mx = group_moments(
            filled, in_range)
        gv = _finish(agg_group, g_count, g_total, g_m2, g_mn, g_mx)[None]
        gm = series_mask.any(axis=0)[None]
        return gv, gm
    need = _needs(agg_group)
    g_count = jax.ops.segment_sum(
        in_range.astype(jnp.float32), gmap, num_groups)
    v = jnp.where(in_range, filled, 0.0)
    g_total = g_m2 = g_mn = g_mx = None
    if "sum" in need or "m2" in need:
        g_total = jax.ops.segment_sum(v, gmap, num_groups)
    if "m2" in need:
        g_mean = g_total / jnp.maximum(g_count, 1.0)
        centered = jnp.where(in_range, filled - g_mean[gmap], 0.0)
        g_m2 = jax.ops.segment_sum(centered * centered, gmap,
                                   num_groups)
    if "min" in need:
        g_mn = jax.ops.segment_min(
            jnp.where(in_range, filled, _POS_INF), gmap, num_groups)
    if "max" in need:
        g_mx = jax.ops.segment_max(
            jnp.where(in_range, filled, _NEG_INF), gmap, num_groups)
    gv = _finish(agg_group, g_count, g_total, g_m2, g_mn, g_mx)
    gm = jax.ops.segment_sum(
        series_mask.astype(jnp.int32), gmap, num_groups) > 0
    return gv, gm


def _shrink_wrap(gv, gm, g_out, b_out, wire_bf16=False):
    """Clip apply outputs to the (64-quantized) live group/bucket counts
    and bit-pack the mask before they cross the transport: the axon
    tunnel moves device->host data at ~30 MB/s with a ~100 ms floor
    (measured), so fetching the PADDED [G, B] grids dominated wide
    group-by queries. g_out/b_out are static (bounded recompiles: 64
    quantization).

    ``wire_bf16`` additionally halves the [G, B] value payload by
    casting to bfloat16 ON DEVICE (opt-in via Config.wire_bf16: it
    trades the window path's byte-exactness vs the scan path for wire
    bytes — ~2-3 significant digits, plenty for dashboard pixels,
    wrong for billing). bfloat16, not float16: the float32 exponent
    range means big group sums can't overflow to inf (f16 tops out at
    65504)."""
    gv = gv[..., :g_out, :b_out]
    if wire_bf16:
        gv = gv.astype(jnp.bfloat16)
    gm = jnp.packbits(gm[:g_out, :b_out], axis=1)
    return gv, gm


def _moment_apply(series_values, series_mask, filled, in_range, include,
                  gmap, *, num_groups, agg_group,
                  g_out=None, b_out=None, wire_bf16=False):
    """Cheap per-query half of a resident-window MOMENT query: include
    masking (row-wise — identical to having filtered the points
    upstream, since fill is row-local) + group aggregation over the
    cached [S, B] stage grids."""
    sm = series_mask & include[:, None]
    if agg_group in NOLERP_AGGS:
        f, ir = series_values, sm
    else:
        f, ir = filled, in_range & include[:, None]
    gv, gm = _group_stage(f, ir, sm, gmap,
                          num_groups=num_groups, agg_group=agg_group)
    if g_out is None:
        return gv, gm
    return _shrink_wrap(gv, gm, g_out, b_out, wire_bf16)


def _quantile_apply(series_mask, filled, in_range,
                    include, gmap, q, *, num_groups,
                    g_out=None, b_out=None, wire_bf16=False):
    """Cheap per-quantile half: include masking + [G, B] masked
    quantiles from the cached stage's filled grid (quantiles always use
    the lerp/step fill family — reference SpanGroup percentile
    semantics)."""
    sm = series_mask & include[:, None]
    ir = in_range & include[:, None]
    if num_groups == 1:
        gv = masked_quantile_axis0(filled, ir, q)[:1]
        gm = sm.any(axis=0)[None]
    else:
        # host=* percentile dashboards: all groups' quantiles in the
        # same program (excluded/padded series carry no valid buckets,
        # so wherever gmap sends them they add nothing).
        gv = masked_quantile_groups(filled, ir, gmap, q,
                                    num_groups=num_groups)[0]
        gm = jax.ops.segment_sum(
            sm.astype(jnp.int32), gmap, num_groups) > 0
    if g_out is None:
        return gv, gm
    return _shrink_wrap(gv, gm, g_out, b_out, wire_bf16)


def _stage_tail(series_values, series_mask, presence, *, num_buckets,
                rate):
    """Shared tail of both window stages (concat + chunked): fill per
    the rate family and return the stage contract. One definition so
    the fill-choice semantics can't diverge between the two."""
    fill = step_fill if rate else gap_fill
    filled, in_range = fill(series_values, series_mask, num_buckets)
    return series_values, series_mask, filled, in_range, presence


@jit_plan(ExecPlan(
    name="window.chunk_fold", axis="series",
    static_argnames=("num_series", "num_buckets", "interval", "need"),
    donate_argnums=(4, 5, 6, 7, 8)))
def _chunk_fold(rel_ts, vals, sid, valid, count, total, m2, mn, mx,
                lo, hi, shift, *, num_series, num_buckets, interval,
                need):
    """Fold ONE resident chunk into the per-(series, bucket)
    accumulators. Compiled once per chunk shape class (chunks are
    pow2-padded, so there are only a handful); accumulators are donated
    so the fold is in-place. The stage driver issues these
    back-to-back ASYNC — dispatch does not wait for the device, so K
    chunks cost ~K host-side submissions, not K round trips.

    ``m2`` accumulates the exact pairwise (Chan et al.) combination:
    the chunk's M2 is centered on the CHUNK-local segment means, then
    corrected by the mean shift against the running accumulator —
    numerically sound where a naive E[x^2]-E[x]^2 merge cancels
    catastrophically (same scheme as the sharded psum fan-in,
    parallel/sharded.py)."""
    nseg = num_series * num_buckets + 1
    ok = valid & (rel_ts >= lo) & (rel_ts <= hi)
    bucket = jnp.clip((rel_ts - shift) // interval, 0, num_buckets - 1)
    seg = jnp.where(ok, sid * num_buckets + bucket, nseg - 1)
    c_cnt = jax.ops.segment_sum(ok.astype(jnp.float32), seg, nseg)
    c_tot = None
    if "sum" in need or "m2" in need:
        c_tot = jax.ops.segment_sum(jnp.where(ok, vals, 0.0), seg,
                                    nseg)
    if "m2" in need:
        c_mean = c_tot / jnp.maximum(c_cnt, 1.0)
        centered = jnp.where(ok, vals - c_mean[seg], 0.0)
        c_m2 = jax.ops.segment_sum(centered * centered, seg, nseg)
        # Chan combine with the running (count, total, m2): the
        # mean-shift correction uses the PRE-update accumulator.
        a_cnt = count
        a_mean = total / jnp.maximum(a_cnt, 1.0)
        tot_n = a_cnt + c_cnt
        delta = c_mean - a_mean
        corr = jnp.where(tot_n > 0,
                         delta * delta * a_cnt * c_cnt
                         / jnp.maximum(tot_n, 1.0), 0.0)
        m2 = m2 + c_m2 + corr
    count = count + c_cnt
    if c_tot is not None:
        total = total + c_tot
    if "min" in need:
        mn = jnp.minimum(mn, jax.ops.segment_min(
            jnp.where(ok, vals, _POS_INF), seg, nseg))
    if "max" in need:
        mx = jnp.maximum(mx, jax.ops.segment_max(
            jnp.where(ok, vals, _NEG_INF), seg, nseg))
    return count, total, m2, mn, mx


@jit_plan(ExecPlan(
    name="window.chunk_stage_finish", axis="series",
    static_argnames=("num_series", "num_buckets", "interval", "agg_down")
    + _RATE_STATICS))
def _chunk_stage_finish(count, total, m2, mn, mx, *, num_series,
                        num_buckets, interval, agg_down, rate=False,
                        counter_max=0.0, reset_value=0.0, counter=False,
                        drop_resets=False):
    need = _needs(agg_down)
    per = _finish(agg_down, count,
                  total if ("sum" in need or "m2" in need) else None,
                  m2 if "m2" in need else None,
                  mn if "min" in need else None,
                  mx if "max" in need else None)
    shape = (num_series, num_buckets)
    series_values = per[:-1].reshape(shape)
    series_mask = count[:-1].reshape(shape) > 0
    presence = series_mask.any(axis=1)  # pre-rate, like downsample_group
    if rate:
        series_values, series_mask = bucket_rate(
            series_values, series_mask, interval, counter_max,
            reset_value, counter=counter, drop_resets=drop_resets)
    return _stage_tail(series_values, series_mask, presence,
                       num_buckets=num_buckets, rate=rate)


def window_series_stage_chunks(chunks, lo, hi, shift, *, num_series,
                               num_buckets, interval, agg_down,
                               rate=False, counter_max=0.0,
                               reset_value=0.0, counter=False,
                               drop_resets=False):
    """window_series_stage over the devwindow's RAW CHUNK LIST — no
    concatenated copy of the columns ever exists, so a queryable window
    can approach the chip's WHOLE HBM (the concat view costs a second
    full copy plus N-sized transients, capping it near half — the
    1B-points-resident north star, BASELINE.md).

    Structure: one per-chunk fold jit (compiled once per pow2 chunk
    shape class, NOT one giant unrolled program that would retrace on
    every chunk-count change) driven by a host loop; async dispatch
    pipelines the folds on device and only the finish stage joins.
    Accumulators are donated, so peak HBM is the resident chunks + one
    accumulator set + one chunk's transients.

    Every moment family merges exactly (dev via the chunk-locally-
    centered M2 + Chan mean-shift correction — see _chunk_fold).

    ``chunks``: iterable of (rel_ts, values, sid, valid) tuples.
    Returns the window_series_stage contract: (series_values,
    series_mask, filled, in_range, presence)."""
    need = _needs(agg_down)
    nseg = num_series * num_buckets + 1
    count = jnp.zeros(nseg, jnp.float32)
    # Unused statistics still flow through the fold signature (static
    # ``need`` gates their updates to no-ops) so one jit serves every
    # mergeable aggregator per shape class.
    total = jnp.zeros(nseg, jnp.float32)
    m2 = jnp.zeros(nseg, jnp.float32)
    mn = jnp.full(nseg, _POS_INF, jnp.float32)
    mx = jnp.full(nseg, _NEG_INF, jnp.float32)
    for rel_ts, vals, sid, valid in chunks:
        count, total, m2, mn, mx = _chunk_fold(
            rel_ts, vals, sid, valid, count, total, m2, mn, mx,
            lo, hi, shift, num_series=num_series,
            num_buckets=num_buckets, interval=interval, need=need)
    return _chunk_stage_finish(
        count, total, m2, mn, mx, num_series=num_series,
        num_buckets=num_buckets, interval=interval, agg_down=agg_down,
        rate=rate, counter_max=counter_max, reset_value=reset_value,
        counter=counter, drop_resets=drop_resets)


WINDOW_STAGE_PLAN = ExecPlan(
    name="window.stage", axis="series",
    static_argnames=("num_series", "num_buckets", "interval",
                     "agg_down") + _RATE_STATICS)
WINDOW_MOMENT_APPLY_PLAN = ExecPlan(
    name="window.moment_apply", axis="series",
    static_argnames=("num_groups", "agg_group", "g_out", "b_out",
                     "wire_bf16"))
WINDOW_QUANTILE_APPLY_PLAN = ExecPlan(
    name="window.quantile_apply", axis="series",
    static_argnames=("num_groups", "g_out", "b_out", "wire_bf16"))

window_series_stage = compile_with_plan(_window_series_stage,
                                        WINDOW_STAGE_PLAN)
window_moment_apply = compile_with_plan(_moment_apply,
                                        WINDOW_MOMENT_APPLY_PLAN)
window_quantile_apply = compile_with_plan(_quantile_apply,
                                          WINDOW_QUANTILE_APPLY_PLAN)


@jit_plan(ExecPlan(
    name="window.query", axis="series",
    static_argnames=("num_series", "num_groups", "num_buckets",
                     "interval", "agg_down", "agg_group")
    + _RATE_STATICS))
def window_query(rel_ts: jnp.ndarray, vals: jnp.ndarray, sid: jnp.ndarray,
                 valid_in: jnp.ndarray, include: jnp.ndarray,
                 gmap: jnp.ndarray, lo, hi, shift, *, num_series: int,
                 num_groups: int, num_buckets: int, interval: int,
                 agg_down: str, agg_group: str,
                 rate: bool = False, counter_max: float = 0.0,
                 reset_value: float = 0.0, counter: bool = False,
                 drop_resets: bool = False):
    """The whole resident-window MOMENT query in ONE jit — the
    single-shot composition of window_series_stage + window_moment_apply
    (one dispatch instead of two; results are identical, so the
    executor's cached-stage path and this path are interchangeable).

    Returns (group_values [G, B], group_mask [G, B], presence [S]).
    """
    sv, sm, filled, in_range, presence = _window_series_stage(
        rel_ts, vals, sid, valid_in, lo, hi, shift,
        num_series=num_series, num_buckets=num_buckets,
        interval=interval, agg_down=agg_down, rate=rate,
        counter_max=counter_max, reset_value=reset_value,
        counter=counter, drop_resets=drop_resets)
    gv, gm = _moment_apply(sv, sm, filled, in_range, include, gmap,
                           num_groups=num_groups, agg_group=agg_group)
    return gv, gm, presence


# ---------------------------------------------------------------------------
# Fused downsample + group-by (the hot query kernel)
# ---------------------------------------------------------------------------

def _series_stage(ts, vals, sid, valid, *, num_series, num_buckets,
                  interval, agg_down, with_ts: bool):
    """Shared per-(series, bucket) downsample stage: one fused segment
    reduction producing series_values/series_mask [S, B] (and, when
    ``with_ts``, per-bucket integer-mean member timestamps).

    Negative result, measured r03: a scatter-free formulation for
    (sid, ts)-sorted columns — int32/fixed-point-int64 prefix sums +
    searchsorted of the [S*B] grid — LOST to the XLA scatter on both
    TPU (1248 vs 598 ms at N=20M) and CPU (179 vs 56 ms): the grid-
    side searchsorted (820 ms default 'scan', 305 ms 'sort' method on
    TPU) costs more than the scatter it replaces. The scatter path
    stays; don't re-derive without beating those numbers."""
    bucket = jnp.clip(ts // interval, 0, num_buckets - 1)
    seg = jnp.where(valid, sid * num_buckets + bucket,
                    num_series * num_buckets)
    nseg = num_series * num_buckets + 1  # +1 trash segment for padding
    need = _needs(agg_down)
    if with_ts:
        # Mean member timestamp rides the same reduction pass, relative
        # to bucket start for f32 exactness.
        rel = (ts - bucket * interval).astype(jnp.float32)
        count, total, sumsq, mn, mx, rel_sum = _segment_moments(
            vals, seg, valid, nseg, extra=rel, need=need)
    else:
        count, total, sumsq, mn, mx = _segment_moments(
            vals, seg, valid, nseg, need=need)
    per = _finish(agg_down, count, total, sumsq, mn, mx)
    shape = (num_series, num_buckets)
    series_values = per[:-1].reshape(shape)
    series_mask = count[:-1].reshape(shape) > 0
    if not with_ts:
        return series_values, series_mask, None
    mean_rel = jnp.floor(rel_sum / jnp.maximum(count, 1.0))
    bucket_starts = (jnp.arange(num_buckets, dtype=jnp.int32) * interval)
    series_ts = bucket_starts[None, :] + mean_rel[:-1].reshape(shape) \
        .astype(jnp.int32)
    return series_values, series_mask, series_ts

@jit_plan(ExecPlan(
    name="downsample.group", axis="series",
    static_argnames=("num_series", "num_buckets", "interval", "agg_down",
                     "agg_group") + _RATE_STATICS))
def downsample_group(ts: jnp.ndarray, vals: jnp.ndarray, sid: jnp.ndarray,
                     valid: jnp.ndarray, *, num_series: int,
                     num_buckets: int, interval: int, agg_down: str,
                     agg_group: str, rate: bool = False,
                     counter_max: float = 0.0, reset_value: float = 0.0,
                     counter: bool = False, drop_resets: bool = False):
    """Downsample every series into aligned buckets, then aggregate across
    series — one fused computation.

    Args:
      ts:    [N] int32 offsets from the query start (bucket-aligned base).
      vals:  [N] float32 point values.
      sid:   [N] int32 series index in [0, num_series).
      valid: [N] bool padding mask.
      interval: bucket width (seconds); num_buckets: static bucket count
        covering the query range.

    Returns dict with:
      series_values [S, B] per-series downsampled buckets,
      series_ts     [S, B] int32 mean member-timestamp offset per bucket,
      series_mask   [S, B] bool bucket-nonempty mask,
      group_values  [B] cross-series aggregate (over nonempty buckets),
      group_mask    [B] bool.

    Semantics parity: aligned buckets + integer-mean member timestamps =
    oracle.downsample(mode='aligned', bucket_ts='avg'); cross-series
    aggregation on the shared bucket grid = the lerp-free fast path
    (identical grids need no interpolation).

    ``rate=True`` inserts the rate stage between downsample and group
    (reference pipeline order: SGIterator computes rates from consecutive
    downsampled points, SpanGroup.java:736-784): series_values/series_mask
    become the per-bucket rates and their validity (each series' first
    nonempty bucket yields none), and the group stage step-fills instead
    of lerping — all still one fused computation.
    """
    series_values, series_mask, series_ts = _series_stage(
        ts, vals, sid, valid, num_series=num_series,
        num_buckets=num_buckets, interval=interval, agg_down=agg_down,
        with_ts=True)
    # Pre-rate: "series has any valid point", free from the bucket grid
    # — a separate segment reduction over the N points (series_presence)
    # would cost a second N-sized scatter pass.
    presence = series_mask.any(axis=1)
    if rate:
        series_values, series_mask = bucket_rate(
            series_values, series_mask, interval, counter_max,
            reset_value, counter=counter, drop_resets=drop_resets)

    # Group stage: aggregate across series on the shared bucket grid.
    # The no-lerp family skips gap filling: a series only contributes
    # where it actually has a bucket. Rates step-hold; plain values lerp.
    if agg_group in NOLERP_AGGS:
        filled, in_range = series_values, series_mask
    elif rate:
        filled, in_range = step_fill(series_values, series_mask,
                                     num_buckets)
    else:
        filled, in_range = gap_fill(series_values, series_mask,
                                    num_buckets)
    g_count, g_total, g_m2, _, g_mn, g_mx = group_moments(filled, in_range)
    group_values = _finish(agg_group, g_count, g_total, g_m2, g_mn, g_mx)

    return {
        "series_values": series_values,
        "series_ts": series_ts,
        "series_mask": series_mask,
        "presence": presence,
        "group_values": group_values,
        # Emit only buckets where some series has a real point (the union
        # grid); filled contributions never create grid points. With rate,
        # "real" means a real rate (first points emit none).
        "group_mask": series_mask.any(axis=0),
    }


@jit_plan(ExecPlan(
    name="downsample.multigroup", axis="series",
    static_argnames=("num_series", "num_groups", "num_buckets",
                     "interval", "agg_down", "agg_group")
    + _RATE_STATICS))
def downsample_multigroup(ts: jnp.ndarray, vals: jnp.ndarray,
                          sid: jnp.ndarray, valid: jnp.ndarray,
                          group_of_sid: jnp.ndarray, *, num_series: int,
                          num_groups: int, num_buckets: int, interval: int,
                          agg_down: str, agg_group: str,
                          rate: bool = False, counter_max: float = 0.0,
                          reset_value: float = 0.0, counter: bool = False,
                          drop_resets: bool = False):
    """Fused downsample + group-by for MANY group-by buckets in ONE call.

    The reference materializes one SpanGroup per distinct group-by tag
    combination and iterates them sequentially (TsdbQuery.java:294-363);
    a wide ``host=*`` query therefore costs G separate aggregations. Here
    all G groups ride two segment reductions: per-(series, bucket)
    downsample, then per-(group, bucket) moments with ``group_of_sid``
    [S] mapping each series to its group.

    Args as downsample_group, plus group_of_sid [S] int32 in
    [0, num_groups). Returns dict with group_values / group_mask shaped
    [G, B]. Semantics per group are identical to calling
    downsample_group on that group's series alone.
    """
    series_values, series_mask, _ = _series_stage(
        ts, vals, sid, valid, num_series=num_series,
        num_buckets=num_buckets, interval=interval, agg_down=agg_down,
        with_ts=False)
    presence = series_mask.any(axis=1)  # pre-rate, see downsample_group
    if rate:
        series_values, series_mask = bucket_rate(
            series_values, series_mask, interval, counter_max,
            reset_value, counter=counter, drop_resets=drop_resets)

    if agg_group in NOLERP_AGGS:
        filled, in_range = series_values, series_mask
    elif rate:
        filled, in_range = step_fill(series_values, series_mask,
                                     num_buckets)
    else:
        filled, in_range = gap_fill(series_values, series_mask,
                                    num_buckets)

    group_values, group_mask = _group_stage(
        filled, in_range, series_mask, group_of_sid,
        num_groups=num_groups, agg_group=agg_group)
    return {
        "group_values": group_values,
        "group_mask": group_mask,
        "series_values": series_values,
        "series_mask": series_mask,
        "presence": presence,
    }


def _order_key(vals: jnp.ndarray) -> jnp.ndarray:
    """Monotone f32 -> uint32 mapping (IEEE total order): x < y iff
    key(x) < key(y). Negative floats flip all bits, non-negative set the
    sign bit — the classic radix-sort float trick."""
    b = jax.lax.bitcast_convert_type(vals, jnp.uint32)
    return jnp.where((b >> 31).astype(bool), ~b,
                     b | jnp.uint32(0x80000000))


def _key_to_float(key: jnp.ndarray) -> jnp.ndarray:
    """Inverse of _order_key."""
    neg = (key >> 31) == 0
    b = jnp.where(neg, ~key, key & jnp.uint32(0x7FFFFFFF))
    return jax.lax.bitcast_convert_type(b, jnp.float32)


@jit_plan(ExecPlan(name="quantile.axis0", axis="series"))
def masked_quantile_axis0(vals: jnp.ndarray, mask: jnp.ndarray,
                          q: jnp.ndarray):
    """Per-column quantiles across series (axis 0) with a validity mask.

    Matches numpy's default linear interpolation: position (n-1)*q between
    the sorted valid values of each column. Columns with no valid entries
    return 0. ``q`` is a [K] array; returns [K, B].

    Implementation is a vectorized MSB-first radix SELECT, not a sort:
    32 masked-count passes over [S, B] find each column's rank-k key
    exactly. XLA's variable sort on a 16k-row axis costs ~1.1 s on one
    CPU core and is no better on TPU (sorts don't map to the VPU);
    the counting passes are pure masked reductions and run ~10x faster
    on CPU, and at memory speed on TPU (measured: 16384x256 select
    115 ms vs 1100 ms sort, CPU). Exactness: the selected key is a
    bit-exact rank statistic, so results match the sort-based form
    bit for bit.
    """
    keys = jnp.where(mask, _order_key(vals), jnp.uint32(0xFFFFFFFF))
    n = mask.sum(axis=0)  # [B]

    def kth(k):
        """Key of rank ``k`` [B] (0-indexed among valid entries)."""
        def body(i, carry):
            prefix, kk = carry
            bit = 31 - i
            # (x >> bit) >> 1 == x >> (bit+1) without a 32-bit shift.
            m_hi = ((keys >> bit) >> 1) == ((prefix >> bit) >> 1)[None, :]
            bit0 = ((keys >> bit) & 1) == 0
            c0 = (mask & m_hi & bit0).sum(axis=0)
            take1 = kk >= c0
            return (jnp.where(take1, prefix | (jnp.uint32(1) << bit),
                              prefix),
                    jnp.where(take1, kk - c0, kk))
        prefix, _ = jax.lax.fori_loop(
            0, 32, body, (jnp.zeros_like(k, jnp.uint32), k))
        return prefix

    def one(qi):
        pos = jnp.maximum(n - 1, 0).astype(jnp.float32) * qi
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.ceil(pos).astype(jnp.int32)
        key_lo = kth(lo)
        vlo = _key_to_float(key_lo)
        # Rank hi's value: with duplicates spanning rank hi it is still
        # key_lo (count-of-<=key_lo exceeds hi); otherwise the smallest
        # valid key strictly above key_lo.
        cle = (mask & (keys <= key_lo[None, :])).sum(axis=0)
        above = jnp.min(
            jnp.where(mask & (keys > key_lo[None, :]), keys,
                      jnp.uint32(0xFFFFFFFF)), axis=0)
        vhi = jnp.where(hi < cle, vlo, _key_to_float(above))
        out = vlo + (pos - lo) * (vhi - vlo)
        return jnp.where(n > 0, out, 0.0)

    return jax.vmap(one)(jnp.atleast_1d(jnp.asarray(q, jnp.float32)))


@jit_plan(ExecPlan(name="quantile.groups", axis="series",
                   static_argnames=("num_groups",)))
def masked_quantile_groups(vals: jnp.ndarray, mask: jnp.ndarray,
                           gmap: jnp.ndarray, q: jnp.ndarray, *,
                           num_groups: int):
    """Per-(group, bucket) quantiles across member series, all groups in
    one call: the percentile form of the multigroup group stage.
    ``gmap`` [S] maps each series row to its group; semantics per group
    match masked_quantile_axis0 on that group's rows alone.

    ONE segmented 2-key sort does all the work: each column sorts by
    (group, value-order-key), which lays every (group, bucket)'s valid
    members out as a contiguous ascending run at a COLUMN-INDEPENDENT
    row offset (group sizes come from gmap alone), so rank selection is
    two take_along_axis gathers + a lerp. This replaced a 32-pass
    radix-select whose per-bit [S, B] segment reductions dominated
    grouped-percentile latency ~10x on TPU, and replaces the
    sequential per-group kernel loop the reference's SpanGroup
    materialization forces (src/core/TsdbQuery.java:294-363).
    Returns [K, G, B].
    """
    S, B = vals.shape
    keys = jnp.where(mask, _order_key(vals), jnp.uint32(0xFFFFFFFF))
    gcol = jnp.broadcast_to(gmap[:, None], (S, B)).astype(jnp.int32)
    # Lexicographic segmented sort along the series axis: primary key
    # group, secondary key value order; invalid entries sink to each
    # group's tail (key 0xFFFFFFFF).
    _, skeys = jax.lax.sort((gcol, keys), dimension=0, num_keys=2)
    svals = _key_to_float(skeys)
    # Column-independent group layout: group g's rows start at the
    # exclusive prefix of group sizes.
    sizes = jax.ops.segment_sum(jnp.ones_like(gmap, jnp.int32), gmap,
                                num_groups)
    starts = jnp.cumsum(sizes) - sizes                       # [G]
    n = jax.ops.segment_sum(mask.astype(jnp.int32), gmap,
                            num_groups)                      # [G, B]

    def one(qi):
        pos = jnp.maximum(n - 1, 0).astype(jnp.float32) * qi
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.ceil(pos).astype(jnp.int32)
        idx_lo = jnp.clip(starts[:, None] + lo, 0, S - 1)    # [G, B]
        idx_hi = jnp.clip(starts[:, None] + hi, 0, S - 1)
        vlo = jnp.take_along_axis(svals, idx_lo, axis=0)
        vhi = jnp.take_along_axis(svals, idx_hi, axis=0)
        out = vlo + (pos - lo) * (vhi - vlo)
        return jnp.where(n > 0, out, 0.0)

    return jax.vmap(one)(jnp.atleast_1d(jnp.asarray(q, jnp.float32)))


@jit_plan(ExecPlan(
    name="downsample.multigroup_quantile", axis="series",
    static_argnames=("num_series", "num_groups", "num_buckets",
                     "interval", "agg_down") + _RATE_STATICS))
def downsample_multigroup_quantile(
        ts: jnp.ndarray, vals: jnp.ndarray, sid: jnp.ndarray,
        valid: jnp.ndarray, group_of_sid: jnp.ndarray, q: jnp.ndarray, *,
        num_series: int, num_groups: int, num_buckets: int, interval: int,
        agg_down: str, rate: bool = False, counter_max: float = 0.0,
        reset_value: float = 0.0, counter: bool = False,
        drop_resets: bool = False):
    """Fused downsample [+ rate] + per-group PERCENTILE aggregation for
    many group-by buckets in one call — the percentile sibling of
    downsample_multigroup (which is moment-only), closing the host=*
    p99 dashboard's per-group kernel loop.

    Per-group semantics are identical to downsample_group + the
    single-group quantile path on that group's series alone: series
    stage, optional bucket rates, gap/step fill between each series'
    real buckets, then the quantile across member series' contributions.
    Returns dict with group_values [G, B] (quantile ``q[0]``),
    group_mask [G, B], series_values, series_mask.
    """
    series_values, series_mask, _ = _series_stage(
        ts, vals, sid, valid, num_series=num_series,
        num_buckets=num_buckets, interval=interval, agg_down=agg_down,
        with_ts=False)
    if rate:
        series_values, series_mask = bucket_rate(
            series_values, series_mask, interval, counter_max,
            reset_value, counter=counter, drop_resets=drop_resets)
    fill = step_fill if rate else gap_fill
    filled, in_range = fill(series_values, series_mask, num_buckets)
    gv = masked_quantile_groups(filled, in_range, group_of_sid, q,
                                num_groups=num_groups)
    real = jax.ops.segment_sum(
        series_mask.astype(jnp.int32), group_of_sid, num_groups) > 0
    return {
        "group_values": gv[0],
        "group_mask": real,
        "series_values": series_values,
        "series_mask": series_mask,
    }


# ---------------------------------------------------------------------------
# Rate (flat layout)
# ---------------------------------------------------------------------------

def _flat_rate(ts, vals, sid, valid, counter_max, reset_value, *,
               counter: bool, drop_resets: bool, carry_ts=None,
               carry_val=None, use_carry=None):
    """Core of flat_rate; see its docstring. The optional carry args serve
    the time-sharded path (parallel/timeshard.py): where ``use_carry`` [N]
    is set, the point's predecessor is (carry_ts, carry_val) [N] — the
    series' last point on an earlier time tile — instead of the rolled
    neighbor, keeping counter/reset/epsilon semantics in this one place.
    """
    prev_ts = jnp.roll(ts, 1)
    prev_v = jnp.roll(vals, 1)
    prev_sid = jnp.roll(sid, 1)
    prev_valid = jnp.roll(valid, 1)
    ok = valid & prev_valid & (prev_sid == sid)
    ok = ok.at[0].set(False)
    if use_carry is not None:
        prev_ts = jnp.where(use_carry, carry_ts, prev_ts)
        prev_v = jnp.where(use_carry, carry_val, prev_v)
        ok = ok | use_carry
    dt = jnp.maximum((ts - prev_ts).astype(jnp.float32), 1e-9)
    dv = vals - prev_v
    if counter:
        dv = jnp.where(dv < 0, dv + counter_max, dv)
    r = dv / dt
    if drop_resets:
        r = jnp.where(jnp.abs(r) > reset_value, 0.0, r)
    return jnp.where(ok, r, 0.0), ok


@jit_plan(ExecPlan(name="rate.flat", axis="series",
                   static_argnames=("counter", "drop_resets")))
def flat_rate(ts: jnp.ndarray, vals: jnp.ndarray, sid: jnp.ndarray,
              valid: jnp.ndarray, counter_max: float = 0.0,
              reset_value: float = 0.0, *, counter: bool = False,
              drop_resets: bool = False):
    """Per-point rate of change within each series, in flat layout.

    Requires points sorted by (sid, ts) — the natural scan order. The first
    point of each series yields no rate (its valid bit clears), matching
    oracle.rate. ``counter`` adds rollover correction at counter_max;
    ``drop_resets``/reset_value zeroes implausible spikes.

    Returns (rates [N] float32 emitted at each point's own ts, valid [N]).
    """
    return _flat_rate(ts, vals, sid, valid, counter_max, reset_value,
                      counter=counter, drop_resets=drop_resets)


# ---------------------------------------------------------------------------
# Union-grid group aggregation with interpolation (reference-parity path)
# ---------------------------------------------------------------------------

@jit_plan(ExecPlan(name="grid.contributions", axis="series",
                   static_argnames=("interp",)))
def series_contributions(ts: jnp.ndarray, vals: jnp.ndarray,
                         counts: jnp.ndarray, grid: jnp.ndarray, *,
                         interp: str = "lerp"):
    """Each series' contribution at every grid point.

    ts/vals are [S, T] left-aligned padded rows; grid is [G] sorted. A
    series contributes its exact value at its own timestamps, an
    interpolation ('lerp' or 'step' last-value-hold) between them, and
    nothing outside [first, last]. Returns (contrib [S, G], cmask [S, G]).
    """
    T = ts.shape[1]
    idx = jnp.arange(T)
    big = jnp.int32(2**31 - 1)

    def one_series(row_ts, row_vals, n):
        # Padded slots read as +inf-alike; searchsorted-right gives the
        # count of points <= x.
        safe_ts = jnp.where(idx < n, row_ts, big)
        pos = jnp.searchsorted(safe_ts, grid, side="right")
        has_prev = pos > 0
        i0 = jnp.clip(pos - 1, 0, T - 1)
        i1 = jnp.clip(pos, 0, T - 1)
        x0 = safe_ts[i0]
        y0 = row_vals[i0]
        x1 = safe_ts[i1]
        y1 = row_vals[i1]
        exact = has_prev & (x0 == grid)
        in_range = has_prev & (pos < n) | exact  # first <= x <= last
        if interp == "lerp":
            dx = jnp.maximum((x1 - x0).astype(jnp.float32), 1e-9)
            t = (grid - x0).astype(jnp.float32) / dx
            interpd = y0 + t * (y1 - y0)
        elif interp == "step":
            interpd = y0
        elif interp == "none":
            # zimsum/mimmin/mimmax: only exact samples contribute.
            in_range = exact
            interpd = y0
        else:
            raise ValueError(f"unknown interp: {interp}")
        contrib = jnp.where(exact, y0, interpd)
        return jnp.where(in_range, contrib, 0.0), in_range

    return jax.vmap(one_series)(ts, vals, counts)

@jit_plan(ExecPlan(name="grid.union", axis="series"))
def union_grid(ts: jnp.ndarray, counts: jnp.ndarray):
    """Deduplicated sorted union of S padded timestamp rows.

    ts is [S, T] int32 left-aligned; counts [S]. Returns (grid [S*T]
    int32, gmask [S*T] bool) with real entries compacted to the front —
    the grid-construction half of group_interpolate, exposed separately
    so percentile queries build the grid once and feed it straight to
    series_contributions.
    """
    S, T = ts.shape
    idx = jnp.arange(T)
    row_valid = idx[None, :] < counts[:, None]
    big = jnp.int32(2**31 - 1)
    flat = jnp.where(row_valid, ts, big).reshape(-1)
    sorted_ts = jnp.sort(flat)
    first = jnp.concatenate([
        jnp.array([True]), sorted_ts[1:] != sorted_ts[:-1]])
    gmask = first & (sorted_ts != big)
    order = jnp.argsort(~gmask, stable=True)
    return sorted_ts[order], gmask[order]


@jit_plan(ExecPlan(name="grid.group_interpolate", axis="series",
                   static_argnames=("agg", "interp")))
def group_interpolate(ts: jnp.ndarray, vals: jnp.ndarray,
                      counts: jnp.ndarray, *, agg: str,
                      interp: str = "lerp"):
    """Aggregate S padded series on the union of their timestamps.

    Args:
      ts:     [S, T] int32, each row sorted, left-aligned (valid prefix).
      vals:   [S, T] float32.
      counts: [S] int32 valid-point counts per row.
      interp: 'lerp' or 'step' (last-value hold, for rates).

    Returns (grid [G=S*T] int32, out [G] float32, gmask [G] bool): the
    deduplicated union grid (padded; gmask marks real entries) and the
    aggregate at each grid point. A series contributes exact values at its
    own timestamps, interpolation elsewhere, nothing outside its
    [first, last] — reference SGIterator semantics (SpanGroup.java:370-796).
    """
    grid, gmask = union_grid(ts, counts)
    contrib, cmask = series_contributions(ts, vals, counts, grid,
                                          interp=interp)  # [S, G]

    cnt = cmask.astype(jnp.float32).sum(axis=0)
    v = jnp.where(cmask, contrib, 0.0)
    total = v.sum(axis=0)
    mean = total / jnp.maximum(cnt, 1.0)
    centered = jnp.where(cmask, contrib - mean[None, :], 0.0)
    m2 = (centered * centered).sum(axis=0)
    mn = jnp.where(cmask, contrib, _POS_INF).min(axis=0)
    mx = jnp.where(cmask, contrib, _NEG_INF).max(axis=0)
    out = _finish(agg, cnt, total, m2, mn, mx)
    gmask = gmask & (cnt > 0)
    return grid, out, gmask
