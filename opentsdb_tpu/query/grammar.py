"""The m= query expression grammar.

Parity: reference GraphHandler.parseQuery (:828-879) —
``agg:[interval-agg:][rate:]metric[{tag=value,...}]`` where the optional
middle parts may appear in either order; tag values support ``*`` (group
by all values) and ``v1|v2`` (group by listed values). Extension beyond
the 1.1 reference: ``rate{counter[,max[,reset]]}`` rollover options (the
2.x syntax), since the executor's rate kernel handles counter wraps.
"""

from __future__ import annotations

from typing import NamedTuple

from opentsdb_tpu.core import tags as tags_mod
from opentsdb_tpu.core.errors import BadRequestError
from opentsdb_tpu.query.aggregators import Aggregators
from opentsdb_tpu.utils.timeparse import parse_duration


def _validate_agg(name: str) -> None:
    try:
        Aggregators.get(name)
    except ValueError as e:
        raise BadRequestError(str(e)) from None


class ParsedMetric(NamedTuple):
    aggregator: str
    metric: str
    tags: dict[str, str]
    rate: bool
    downsample: tuple[int, str] | None  # (interval_seconds, agg)
    counter: bool = False
    counter_max: float = float(2 ** 64)
    reset_value: float | None = None


def _parse_rate_options(part: str, expr: str) -> tuple[bool, float,
                                                       float | None]:
    """``rate{counter[,max[,reset]]}`` -> (counter, counter_max, reset)."""
    body = part[len("rate{"):-1]
    fields = body.split(",") if body else []
    if not fields or fields[0] != "counter":
        raise BadRequestError(f"Invalid rate options: {part} in m={expr}")
    counter_max = float(2 ** 64)
    reset: float | None = None
    try:
        if len(fields) > 1 and fields[1]:
            counter_max = float(fields[1])
        if len(fields) > 2 and fields[2]:
            reset = float(fields[2])
        if len(fields) > 3:
            raise ValueError("too many rate options")
    except ValueError as e:
        raise BadRequestError(
            f"Invalid rate options: {part} in m={expr}: {e}") from None
    return True, counter_max, reset


def parse_m(expr: str) -> ParsedMetric:
    parts = expr.split(":")
    if len(parts) < 2:
        raise BadRequestError(
            f"smallest possible metric name is 7 chars, got: {expr}"
            if not expr else f"Invalid parameter m={expr}")
    agg = parts[0]
    _validate_agg(agg)

    rate = False
    counter = False
    counter_max = float(2 ** 64)
    reset_value: float | None = None
    downsample = None
    for part in parts[1:-1]:
        if part == "rate":
            rate = True
        elif part.startswith("rate{") and part.endswith("}"):
            rate = True
            counter, counter_max, reset_value = _parse_rate_options(
                part, expr)
        elif "-" in part:
            interval_s, _, ds_agg = part.partition("-")
            interval = parse_duration(interval_s)
            _validate_agg(ds_agg)
            # Moment downsamplers (the classic set) plus percentile
            # downsamplers (``1h-p95``): the latter serve exactly via
            # the float64 oracle, or approximately from rollup sketch
            # columns under the error contract (sketch/serving.py).
            kind = Aggregators.get(ds_agg).kind
            if kind not in ("moment", "percentile"):
                raise BadRequestError(
                    f"downsampler must be a moment or percentile "
                    f"aggregator: {ds_agg}")
            downsample = (interval, ds_agg)
        else:
            raise BadRequestError(f"Invalid query part: {part} in m={expr}")

    tag_map: dict[str, str] = {}
    try:
        metric = tags_mod.parse_with_metric(parts[-1], tag_map)
    except ValueError as e:
        raise BadRequestError(str(e)) from None
    return ParsedMetric(agg, metric, tag_map, rate, downsample,
                        counter, counter_max, reset_value)
